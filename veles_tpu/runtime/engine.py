"""Continuous-batching decode engine: slot-based serving with bucketed
prefill and a lifetime-compiled decode step.

``generate()`` is the wrong engine for serving: every distinct
``(B, P, n_steps, sampling)`` tuple compiles a fresh whole-sequence scan
and requests run serially — the opposite of the ROADMAP's "heavy traffic"
north star, and the reason the reference project shipped a standalone
inference runtime (libVeles) instead of serving from its training graph.
This module applies the fixed-shape AOT discipline TPUs impose (PAPERS:
"Automatic Full Compilation ... to Cloud TPUs") to decode, the way PR 1's
StepCache applied it to training:

* the engine owns a fixed-capacity **slot batch** ``(slots, l_max)`` of
  KV caches (plus recurrent carried state) for its whole lifetime;
* it compiles exactly **two kinds of programs**, AOT via the same
  :class:`~veles_tpu.runtime.step_cache.StepCache` whose counters tests
  assert on: a *bucketed prefill* (prompt lengths padded to power-of-two
  buckets, so at most ``log2(l_max)``-ish compiles ever) and a single
  *decode step* advancing every active slot one token with per-slot
  positions, per-slot sampling params, and per-slot eos / length
  retirement — total programs ≤ bucket count + 1, recompiles 0;
* a host-side scheduler thread owns the request queue: admission into
  free slots happens **mid-flight** (no drain barrier — running slots
  keep decoding across an admission), finished sequences retire and free
  their slot immediately, a small batching window coalesces concurrent
  arrivals, a bounded queue raises :class:`EngineOverloaded` (HTTP 429 +
  Retry-After in restful.py) instead of unbounded latency, and per-
  request deadlines fail requests loudly instead of wedging a slot.

Result parity: greedy tokens are identical to per-request ``generate()``
calls (the step math IS ``DecodePlan.step``, just masked/batched), and
sampled tokens are bitwise-identical for single-row requests with the
same key — per-slot keys fold in the slot's own position exactly like
the ``generate()`` scan (multi-row sampled requests draw per-row keys
``fold_in(key, row)`` instead of one batched categorical, documented in
docs/serving.md).

**Paged KV cache + shared-prefix reuse** (default; disable with
``root.common.serve.paged = False``): instead of one dense ``(slots,
l_max)`` KV row per slot — which caps concurrency by HBM at
``slots * l_max`` token-cells even though most requests use a fraction
of ``l_max`` — the engine owns a fixed pool of ``root.common.serve
.pages`` pages of ``page_size`` tokens each, and every slot maps its
logical positions onto pool pages through an int32 page table threaded
through the SAME two program kinds as traced data flow (gather/scatter
on the page axis — no third program, StepCache counters stay flat
across page allocation, reclamation, prefix hits, and copy-on-write).
The host scheduler refcounts pages and keeps a chained content-hash
index over full prompt pages: a request whose prompt prefix matches a
cached page chain maps those pages read-only (refcount++) and prefills
only its tail — N requests sharing a system prompt prefill it ONCE —
with copy-on-write semantics at the first divergent token (the
divergent page is recomputed into a private page; shared pages are
never written: decode/prefill writes of masked-off rows route to a
scratch pool row).  A request that cannot get pages is refused with
the same 429/Retry-After backpressure as a full queue
(docs/serving.md "Paged KV cache").
"""

from __future__ import annotations

import collections
import hashlib
import json
import math
import threading
import time
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import root
from ..logger import Logger
from ..units.base import Context
from .admission import AdmissionController
from .generate import DecodePlan
from .memory import memory_monitor, tree_bytes
from .metrics import ScopedCounter, next_trace_id, registry, span_ring
from .slo import slo_tracker
from .step_cache import StepCache, tree_signature


class EngineOverloaded(RuntimeError):
    """Request queue is full; retry after ``retry_after_s`` seconds.

    Interactive overload hints come from :meth:`_retry_after` (floored
    at 1s — real congestion drains slowly); the batch trough-closed 429
    passes a sub-second hint instead, because trough state flips at
    slot granularity and a 1s floor would make the job manager sleep
    through every short trough it exists to harvest."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.0, float(retry_after_s))


class EngineStopped(RuntimeError):
    """The engine was stopped before this request completed."""


class SchedulerCrashed(RuntimeError):
    """The scheduler loop died with an unhandled exception: every queued
    and mid-flight request was failed with this error, and new submits
    keep raising it.  Deliberately NOT an :class:`EngineStopped` — a
    crash is a 500 (page someone), not a 503 drain a load balancer
    routes around (runtime/restful.py)."""


class EngineDraining(EngineStopped):
    """The engine is draining: in-flight work retires, new work is
    refused (the REST layer's 503 on ``/ready`` and ``/generate``)."""


def prefix_page_hashes(prompt, page_size: int) -> list:
    """Chained sha256 digests of a prompt's FULL ``page_size``-token
    pages: page ``i``'s key covers tokens ``0 .. (i+1)*page_size`` —
    KV content depends on the whole prefix, not just the page's own
    tokens.  This is THE prefix-cache identity (docs/serving.md "Paged
    KV cache"): the engine keys its refcounted prefix index on it, and
    the fleet router (runtime/fleet.py) computes the SAME digests over
    a prompt head to route same-system-prompt sessions to the replica
    already holding those pages — one function so the two can never
    drift.  ``prompt`` is any 1-D int array-like; hashes are over the
    int32 byte view, matching what the engine stores."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    psz = int(page_size)
    hashes, h = [], b""
    for i in range(int(prompt.size) // psz):
        h = hashlib.sha256(
            h + prompt[i * psz:(i + 1) * psz].tobytes()).digest()
        hashes.append(h)
    return hashes


# KV-page transfer wire magic (docs/serving.md "Disaggregated
# prefill/decode"): version byte baked into the tag so a future format
# bump rejects loudly instead of misparsing
_KV_MAGIC = b"VTKV1\x00"


def signature_mismatch(expected, got, limit: int = 6) -> str:
    """Human-readable diff of two :func:`tree_signature` results — the
    clear-error half of the hot-swap contract: name WHICH leaves differ
    instead of dumping two thousand-entry tuples at the operator."""
    exp = {p: (s, d) for p, s, d in expected}
    new = {p: (s, d) for p, s, d in got}

    def fmt(sd):  # dtype may be blank (shape-only signatures)
        return f"{sd[0]}/{sd[1]}" if sd[1] else f"{sd[0]}"

    msgs = []
    for p in sorted(set(exp) - set(new)):
        msgs.append(f"{p}: missing (expected {fmt(exp[p])})")
    for p in sorted(set(new) - set(exp)):
        msgs.append(f"{p}: unexpected leaf {fmt(new[p])}")
    for p in sorted(set(exp) & set(new)):
        if exp[p] != new[p]:
            msgs.append(f"{p}: {fmt(new[p])} != expected {fmt(exp[p])}")
    extra = len(msgs) - limit
    if extra > 0:
        msgs = msgs[:limit] + [f"... and {extra} more"]
    return "; ".join(msgs) or "identical signatures"


def place_like(tree, template):
    """Device-place ``tree`` mirroring ``template``'s shardings (a bare
    device_put would commit a sharded model's replacement to one device
    — recompile or OOM on the next step), blocking until every leaf is
    fully transferred.  Placement errors propagate: committing the tree
    to the wrong devices as a "fallback" would be strictly worse than
    failing the swap with the old version still serving.  Host-array
    templates (no ``.sharding``) take default placement."""
    try:
        shardings = jax.tree.map(lambda l: l.sharding, template)
    except AttributeError:  # host/numpy template leaves
        shardings = None
    placed = jax.device_put(tree, shardings) if shardings is not None \
        else jax.device_put(tree)
    for leaf in jax.tree.leaves(placed):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return placed


def make_decode_fn(plan, ctx, S: int, *, page_size: Optional[int] = None,
                   paged_kernel: bool = False):
    """The engine's lifetime decode program as an un-compiled jitted
    function: advance all S slots one token with per-slot positions,
    sampling params, and eos/length retirement.  Lives at module level
    (not closed inside the engine) so the compiled-artifact exporter
    (export/compiled.py) serializes EXACTLY the program the live engine
    runs — a single source of step math, never two.

    With ``page_size`` set the signature gains the per-slot page table
    ``ptab`` (S, n_ptab) int32 and the KV caches are the flat page pool
    (page indirection is traced data flow through the same program
    kind); inactive slots' KV writes route to the scratch pool row so a
    retired slot can never corrupt pages reassigned to another slot.
    On BOTH layouts the ``active`` mask also drops inactive rows' dense
    KV scatters and freezes their recurrent carry (``write_ok`` /
    ``carry_ok`` in plan.step): an inactive slot may be mid-CHUNKED-
    prefill, its rows being filled slice by slice, and a stale-position
    write or a carry advance between slices would corrupt the very
    state the next slice continues from (docs/serving.md "Overload
    survival").  ``paged_kernel`` routes the paged attention read
    through the fused Pallas kernel (bounded-error;
    runtime/generate.py)."""

    def step_tail(caches, toks, logits, pos, active, temp, topk, topp,
                  eos, end, keys, rows):
        step_keys = jax.vmap(jax.random.fold_in)(
            jax.random.wrap_key_data(keys), pos)
        nxt = _sample_slots(logits, step_keys, temp, topk, topp)
        new_pos = jnp.where(active, pos + 1, pos)
        cur = toks[rows, new_pos]
        toks = toks.at[rows, new_pos].set(jnp.where(active, nxt, cur))
        finished = active & ((nxt == eos) | (new_pos >= end))
        return caches, toks, new_pos, active & ~finished, finished

    if page_size is None:
        def decode_step(params, caches, toks, pos, active, temp, topk,
                        topp, eos, end, keys):
            rows = jnp.arange(S)
            tok = toks[rows, pos]
            logits, caches = plan.step(params, caches, tok, pos, ctx,
                                       write_ok=active)
            return step_tail(caches, toks, logits, pos, active, temp,
                             topk, topp, eos, end, keys, rows)
    else:
        def decode_step(params, caches, toks, ptab, pos, active, temp,
                        topk, topp, eos, end, keys):
            rows = jnp.arange(S)
            tok = toks[rows, pos]
            logits, caches = plan.step(
                params, caches, tok, pos, ctx,
                pages=(ptab, page_size, active),
                paged_kernel=paged_kernel)
            return step_tail(caches, toks, logits, pos, active, temp,
                             topk, topp, eos, end, keys, rows)

    return jax.jit(decode_step, donate_argnums=(1, 2))


def make_verify_fn(plan, ctx, S: int, K: int, *,
                   page_size: Optional[int] = None,
                   paged_kernel: bool = False):
    """The engine's speculative **verify** program — the third (and
    last) program kind next to prefill and decode, compiled once per
    engine lifetime for a STATIC draft length ``K`` (module-level for
    the same exporter single-source reason as :func:`make_decode_fn`).

    ``draft`` (S, K) int32 carries each slot's host-drafted candidate
    tokens (``-1`` entries never match — the no-draft fallback row).
    One call scores all ``K + 1`` positions in one target forward (an
    in-program scan of the SAME ``DecodePlan.step`` the decode program
    runs — the idiom prefill already uses) and, per slot, accepts the
    longest draft prefix whose tokens equal what the engine's own
    sampler would have chosen at each position, then emits the first
    non-matching (bonus) token.  Because the sampler's choice at a
    position is a deterministic function of (logits, per-slot key
    folded at that GLOBAL position), the emitted sequence is
    **bitwise** the non-speculative engine's for greedy AND sampled
    slots — the drafter only guesses which tokens the sampler will
    pick, it never changes the pick (docs/serving.md "Speculative
    decoding").

    Per micro-step, a slot still extending feeds its last written token
    at its own position (KV write included — identical to a decode
    step), samples the next token, writes it, and keeps extending only
    while the draft matched and neither eos nor the length bound hit
    (mid-block eos retirement: later micro-steps leave the slot
    untouched).  Slots not extending re-feed their last token with KV
    writes routed to the scratch pool row (paged) or dropped (dense)
    and their recurrent carry frozen — state provably unchanged (the
    same ``write_ok`` discipline as :func:`make_decode_fn`; a cell
    iteration is not idempotent, and a mid-chunk slot's rows must not
    be touched between its slices).  Returns
    ``(caches, toks, pos, active, finished, accepted)`` where
    ``accepted`` (S,) int32 counts draft tokens whose emission matched
    the proposal (the accept-rate numerator)."""

    def verify_core(params, caches, toks, ptab, pos, active, temp,
                    topk, topp, eos, end, keys, draft):
        rows = jnp.arange(S)

        def body(carry, i):
            caches, toks, p, alive, fin, acc = carry
            tok = toks[rows, p]
            if page_size is None:
                logits, caches2 = plan.step(params, caches, tok, p, ctx,
                                            write_ok=alive)
            else:
                logits, caches2 = plan.step(
                    params, caches, tok, p, ctx,
                    pages=(ptab, page_size, alive),
                    paged_kernel=paged_kernel)
            step_keys = jax.vmap(jax.random.fold_in)(
                jax.random.wrap_key_data(keys), p)
            nxt = _sample_slots(logits, step_keys, temp, topk, topp)
            new_p = jnp.where(alive, p + 1, p)
            cur = toks[rows, new_p]
            toks = toks.at[rows, new_p].set(jnp.where(alive, nxt, cur))
            done = alive & ((nxt == eos) | (new_p >= end))
            # did the emitted token match this micro-step's proposal?
            # (the last micro-step has none: i == K is the bonus slot)
            d_i = draft[rows, jnp.minimum(i, K - 1)]
            match = alive & (i < K) & (nxt == d_i)
            acc = acc + match.astype(jnp.int32)
            fin = fin | done
            alive = alive & match & ~done
            return (caches2, toks, new_p, alive, fin, acc), None

        init = (caches, toks, pos, active, jnp.zeros(S, bool),
                jnp.zeros(S, jnp.int32))
        (caches, toks, pos, _, fin, acc), _ = jax.lax.scan(
            body, init, jnp.arange(K + 1))
        return caches, toks, pos, active & ~fin, fin, acc

    if page_size is None:
        def verify_step(params, caches, toks, pos, active, temp, topk,
                        topp, eos, end, keys, draft):
            return verify_core(params, caches, toks, None, pos, active,
                               temp, topk, topp, eos, end, keys, draft)
    else:
        def verify_step(params, caches, toks, ptab, pos, active, temp,
                        topk, topp, eos, end, keys, draft):
            return verify_core(params, caches, toks, ptab, pos, active,
                               temp, topk, topp, eos, end, keys, draft)

    return jax.jit(verify_step, donate_argnums=(1, 2))


def make_megastep_fn(plan, ctx, S: int, N: int, *,
                     page_size: Optional[int] = None,
                     paged_kernel: bool = False):
    """The engine's decode **megastep** — the fourth program kind: ``N``
    decode micro-steps fused into ONE compiled dispatch (static ``N =
    root.common.serve.megastep``; module-level for the same exporter
    single-source reason as :func:`make_decode_fn`).  The host loop, not
    the math, bounds tokens/s at production batch sizes; keeping the
    token loop inside XLA amortizes the dispatch + scheduler pass to
    once per ``N`` tokens (docs/serving.md "Megastep decode").

    The body is the verify scan (:func:`make_verify_fn`) minus draft
    matching: each micro-step feeds every live slot its last written
    token at its own position, samples with the slot's key folded at
    that GLOBAL position (so emitted tokens are **bitwise** what N
    separate decode steps emit, greedy and sampled alike), writes the
    token, and retires the slot in-program on eos or its length bound.
    A slot retired at micro-step ``i`` stops writing KV, advancing
    recurrent carry, and emitting tokens for steps ``i+1..N`` — the
    ``write_ok`` discipline of :func:`make_decode_fn`, with paged
    masked writes routed to the scratch pool row and dense ones
    dropped, so a retired slot's rows (possibly mid-chunked-prefill
    after reassignment) are provably untouched.

    Same calling convention as the decode program (paged inserts
    ``ptab``).  Returns ``(caches, toks, pos, active, finished,
    emitted)``: ``toks`` holds each slot's emitted-token buffer at
    ``[old_pos+1 .. old_pos+emitted]`` and ``emitted`` (S,) int32
    counts tokens this call emitted per slot — the host retires,
    streams, and accounts them in one bulk pass."""

    def mega_core(params, caches, toks, ptab, pos, active, temp,
                  topk, topp, eos, end, keys):
        rows = jnp.arange(S)

        def body(carry, _):
            caches, toks, p, alive, fin, emitted = carry
            tok = toks[rows, p]
            if page_size is None:
                logits, caches2 = plan.step(params, caches, tok, p, ctx,
                                            write_ok=alive)
            else:
                logits, caches2 = plan.step(
                    params, caches, tok, p, ctx,
                    pages=(ptab, page_size, alive),
                    paged_kernel=paged_kernel)
            step_keys = jax.vmap(jax.random.fold_in)(
                jax.random.wrap_key_data(keys), p)
            nxt = _sample_slots(logits, step_keys, temp, topk, topp)
            new_p = jnp.where(alive, p + 1, p)
            cur = toks[rows, new_p]
            toks = toks.at[rows, new_p].set(jnp.where(alive, nxt, cur))
            emitted = emitted + alive.astype(jnp.int32)
            done = alive & ((nxt == eos) | (new_p >= end))
            fin = fin | done
            alive = alive & ~done
            return (caches2, toks, new_p, alive, fin, emitted), None

        init = (caches, toks, pos, active, jnp.zeros(S, bool),
                jnp.zeros(S, jnp.int32))
        (caches, toks, pos, _, fin, emitted), _ = jax.lax.scan(
            body, init, None, length=N)
        return caches, toks, pos, active & ~fin, fin, emitted

    if page_size is None:
        def megastep(params, caches, toks, pos, active, temp, topk,
                     topp, eos, end, keys):
            return mega_core(params, caches, toks, None, pos, active,
                             temp, topk, topp, eos, end, keys)
    else:
        def megastep(params, caches, toks, ptab, pos, active, temp,
                     topk, topp, eos, end, keys):
            return mega_core(params, caches, toks, ptab, pos, active,
                             temp, topk, topp, eos, end, keys)

    return jax.jit(megastep, donate_argnums=(1, 2))


#: parked/cold speculative-drafting probe interval (scheduler ticks):
#: a workload the drafter cannot pay for decays to plain decode plus
#: one drafting attempt — and, when a draft exists, one measuring
#: verify step — every this many ticks, bounding the overhead of an
#: unpredictable workload to ~(cost ratio - 1)/64 per tick while still
#: re-qualifying speculation within one interval of a workload shift.
_SPEC_PROBE_TICKS = 64


def ngram_draft(hist, k: int, *, n_max: int = 3, n_min: int = 1):
    """Prompt-lookup/n-gram drafter (host-side): propose the ``k``
    tokens that followed the most recent earlier occurrence of the
    history's trailing n-gram, longest match first.  Returns a (k,)
    int32 row padded with ``-1`` past the available continuation, or
    None when no n-gram of any tried length recurs — the draft is a
    guess the verify program checks against the model's own choices,
    so a bad one costs wasted micro-steps, never wrong tokens.  This is
    the second-model-free drafter (``root.common.serve.spec.drafter =
    "ngram"``): repetitive and structured continuations — chat turns
    over a shared system prompt, code, the cycles greedy decode settles
    into — are exactly where trailing n-grams recur.

    The search is ``bytes.rfind`` over the raw int32 buffer (C speed —
    this runs per slot per scheduler tick, so a numpy window scan
    would cost more than the decode step it is trying to save), with
    a 4-byte alignment walk rejecting the rare unaligned byte-level
    false match."""
    hist = np.ascontiguousarray(hist, np.int32)
    L = int(hist.size)
    buf = hist.tobytes()
    for n in range(n_max, n_min - 1, -1):
        if L < n + 2:       # need the pattern + an earlier occurrence
            continue        # with at least one continuation token
        pat = buf[(L - n) * 4:]
        # search region ends at element L-2: the match must sit
        # strictly before the trailing pattern itself
        hi = (L - 1) * 4
        off = buf.rfind(pat, 0, hi)
        while off >= 0 and off % 4:     # byte-, not element-aligned
            off = buf.rfind(pat, 0, off + len(pat) - 1)
        if off < 0:
            continue
        start = off // 4 + n            # most recent occurrence
        cont = hist[start:start + k]
        if not cont.size:
            continue
        row = np.full(k, -1, np.int32)
        row[:cont.size] = cont
        return row
    return None


def make_prefill_fn(plan, ctx, pb: int, cache_dtype, *,
                    page_size: Optional[int] = None,
                    full_ctx: bool = True):
    """The engine's bucketed-prefill program for bucket length ``pb``
    (un-compiled jitted function; module-level for the same exporter
    single-source reason as :func:`make_decode_fn`).

    BOTH layouts take a traced ``start``: the program processes only the
    ``new_len`` tokens AFTER the ``start`` offset, continuing from
    whatever state the slot already holds.  On the paged side that is
    the shared-prefix half of the paged cache (the prefix-cache hit:
    attend through the page table to pages an earlier request already
    prefilled, prefill only the tail — the bucket is sized by the
    tail).  On BOTH sides it is what makes **chunked prefill** a plain
    bucket call: a long prompt is fed as a sequence of bounded slices,
    each continuing at the previous slice's ``start``, interleaved with
    decode steps (docs/serving.md "Overload survival") — no new program
    kind, the compile counters stay flat.  Positions are global
    throughout (RoPE, masks, KV scatter, sampling-key folds), so the
    emitted token stream is bitwise identical to a single unchunked
    prefill.

    Dense form, ``full_ctx=True`` (the chunk-capable convention, and
    the one v3 artifacts seal): the slot's full ``(1, l_max)`` rows are
    sliced out of the batch caches, the slice scans its positions
    against them (a ``start > 0`` continuation must attend every
    earlier position), and the rows splice back.  ``start == 0`` resets
    recurrent carried state in-program (a traced select — the slot rows
    may hold a previous occupant's carry); pad steps revert the WHOLE
    carried tree, so a pad position's clamped scatter can never clobber
    a real row.

    ``full_ctx=False`` (static; dense only) is the bucket-local fast
    path for whole-tail admissions — the caller guarantees
    ``start == 0``: the scan runs against a FRESH ``(1, pb)`` local
    cache (each of the ``pb`` steps attends at most ``pb`` positions,
    not ``l_max`` — a short prompt on a long-context engine must not
    pay O(l_max) attention per token just because chunking exists) and
    splices its ``pb``-length slab into the slot's rows.  Bitwise: at
    ``start == 0`` the two variants differ only in cache positions
    beyond the prompt, which the causal mask guarantees are never
    attended before decode rewrites them."""

    if page_size is not None:
        return _make_paged_prefill_fn(plan, ctx, pb, page_size)
    from .generate import _rec_state_init

    def prefill(params, caches, toks, prompt, new_len, start, slot,
                temp, topk, topp, key_data):
        if full_ctx:
            local = jax.tree.map(
                lambda big: jax.lax.dynamic_slice(
                    big, (slot,) + (jnp.int32(0),) * (big.ndim - 1),
                    (1,) + big.shape[1:]),
                caches)
            for key, u in plan._rec_units:
                init = _rec_state_init(u, 1)
                local[key] = jax.tree.map(
                    lambda i, o: jnp.where(start == 0,
                                           i.astype(o.dtype), o),
                    init, local[key])
        else:
            # whole-tail admission at start == 0: fresh bucket-length
            # rows (KV length pb, recurrent carry at its reset state —
            # exactly the start == 0 select above resolves to)
            local = plan.init_caches(params, 1, pb, cache_dtype)

        def body(carry, i):
            local = carry
            pos = start + i                     # global position
            tok = prompt[:, i]
            # plan.step REBINDS the dict's top-level entries in
            # place — hand it a shallow copy so ``local`` still
            # holds the pre-step leaves the gate needs
            logits, new = plan.step(params, dict(local), tok, pos, ctx)
            # pad positions (i >= new_len) must not advance carried
            # state, write KV, or — via the update-slice clamp at the
            # cache edge — clobber a real position: revert everything
            valid = i < new_len
            local = jax.tree.map(
                lambda n, o: jnp.where(valid, n, o), new, local)
            return local, logits

        local, ys = jax.lax.scan(body, local, jnp.arange(pb))
        last = jax.lax.dynamic_index_in_dim(
            ys, new_len - 1, 0, keepdims=False)         # (1, V)
        # the fold position is GLOBAL (start + new_len - 1): bitwise
        # the key an unchunked prefill of the whole prompt folds
        key = jax.random.fold_in(
            jax.random.wrap_key_data(key_data), start + new_len - 1)
        first = _sample_slots(
            last, key[None], temp[None], topk[None], topp[None])[0]
        # splice the slot's advanced rows back into the engine batch
        caches = jax.tree.map(
            lambda big, loc: jax.lax.dynamic_update_slice(
                big, loc.astype(big.dtype),
                (slot,) + (jnp.int32(0),) * (loc.ndim - 1)),
            caches, local)
        # like the paged path, the prompt region of ``toks`` is never
        # written (retire assembles from the request's own prompt);
        # only the sampled first token lands, at its global position —
        # an intermediate chunk's sample is overwritten by nothing and
        # read by nothing (decode starts at the FINAL chunk's sample)
        toks = toks.at[slot, start + new_len].set(first)
        return caches, toks, first

    return jax.jit(prefill, donate_argnums=(1, 2))


def _make_paged_prefill_fn(plan, ctx, pb: int, psz: int):
    """Paged prefill for bucket length ``pb`` (see
    :func:`make_prefill_fn`): ``prompt`` holds the ``new_len`` un-shared
    tail tokens, ``start`` the global position of the first one (a page
    multiple — the prefix-cache hit boundary), ``ptab_row`` the slot's
    complete page table (shared prefix pages + freshly allocated private
    pages; unassigned logical pages point at the scratch row).  Attention
    KV lands directly in the pool; recurrent carried state scans a local
    B=1 copy and splices into the engine batch like the dense path.
    ``start`` is either the prefix-cache hit boundary (a page multiple)
    or a chunked-prefill slice boundary — any earlier position whose KV
    the slot's pages already hold (docs/serving.md "Overload
    survival").  NOTE: recurrent state is position-recurrent from token
    0, so chains with recurrent units never take PREFIX shortcuts — the
    engine admits them with prefix_start=0 (enforced host-side in
    ``_reserve_pages``); chunk boundaries instead carry the state
    across slices (see the in-body comment)."""
    from .generate import _rec_state_init
    attn_keys = plan.attn_keys()

    def prefill(params, caches, toks, ptab_row, prompt, new_len, start,
                slot, temp, topk, topp, key_data):
        work = dict(caches)
        for key, u in plan._rec_units:
            # start == 0 resets the carry in-program (fresh admission);
            # start > 0 CONTINUES from the slot's batch rows — the
            # previous chunk's splice — which is what makes chunked
            # prefill exact for recurrent chains too.  (Prefix-cache
            # shortcuts still never apply to recurrent chains: the
            # scheduler admits them with prefix_start = 0, so a start>0
            # here is always a chunk boundary.)
            init = _rec_state_init(u, 1)
            cur = jax.tree.map(
                lambda big: jax.lax.dynamic_slice(
                    big, (slot,) + (jnp.int32(0),) * (big.ndim - 1),
                    (1,) + big.shape[1:]),
                caches[key])
            work[key] = jax.tree.map(
                lambda i, o: jnp.where(start == 0, i, o.astype(i.dtype)),
                init, cur)

        def body(carry, i):
            work = carry
            pos = start + i                     # global position
            # pad steps (i >= new_len) must neither advance carried
            # state nor write KV: attention writes route to the scratch
            # pool row, recurrent state is where-gated below
            valid = i < new_len
            logits, new = plan.step(
                params, dict(work), prompt[:, i], pos[None], ctx,
                pages=(ptab_row[None], psz, valid[None]))
            out = {}
            for k in new:
                if k in attn_keys:
                    out[k] = new[k]             # pool: scratch-gated
                else:
                    out[k] = jax.tree.map(
                        lambda n, o: jnp.where(valid, n, o),
                        new[k], work[k])
            return out, logits

        work, ys = jax.lax.scan(body, work, jnp.arange(pb))
        last = jax.lax.dynamic_index_in_dim(
            ys, new_len - 1, 0, keepdims=False)         # (1, V)
        # the fold position is GLOBAL (start + new_len - 1 == P - 1):
        # bitwise the key a dense prefill of the whole prompt folds
        key = jax.random.fold_in(
            jax.random.wrap_key_data(key_data), start + new_len - 1)
        first = _sample_slots(
            last, key[None], temp[None], topk[None], topp[None])[0]
        out_caches = dict(caches)
        for k in work:
            if k in attn_keys:
                out_caches[k] = work[k]
            else:  # splice the slot's fresh recurrent state into the batch
                out_caches[k] = jax.tree.map(
                    lambda big, loc: jax.lax.dynamic_update_slice(
                        big, loc.astype(big.dtype),
                        (slot,) + (jnp.int32(0),) * (loc.ndim - 1)),
                    caches[k], work[k])
        toks = toks.at[slot, start + new_len].set(first)
        return out_caches, toks, first

    return jax.jit(prefill, donate_argnums=(1, 2))


class ServeGeometry(NamedTuple):
    """Resolved serving geometry (see :func:`resolve_serve_geometry`).
    ``paged`` selects the page-pool KV layout; ``pages`` is 0 when
    dense.  ``n_ptab`` (= l_max // page_size) is the per-slot page-table
    width — the number of logical pages a max-length request spans.
    ``paged_kernel`` routes paged attention reads through the fused
    Pallas kernel (bounded-error; only meaningful when ``paged``).
    ``megastep`` is the decode micro-steps fused per dispatch (1 =
    plain per-token stepping; see :func:`make_megastep_fn`)."""
    slots: int
    l_max: int
    bucket_min: int
    paged: bool
    page_size: int
    pages: int
    paged_kernel: bool = False
    megastep: int = 1

    @property
    def n_ptab(self) -> int:
        return self.l_max // self.page_size if self.paged else 0


def resolve_serve_geometry(slots=None, l_max=None, bucket_min=None,
                           paged=None, page_size=None, pages=None,
                           paged_kernel=None, megastep=None):
    """Slot-batch geometry with ``root.common.serve`` defaults — ONE
    resolution shared by the live engine and the compiled-artifact
    exporter (export/compiled.py), so a default-configured export's
    bucket inventory is exactly what a default-configured engine
    compiles.

    Paged knobs (``root.common.serve.{paged, page_size, pages}``): the
    default pool (``slots * l_max / page_size`` pages) matches the dense
    layout's HBM exactly; serving MORE concurrent requests in the same
    memory means raising ``slots`` while holding ``pages`` — the pool,
    not ``slots * l_max``, is then the real token capacity.  A
    default ``page_size`` that does not divide ``l_max`` halves itself
    until it does (an explicit one must divide, or the page table could
    not tile the sequence)."""
    serve = root.common.serve
    slots = int(slots if slots is not None else serve.get("slots", 8))
    l_max = int(l_max if l_max is not None else serve.get("l_max", 512))
    bucket_min = max(1, int(bucket_min if bucket_min is not None
                            else serve.get("prefill_bucket_min", 16)))
    if slots < 1 or l_max < 2:
        raise ValueError("need slots >= 1 and l_max >= 2")
    use_paged = bool(serve.get("paged", True) if paged is None else paged)
    psz = int(page_size if page_size is not None
              else serve.get("page_size", 16))
    # the fused Pallas read path only exists for the paged layout: an
    # EXPLICIT request on a dense geometry is a loud misconfiguration;
    # the config default merely doesn't apply (so a dense artifact
    # still loads under a paged_kernel-on config)
    use_kernel = bool(serve.get("paged_kernel", False)
                      if paged_kernel is None else paged_kernel)
    mega = int(serve.get("megastep", 1)
               if megastep is None else megastep)
    if mega < 1:
        raise ValueError(
            f"serve.megastep must be >= 1, got {mega}")
    if not use_paged:
        if paged_kernel:
            raise ValueError(
                "paged_kernel requires the paged KV layout "
                "(root.common.serve.paged / paged=True)")
        return ServeGeometry(slots, l_max, bucket_min, False, psz, 0,
                             False, mega)
    if psz < 1:
        raise ValueError(f"page_size must be >= 1, got {psz}")
    if l_max % psz:
        if page_size is not None:
            raise ValueError(
                f"page_size {psz} must divide l_max {l_max} (the page "
                "table tiles the sequence in whole pages)")
        while l_max % psz:  # default page size adapts to small l_max
            psz //= 2
    n_ptab = l_max // psz
    if pages is None:
        pages = serve.get("pages", None)     # config None = dense-equiv
    pages = int(pages) if pages is not None else slots * n_ptab
    if pages < n_ptab:
        raise ValueError(
            f"page pool of {pages} pages cannot hold one max-length "
            f"request ({n_ptab} pages of {psz} tokens for l_max {l_max})")
    return ServeGeometry(slots, l_max, bucket_min, True, psz, pages,
                         use_kernel, mega)


def prefill_bucket(p: int, bucket_min: int, l_max: int) -> int:
    """THE bucket function: pow2 ceiling of prompt length ``p``, floored
    at ``bucket_min``, clipped to ``l_max``.  The live lookup and the
    exporter's inventory (:func:`bucket_table`) must agree, or an
    ArtifactRunner request maps to a bucket absent from the sealed
    program set."""
    return min(1 << max(0, math.ceil(math.log2(max(p, bucket_min)))),
               l_max)


def bucket_table(bucket_min: int, l_max: int):
    """The fixed prefill-bucket set a (bucket_min, l_max) engine can ever
    request — the compiled-artifact manifest's program inventory (one
    exported prefill per entry)."""
    return sorted({prefill_bucket(p, bucket_min, l_max)
                   for p in range(1, l_max + 1)})


class _StreamHandle:
    """Incremental token feed for ONE streaming request (docs/serving.md
    "Streaming and mid-stream failover").  The scheduler thread is the
    only producer: it pushes monotonically numbered frames — the index
    is the GLOBAL generated-token index, so a resume seeded from an
    emitted prefix numbers its first frame exactly one past the last
    frame the interrupted run delivered, and the router can splice the
    two streams gaplessly.  The consumer drains via :meth:`events`,
    which always ends with exactly one terminal event (the engine
    closes the handle from ``_observe_finish``, which every terminal
    edge reaches).  The buffer is bounded: a consumer that stops
    draining gets its stream closed with an overflow error instead of
    growing host memory — the request itself still retires unary."""

    __slots__ = ("_cond", "_frames", "next_i", "prompt_tokens",
                 "buffer_tokens", "closed", "finish_reason", "error",
                 "overflowed")

    def __init__(self, start_i: int, prompt_tokens: int,
                 buffer_tokens: int):
        self._cond = threading.Condition()
        self._frames = collections.deque()  # pending (i, token)  # guarded-by: self._cond
        # next global generated index the engine will push; the
        # scheduler thread is the sole writer, so its own unlocked
        # reads are safe
        self.next_i = int(start_i)          # guarded-by: self._cond
        self.prompt_tokens = int(prompt_tokens)
        self.buffer_tokens = int(buffer_tokens)
        self.closed = False                 # guarded-by: self._cond
        self.finish_reason = None           # guarded-by: self._cond
        self.error: Optional[str] = None    # guarded-by: self._cond
        self.overflowed = False             # guarded-by: self._cond

    def push(self, start_i: int, tokens) -> int:
        """Producer: append frames numbered ``start_i`` onward, skipping
        indices already pushed (idempotent across prefill/flush overlap).
        Returns the number of frames actually appended."""
        n = 0
        with self._cond:
            if self.closed:
                return 0
            i = int(start_i)
            for t in tokens:
                if i >= self.next_i:
                    self._frames.append((i, int(t)))
                    self.next_i = i + 1
                    n += 1
                i += 1
            if self.buffer_tokens and len(self._frames) > self.buffer_tokens:
                # slow consumer: close the stream rather than stall the
                # scheduler or grow without bound; the unary result on
                # the request stays available
                self.overflowed = True
                self.closed = True
                self.finish_reason = "error"
                self.error = (f"stream buffer overflow: consumer left "
                              f"more than {self.buffer_tokens} frames "
                              "undrained (serve.stream.buffer_tokens)")
            self._cond.notify_all()
        return n

    def close(self, finish_reason: str, error: Optional[str] = None):
        """Producer: mark the stream terminal (first close wins)."""
        with self._cond:
            if not self.closed:
                self.closed = True
                self.finish_reason = finish_reason
                self.error = error
            self._cond.notify_all()

    def events(self, timeout_s: Optional[float] = None):
        """Consumer generator: every pending ``("token", i, tok)`` frame
        in index order, then exactly one ``("done", finish_reason,
        error)``.  ``timeout_s`` bounds the TOTAL wait for a live
        producer (a dead engine thread must not hang the consumer
        forever); expiry raises :class:`TimeoutError`."""
        end = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            with self._cond:
                while not self._frames and not self.closed:
                    rem = 1.0 if end is None \
                        else end - time.monotonic()
                    if rem <= 0:
                        raise TimeoutError(
                            "stream consumer timed out waiting for the "
                            "next frame")
                    self._cond.wait(min(rem, 1.0))
                frames = list(self._frames)
                self._frames.clear()
                closed = self.closed
                reason, err = self.finish_reason, self.error
            for i, t in frames:
                yield ("token", i, t)
            if closed:
                yield ("done", reason, err)
                return


class _Request:
    __slots__ = ("prompt", "n_steps", "temperature", "top_k", "top_p",
                 "eos_id", "key_data", "deadline", "done", "result",
                 "error", "submitted_at", "slot", "finished_at",
                 "page_row", "prefix_start", "page_hashes",
                 "trace_id", "admitted_at", "first_token_at", "bucket",
                 "priority", "batch", "gen", "preemptions",
                 "chunk_next", "chunk_first", "run_started_at", "_eff",
                 "stream", "stop_seqs", "stop_hit")

    def __init__(self, prompt, n_steps, temperature, top_k, top_p,
                 eos_id, key_data, deadline, priority: int = 0,
                 batch: bool = False):
        self.prompt = prompt            # (P,) np.int32
        self.n_steps = n_steps
        self.temperature = temperature
        self.top_k = top_k              # None or int
        self.top_p = top_p              # None or float
        self.eos_id = eos_id            # None or int
        self.key_data = key_data        # raw uint32 PRNG key data
        self.deadline = deadline        # absolute monotonic seconds
        self.done = threading.Event()
        self.result = None              # np.int32 tokens, prompt included
        self.error: Optional[Exception] = None
        self.submitted_at = time.monotonic()
        self.finished_at = None
        self.slot = None
        self.page_row = None            # paged: this request's page table
        self.prefix_start = 0           # paged: first un-shared position
        self.page_hashes = ()           # paged: chained full-page hashes
        # observability (runtime/metrics.py): one trace track per
        # request, host timestamps for the queue-wait/prefill/decode
        # span breakdown in GET /trace.json
        self.trace_id = next_trace_id()
        self.admitted_at = None         # FIRST admission (left the queue)
        self.first_token_at = None      # prefill returned (== TTFT end)
        self.bucket = None              # prefill bucket this request took
        # overload survival (docs/serving.md "Overload survival"):
        # request class (0 = highest), tokens already generated before a
        # preemption (a resume re-prefills prompt + gen so the final
        # stream is bitwise an uninterrupted run), chunked-prefill
        # progress, and the latest admission stamp (victim selection
        # prefers the youngest run — the one losing least progress)
        self.priority = int(priority)
        # batch lane (docs/serving.md "Batch lane"): trough-filler
        # class strictly below every interactive priority — admitted
        # only with headroom, first-preempted, excluded from SLO
        # histograms (the tracker snapshots whole registry histograms,
        # so exclusion must happen at observation time)
        self.batch = bool(batch)
        self.gen = np.empty(0, np.int32)
        self.preemptions = 0
        self.chunk_next = 0             # next global position to prefill
        self.chunk_first = 0            # where THIS admission's prefill
        #                                 began (metric labels use the
        #                                 whole tail's bucket, not the
        #                                 final slice's)
        self.run_started_at = None      # latest admission into a slot
        self._eff = None                # memoized effective prompt
        # streaming (docs/serving.md "Streaming and mid-stream
        # failover"): the per-request frame feed, optional stop
        # sequences (token-id arrays, stream-only), and whether a stop
        # sequence — not eos/length — ended the run
        self.stream: Optional["_StreamHandle"] = None
        self.stop_seqs = ()
        self.stop_hit = False

    @property
    def end_index(self) -> int:
        """Global index of the FINAL token (invariant across
        preemptions: original prompt length + n_steps - 1)."""
        return int(self.prompt.size) + int(self.n_steps) - 1

    def effective_prompt(self):
        """What an admission prefills: the original prompt plus every
        token generated before a preemption."""
        if self._eff is None:
            self._eff = (np.concatenate([self.prompt, self.gen])
                         if self.gen.size else self.prompt)
        return self._eff

    def finish(self, result=None, error=None):
        self.result, self.error = result, error
        self.finished_at = time.monotonic()
        self.done.set()


class _PrioQueue:
    """Strict-priority FIFO over ``priorities`` classes (0 = highest):
    FIFO within a class, pops always drain the highest class first —
    the queue-jump half of the priority contract.  NOT thread-safe on
    its own: every mutation happens under the engine's ``_qlock``; the
    scheduler's lock-free emptiness peeks read one deque's truthiness
    at a time (GIL-atomic, the same staleness contract as the single
    deque this replaces)."""

    __slots__ = ("_qs",)

    def __init__(self, priorities: int):
        self._qs = [collections.deque()
                    for _ in range(max(1, int(priorities)))]

    def __len__(self):
        return sum(len(q) for q in self._qs)

    def __bool__(self):
        return any(self._qs)

    def __iter__(self):
        for q in self._qs:
            yield from q

    def append(self, req):
        self._qs[req.priority].append(req)

    def appendleft(self, req):
        self._qs[req.priority].appendleft(req)

    def popleft(self):
        for q in self._qs:
            if q:
                return q.popleft()
        return None

    def steal_lower(self, priority: int):
        """Evict and return the youngest NOT-YET-STARTED queued request
        of the LOWEST class strictly below ``priority``'s (class index
        strictly greater); None when nothing displaceable is queued —
        the full-queue queue-jump rule: a high-class arrival displaces
        the request that would have been served last anyway.  A
        PREEMPTED resume (``preemptions > 0``) is never displaced: it
        was accepted, held a slot, and carries committed device work in
        ``req.gen`` — finishing it with a 429 now would discard all of
        that and break the acceptance the 200-on-submit implied."""
        for c in range(len(self._qs) - 1, int(priority), -1):
            q = self._qs[c]
            for i in range(len(q) - 1, -1, -1):
                if q[i].preemptions == 0:
                    r = q[i]
                    del q[i]
                    return r
        return None

    def remove_if(self, pred):
        """Remove and return every queued request matching ``pred``
        (deadline sweeps), preserving order among the kept."""
        out = []
        for i, q in enumerate(self._qs):
            kept = collections.deque()
            for r in q:
                (out if pred(r) else kept).append(r)
            self._qs[i] = kept
        return out

    def clear(self):
        for q in self._qs:
            q.clear()


def _sample_slots(logits, keys, temp, top_k, top_p):
    """Per-slot next-token choice from (S, V) logits with per-slot
    traced sampling params — the batched twin of ``sample_logits``.

    Sentinels make a slot's filter a bitwise no-op exactly where the
    scalar path would SKIP it: ``top_k >= V`` clips to the minimum
    logit threshold (nothing filtered), ``top_p = 1.0`` cuts at the last
    sorted position (same), ``temp <= 0`` selects the greedy argmax.
    The op ORDER mirrors sample_logits: scale, top-k filter, top-p cut
    on the filtered logits, categorical; each slot draws its gumbel
    noise from its own key at shape (1, V) — the exact draw a B=1
    ``generate()`` makes, so single-row results are bitwise identical.
    """
    lg = logits.astype(jnp.float32)
    S, V = lg.shape
    greedy = jnp.argmax(lg, axis=-1)

    def do_sample():
        x = lg / jnp.where(temp > 0, temp, 1.0)[:, None]
        # top-k: k-th largest value as threshold
        # (== lax.top_k(...)[0][:,-1])
        srt = jnp.sort(x, axis=-1)[:, ::-1]
        kth = jnp.take_along_axis(
            srt, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1)
        x2 = jnp.where(x < kth, -jnp.inf, x)
        # top-p on the top-k-FILTERED logits (sample_logits order)
        srt2 = jnp.sort(x2, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(srt2, axis=-1)
        csum = jnp.cumsum(probs, axis=-1) - probs
        cut = jnp.maximum(
            jnp.sum(jnp.where(csum < top_p[:, None], 1, 0), axis=-1) - 1,
            0)
        thresh = jnp.take_along_axis(srt2, cut[:, None], axis=-1)
        x3 = jnp.where(x2 < thresh, -jnp.inf, x2)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row[None, :])[0])(
                keys, x3)
        return jnp.where(temp > 0, sampled, greedy)

    # all-greedy steps skip the sort/softmax/gumbel machinery entirely
    # (a runtime branch, not a trace-time one: the program stays fixed)
    return jax.lax.cond(
        (temp > 0).any(), do_sample, lambda: greedy).astype(jnp.int32)


class DecodeEngine(Logger):
    """Continuous-batching decode engine over a :class:`DecodePlan`.

    ``slots`` / ``l_max`` / ``window_ms`` / ``queue_depth`` /
    ``deadline_s`` / ``prefill_bucket_min`` default from
    ``root.common.serve.*`` (docs/serving.md).  Requests are single
    sequences; :meth:`generate` is the batch-blocking convenience with
    the ``generate()`` contract, :meth:`submit` the async primitive the
    REST layer drives.
    """

    def __init__(self, workflow, wstate, *, slots: Optional[int] = None,
                 l_max: Optional[int] = None,
                 window_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 output_unit: Optional[str] = None,
                 cache_dtype=jnp.float32, status=None,
                 paged: Optional[bool] = None,
                 page_size: Optional[int] = None,
                 pages: Optional[int] = None,
                 paged_kernel: Optional[bool] = None,
                 spec: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_drafter: Optional[str] = None,
                 megastep: Optional[int] = None,
                 priorities: Optional[int] = None,
                 preempt: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 admission: Optional[AdmissionController] = None):
        self.workflow = workflow
        self.wstate = wstate
        self._init_config(slots=slots, l_max=l_max, window_ms=window_ms,
                          queue_depth=queue_depth, deadline_s=deadline_s,
                          paged=paged, page_size=page_size, pages=pages,
                          paged_kernel=paged_kernel, spec=spec,
                          spec_k=spec_k, spec_drafter=spec_drafter,
                          megastep=megastep,
                          priorities=priorities, preempt=preempt,
                          prefill_chunk=prefill_chunk,
                          admission=admission)
        self.plan = DecodePlan(workflow, output_unit)
        self.cache_dtype = cache_dtype
        self._ctx = Context(train=False, key=None, mesh=None)
        self.step_cache = StepCache()
        self.status = status
        # recurrent carried state is position-recurrent from token 0 and
        # is NOT paged, so prefix shortcuts are attention-only chains'
        # win (ArtifactRunner reads the same fact off the manifest)
        self._prefix_ok = not self.plan._rec_units
        self._init_runtime(wstate["params"])

    def _init_config(self, *, slots, l_max, window_ms, queue_depth,
                     deadline_s, bucket_min=None, paged=None,
                     page_size=None, pages=None, paged_kernel=None,
                     spec=None, spec_k=None, spec_drafter=None,
                     megastep=None, priorities=None, preempt=None,
                     prefill_chunk=None, admission=None):
        serve = root.common.serve
        geo = resolve_serve_geometry(slots, l_max, bucket_min,
                                     paged=paged, page_size=page_size,
                                     pages=pages,
                                     paged_kernel=paged_kernel,
                                     megastep=megastep)
        self.slots, self.l_max, self.bucket_min = \
            geo.slots, geo.l_max, geo.bucket_min
        self.paged, self.page_size, self.pages = \
            geo.paged, geo.page_size, geo.pages
        self.n_ptab = geo.n_ptab
        self.paged_kernel = geo.paged_kernel
        # megastep decode (docs/serving.md "Megastep decode"): N decode
        # micro-steps per dispatch; 1 = the plain per-token loop and no
        # fourth program is compiled at all
        self.megastep = geo.megastep
        self.window_s = float(window_ms if window_ms is not None
                              else serve.get("window_ms", 2.0)) / 1e3
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else serve.get("queue_depth", 64))
        self.deadline_s = float(deadline_s if deadline_s is not None
                                else serve.get("deadline_s", 120.0))
        # overload survival (docs/serving.md "Overload survival"):
        # request classes (0 = highest; priorities=1 turns the feature
        # off), preemption of strictly-lower classes, and chunked
        # prefill (0 = off; slices of this many tokens interleave with
        # decode steps so one long prompt costs everyone bounded delay)
        self.priorities = max(1, int(serve.get("priorities", 3)
                                     if priorities is None
                                     else priorities))
        self.preempt = bool(serve.get("preempt", True)
                            if preempt is None else preempt)
        self.prefill_chunk = int(serve.get("prefill_chunk", 256)
                                 if prefill_chunk is None
                                 else prefill_chunk)
        # streaming (docs/serving.md "Streaming and mid-stream
        # failover"): how many undrained frames a consumer may leave
        # buffered before its stream is closed with an overflow error
        self.stream_buffer_tokens = int(
            serve.stream.get("buffer_tokens", 4096))
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {self.prefill_chunk}")
        # calling-convention / capability flags the ArtifactRunner
        # overrides from its manifest: whether the prefill programs take
        # the traced ``start`` (live builders always do; sealed dense
        # programs from older exports do not) and whether mid-prompt
        # continuation — chunked prefill — is safe on them
        self._prefill_start = True
        self._chunk_capable = True
        self._admission_arg = admission
        # speculative decoding (docs/serving.md "Speculative decoding"):
        # the host-side drafter proposes up to spec_k tokens per slot
        # and the third program kind verifies them in one call
        self.spec = bool(serve.spec.get("enabled", False)
                         if spec is None else spec)
        self.spec_k = int(serve.spec.get("k", 4)
                          if spec_k is None else spec_k)
        self.spec_drafter = str(serve.spec.get("drafter", "ngram")
                                if spec_drafter is None else spec_drafter)
        if self.spec:
            if self.spec_k < 1:
                raise ValueError(
                    f"serve.spec.k must be >= 1, got {self.spec_k}")
            if self.spec_drafter != "ngram":
                raise ValueError(
                    f"unknown speculative drafter "
                    f"{self.spec_drafter!r} (supported: 'ngram')")

    def _init_runtime(self, params):  # not-shared: __init__-only construction, precedes any thread
        """Slot state + scheduler + gauges + the AOT decode program —
        everything downstream of the three program hooks
        (:meth:`_make_caches` / :meth:`_head_width` /
        :meth:`_compile_decode`), which the artifact runner
        (runtime/artifact.py) overrides to serve deserialized StableHLO
        instead of freshly traced model code."""
        self._caches = self._make_caches(params)
        self._toks = jnp.zeros((self.slots, self.l_max), jnp.int32)
        # host-side per-slot metadata, passed into the compiled step
        S = self.slots
        self._pos = np.zeros(S, np.int32)       # index of last written tok
        self._active = np.zeros(S, bool)
        self._temp = np.zeros(S, np.float32)
        self._topk = np.zeros(S, np.int32)      # sentinel: V (keeps all)
        self._topp = np.ones(S, np.float32)     # sentinel: 1.0
        self._eos = np.full(S, -1, np.int32)    # sentinel: -1 (never hits)
        self._end = np.zeros(S, np.int32)       # final token index
        kd = jax.random.key_data(jax.random.key(0))
        self._keys = np.zeros((S,) + kd.shape, kd.dtype)
        self._slot_req: list = [None] * S

        # paged pool bookkeeping (host side; the device only ever sees
        # the int32 page table): refcounted physical pages, a chained
        # content-hash prefix index over full prompt pages, an LRU tick
        # for cached-page eviction, and the pool gauges
        if self.paged:
            self._scratch = self.pages          # pool row absorbing
            #                                     masked-off writes
            # _ptab is scheduler-thread-owned (written in _prefill,
            # read by _step_once); only the refcount/index structures
            # and pool gauges below cross threads via submit()/stats()
            self._ptab = np.full((S, self.n_ptab), self._scratch,
                                 np.int32)
            self._page_lock = threading.Lock()
            self._page_ref = np.zeros(self.pages, np.int32)  # guarded-by: self._page_lock
            self._page_free = list(range(self.pages))  # guarded-by: self._page_lock
            self._prefix_index: dict = {}  # guarded-by: self._page_lock
            self._page_key: dict = {}      # guarded-by: self._page_lock
            self._page_tick = np.zeros(self.pages, np.int64)  # guarded-by: self._page_lock
            self._tick = 0                 # guarded-by: self._page_lock
            self._prefix_hit_pages = 0     # guarded-by: self._page_lock
            self._prefix_miss_pages = 0    # guarded-by: self._page_lock
            self._evictions = 0            # guarded-by: self._page_lock
            self._cow_admissions = 0       # guarded-by: self._page_lock
            self._pool_rejected = 0        # guarded-by: self._page_lock
            # KV-page transfer (docs/serving.md "Disaggregated
            # prefill/decode"): which resident pages arrived over the
            # wire (import_pages) rather than from a local prefill, so
            # prefix hits on them can be attributed to the transfer
            self._imported_pages: set = set()  # guarded-by: self._page_lock
            self._remote_hit_pages = 0     # guarded-by: self._page_lock
            self._kv_exported_pages = 0    # guarded-by: self._page_lock
            self._kv_imported_pages = 0    # guarded-by: self._page_lock
            self._kv_export_bytes = 0      # guarded-by: self._page_lock
            self._kv_import_bytes = 0      # guarded-by: self._page_lock

        # staged KV-page imports: parsed+validated blobs wait here for
        # the scheduler to apply them at a decode-step boundary (the
        # same discipline as the swap double buffer — the scheduler
        # thread owns every _caches write).  Defined for dense engines
        # too (always empty there: import_pages rejects before staging).
        self._kv_imports: collections.deque = collections.deque()  # guarded-by: self._kv_import_lock
        self._kv_import_lock = threading.Lock()
        # wire-format identity: same-architecture weight sets share the
        # signature hash; the swap counter separates weight VERSIONS so
        # a blob exported before a hot swap can never contaminate the
        # post-swap prefix index (kv_wver property)
        self._kv_sig = hashlib.sha256(
            repr(tree_signature(params)).encode()).hexdigest()[:12]
        self._kv_entry_cache = None     # lazy _kv_entries() memo
        self._prefill_tok_s = 0.0       # scheduler-thread-written

        # queue + scheduler (priority-FIFO: class 0 pops first).  One
        # extra INTERNAL class beyond the configured interactive range
        # holds batch-lane work (docs/serving.md "Batch lane"): index
        # self.priorities, strictly below every submittable priority,
        # so victim selection preempts batch first and displacement
        # sheds queued batch first — with no code path treating batch
        # as anything but "just another (lowest) class".
        self._queue: _PrioQueue = _PrioQueue(self.priorities + 1)  # guarded-by: self._qlock
        self._qlock = threading.Lock()
        self._shed_by_class: dict = {}  # guarded-by: self._qlock
        # streaming: the live stream handles — the backing set of the
        # "stream-handles" resource pair (analysis/registry.py): every
        # _acquire_stream is balanced by a _release_stream on every
        # terminal edge via _observe_finish
        self._streams: set = set()      # guarded-by: self._qlock
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # chunked prefill: slots whose admission is mid-prefill (one
        # bounded slice per scheduler iteration, interleaved with
        # decode steps).  Scheduler-thread state like _ptab.
        self._chunking: set = set()
        self._qwait_ewma = 0.0          # scheduler-thread-written

        # hot-swap double buffer + drain mode (runtime/deploy.py)
        self._swap_lock = threading.Lock()
        self._staged = None  # (placed params, applied event)  # guarded-by: self._swap_lock
        self._swaps = 0
        self._draining = False
        self._died = False              # scheduler crashed (work FAILED)

        # gauges: per-engine views over the process-global metrics
        # registry (runtime/metrics.py) — stats(), status.json, GET
        # /engine and GET /metrics all read the SAME increments
        self._init_metrics()
        self._admitted = ScopedCounter(self._m_admitted)
        self._retired = ScopedCounter(self._m_retired)
        self._rejected = ScopedCounter(self._m_rejected)
        self._timeouts = ScopedCounter(self._m_timeouts)
        self._decode_steps = ScopedCounter(self._m_decode_steps)
        self._dispatches = ScopedCounter(self._m_dispatches)
        self._tok_count = ScopedCounter(self._m_tokens)
        self._occupancy_sum = 0
        self._rate_mark = (time.monotonic(), 0)
        self._tokens_per_sec = 0.0
        self._status_mark = 0.0
        # rolling SLO windows over the request histograms: the scheduler
        # tick rotates the ring (runtime/slo.py)
        self._slo = slo_tracker()
        # overload reflexes (runtime/admission.py): preemption counter
        # view + the SLO-driven admission-window controller, whose
        # sensor is the tracker's windowed burn rate.  Injectable for
        # deterministic tests (``admission=``).
        self._preempted = ScopedCounter(self._m_preempt)
        # batch lane: preemption counter view + a dedicated token rate
        # (scheduler-thread-written, published by _publish_gauges)
        self._batch_preempted = ScopedCounter(self._m_batch_preempt)
        self._batch_tok_n = 0           # scheduler-thread-written
        self._batch_rate_mark = (time.monotonic(), 0)
        self._batch_tok_s = 0.0         # scheduler-thread-written
        self._admission = (self._admission_arg
                           if self._admission_arg is not None
                           else AdmissionController(
                               queue_depth=self.queue_depth,
                               priorities=self.priorities,
                               burn_fn=self._slo.max_burn,
                               gauge=self._g_admission))

        # head width (== logits' last dim), for the top_k no-op sentinel
        self._vocab = self._head_width(params)

        # the lifetime decode program, AOT-compiled up front
        self._decode = self._compile_decode(params)

        # megastep decode: the fourth program kind, compiled only when
        # configured on (N > 1) — an N=1 engine never pays its compile.
        # _mega_steps/_mega_bytes are scheduler-thread state.
        self._mega = None
        self._mega_steps = 0            # scheduler-thread-written
        self._mega_bytes = 0.0
        self._g_megastep_n.set(self.megastep)
        if self.megastep > 1:
            self._mega = self._compile_megastep(params)
            self._mega_bytes = self.step_cache.program_cost(
                "megastep")["bytes_accessed"]

        # speculative decoding: the ONE verify program (static k — the
        # third and last program kind) plus the host-side token history
        # the n-gram drafter reads.  _hist/_spec_* are scheduler-thread
        # state like _ptab; only the ScopedCounter views cross threads.
        self._verify = None
        self._verify_steps = 0          # scheduler-thread-written
        self._spec_proposed = ScopedCounter(self._m_spec_proposed)
        self._spec_accepted = ScopedCounter(self._m_spec_accepted)
        self._spec_rate_mark = (time.monotonic(), 0, 0)
        self._spec_accept_rate = 0.0
        # the interleave policy's measured state (scheduler-thread):
        # verify-step wall EWMA (vs the decode EWMA below), a recent
        # accept-rate EWMA (optimistic start so the first drafts run
        # and measure), and an attempt counter so a parked/cold policy
        # probes occasionally instead of paying drafter + history-sync
        # overhead per tick (armed so the FIRST tick attempts)
        self._verify_wall_ewma = 0.0
        self._verify_bytes = 0.0
        self._accept_ewma = 1.0
        self._ticks_since_attempt = _SPEC_PROBE_TICKS
        self._spec_attempts = 0         # cold-phase attempt budget
        if self.spec:
            self._hist = np.zeros((S, self.l_max), np.int32)
            self._hist_pos = np.zeros(S, np.int32)  # hist valid to here
            self._verify = self._compile_verify(params)
            self._verify_bytes = self.step_cache.program_cost(
                "verify")["bytes_accessed"]

        # goodput denominators: the decode program's cost analysis per
        # execution (bandwidth-utilization numerator) and a wall-time
        # EWMA the scheduler updates each step
        dc = self.step_cache.program_cost("decode")
        self._decode_flops = dc["flops"]
        self._decode_bytes = dc["bytes_accessed"]
        self._step_wall_ewma = 0.0      # scheduler-thread-written
        self._bw_ewma = 0.0             # achieved bytes/s (decode AND
        #                                 verify steps feed it)
        self._last_step_at = 0.0        # scheduler-thread-written

        # the aval-derived component ledger (runtime/memory.py,
        # GET /memory.json): exact bytes of what this engine pinned
        self._register_memory()

    def _init_metrics(self):  # not-shared: __init__-only construction, precedes any thread
        """Register the serving metrics (idempotent: engines come and go
        within one process, the registry series live on — stats() stays
        per-engine through the ScopedCounter views).  Names are the
        contract docs/observability.md's reference table documents and
        the VM4xx analysis rule enforces."""
        reg = registry()
        self._m_queue_wait = reg.histogram(
            "vt_request_queue_wait_seconds",
            "time a request waited between submit() and the start of "
            "its prefill (admission into a slot)")
        self._m_ttft = reg.histogram(
            "vt_request_ttft_seconds",
            "submit-to-first-token latency, labelled by the prefill "
            "bucket the request took", labels=("bucket",))
        self._m_prefill = reg.histogram(
            "vt_prefill_seconds",
            "wall time of one prefill program call, labelled by bucket",
            labels=("bucket",))
        self._m_decode_step = reg.histogram(
            "vt_decode_step_seconds",
            "wall time of one decode step (all active slots advance one "
            "token) — the per-token decode latency under load")
        self._m_requests = reg.counter(
            "vt_requests_total",
            "finished requests by outcome: ok | 429 (overload/pool "
            "rejection) | 504 (deadline) | crash (scheduler died) | "
            "stopped (engine stopped with work pending)",
            labels=("outcome",))
        self._m_admitted = reg.counter(
            "vt_engine_admitted_total", "requests admitted into a slot")
        self._m_retired = reg.counter(
            "vt_engine_retired_total", "requests retired complete")
        self._m_rejected = reg.counter(
            "vt_engine_rejected_total",
            "requests refused at submit (queue overflow or page-pool "
            "exhaustion; the HTTP 429 path)")
        self._m_timeouts = reg.counter(
            "vt_engine_timeouts_total",
            "requests failed on their deadline (queued or mid-flight; "
            "the HTTP 504 path)")
        self._m_decode_steps = reg.counter(
            "vt_engine_decode_steps_total",
            "decode micro-steps executed (a megastep dispatch counts "
            "its N fused micro-steps)")
        self._m_dispatches = reg.counter(
            "vt_decode_dispatches_total",
            "host dispatches of a token-advancing program (decode, "
            "speculative verify, or megastep) — the megastep "
            "amortization divides this by ~N at constant tokens")
        self._g_megastep_n = reg.gauge(
            "vt_megastep_n",
            "configured decode micro-steps fused per megastep "
            "dispatch (1 = megastep off)")
        self._m_tokens = reg.counter(
            "vt_engine_tokens_total", "tokens generated")
        self._m_swaps = reg.counter(
            "vt_engine_swaps_total", "hot weight swaps applied")
        self._g_occupancy = reg.gauge(
            "vt_engine_occupancy", "slots currently decoding")
        self._g_queue_depth = reg.gauge(
            "vt_engine_queue_depth", "requests waiting in the queue")
        self._g_tokens_per_sec = reg.gauge(
            "vt_engine_tokens_per_sec",
            "recent decode throughput (0.5s window)")
        self._g_pages_used = reg.gauge(
            "vt_pages_used", "pool pages referenced by live slots")
        self._g_pages_cached = reg.gauge(
            "vt_pages_cached",
            "refcount-0 pages kept resident by the prefix index")
        self._g_pages_free = reg.gauge(
            "vt_pages_free", "pool pages on the free list")
        self._g_prefix_hit_rate = reg.gauge(
            "vt_prefix_hit_rate",
            "fraction of full prompt pages served from the prefix "
            "cache since engine start")
        # goodput (docs/observability.md "Goodput & MFU"): how close to
        # the hardware the decode loop actually runs
        self._g_decode_bw = reg.gauge(
            "vt_decode_bandwidth_bytes_per_sec",
            "achieved decode memory traffic: cost-analysis bytes over "
            "wall (EWMA), fed by decode AND speculative verify steps")
        self._g_decode_mbu = reg.gauge(
            "vt_decode_mbu",
            "decode model-bandwidth-utilization: achieved bytes/s over "
            "root.common.observe.peak_hbm_gbps (0 = peak unknown)")
        self._g_tps_chip = reg.gauge(
            "vt_tokens_per_sec_per_chip",
            "recent decode throughput per local device")
        self._g_headroom = reg.gauge(
            "vt_memory_headroom_slots",
            "max-length requests the engine can still admit (free "
            "slots, bounded by free+evictable pages when paged)")
        # speculative decoding (docs/serving.md "Speculative decoding"):
        # proposal/acceptance volume plus the windowed accept rate that
        # decides whether the drafter is paying for its verify steps
        self._m_spec_proposed = reg.counter(
            "vt_spec_proposed_total",
            "draft tokens proposed to the speculative verify program")
        self._m_spec_accepted = reg.counter(
            "vt_spec_accepted_total",
            "draft tokens accepted (emitted token matched the proposal)")
        self._g_spec_accept_rate = reg.gauge(
            "vt_spec_accept_rate",
            "accepted/proposed draft tokens over the recent window "
            "(0.5s; 0 when nothing was proposed)")
        self._m_spec_verify = reg.histogram(
            "vt_spec_verify_step_seconds",
            "wall time of one speculative verify step (all active "
            "slots score k+1 positions in one call)")
        # overload survival (docs/serving.md "Overload survival"):
        # priority preemption volume, shed load by class, and the
        # admission controller's live window
        self._m_preempt = reg.counter(
            "vt_preemptions_total",
            "slots preempted (retired-and-requeued) so a higher-"
            "priority request could be admitted")
        self._m_shed = reg.counter(
            "vt_shed_total",
            "requests shed by the admission controller or displaced "
            "from a hard-full queue by a higher-priority arrival, by "
            "request class", labels=("priority",))
        self._g_admission = reg.gauge(
            "vt_admission_window",
            "admitted queue window the SLO-driven controller currently "
            "grants (== serve.queue_depth when fully open)")
        # KV-page transfer (docs/serving.md "Disaggregated
        # prefill/decode"): serialized prefix-page export/import volume
        # and the prefix hits that landed on imported pages
        self._m_kv_exported = reg.counter(
            "vt_kv_pages_exported_total",
            "prefix pages serialized out by export_pages "
            "(GET /kv/pages)")
        self._m_kv_imported = reg.counter(
            "vt_kv_pages_imported_total",
            "prefix pages deserialized into the pool by import_pages "
            "(PUT /kv/pages) — skipped duplicates and pool-full drops "
            "not included")
        self._m_kv_bytes = reg.counter(
            "vt_kv_transfer_bytes_total",
            "serialized KV-page wire bytes, by transfer direction",
            labels=("direction",))
        self._m_kv_seconds = reg.histogram(
            "vt_kv_transfer_seconds",
            "wall time of one export_pages / import_pages call "
            "(serialize or validate+apply; not the network leg), by "
            "direction", labels=("direction",))
        self._m_remote_hits = reg.counter(
            "vt_prefix_remote_hits_total",
            "prefix-cache page hits served by pages that arrived via "
            "KV-page import rather than a local prefill")
        # batch lane (docs/serving.md "Batch lane"): trough-filler
        # throughput and how often interactive traffic reclaimed its
        # slots.  Batch requests never touch the SLO histograms above —
        # exclusion happens at observation time.
        self._g_batch_tps = reg.gauge(
            "vt_batch_tokens_per_sec",
            "recent batch-lane decode throughput (0.5s window) — the "
            "trough goodput interactive SLOs never see")
        self._m_batch_preempt = reg.counter(
            "vt_batch_preemptions_total",
            "batch-lane slots preempted so interactive work could be "
            "admitted (subset of vt_preemptions_total)")
        # streaming (docs/serving.md "Streaming and mid-stream
        # failover"): engine-side frame volume and live handle count
        self._m_stream_frames = reg.counter(
            "vt_stream_frames_total",
            "token frames pushed to streaming consumers")
        self._g_stream_active = reg.gauge(
            "vt_stream_active",
            "stream handles currently open on this engine")

    def _register_memory(self):  # not-shared: __init__-only construction, precedes any thread
        """Publish this engine's aval-derived byte ledger (runtime/
        memory.py, GET /memory.json): params, the KV cache (page pool or
        dense rows), and the slot state (recurrent carries + token rows
        + page tables).  Exact shape*itemsize arithmetic — the same
        numbers on CPU and TPU, which is what makes the ledger testable
        where the device reports nothing.  ``stats()["memory"]`` reads
        the per-engine copy kept here, never the process ledger — two
        engines in one process (a bench A/B, a deploy reload) must not
        read each other's bytes; the process ledger keeps last-writer-
        wins for /memory.json and the finalizer drops this engine's
        stamped entries when its buffers are actually freed."""
        import weakref
        mem = memory_monitor()
        attn = self._attn_cache_keys()
        kv = {k: v for k, v in self._caches.items() if k in attn}
        rest = {k: v for k, v in self._caches.items() if k not in attn}
        slot_state = tree_bytes(rest) + tree_bytes(self._toks)
        if self.paged:
            slot_state += int(self._ptab.nbytes)
        self._mem_bytes = {
            "params": tree_bytes(self.wstate["params"]),
            "kv_cache": tree_bytes(kv),
            "slot_state": slot_state,
        }
        stamps = {f"engine.{k}": mem.set_component(f"engine.{k}", v)
                  for k, v in self._mem_bytes.items()}
        extra_stamp = mem.set_extra("engine", {
            "slots": self.slots, "l_max": self.l_max,
            "paged": self.paged,
            **({"pages": self.pages, "page_size": self.page_size}
               if self.paged else {}),
        })
        from .memory import drop_stamped_components
        self._mem_finalizer = weakref.finalize(
            self, drop_stamped_components, stamps,
            {"engine": extra_stamp})
        mem.ensure_poller()

    def _attn_cache_keys(self):
        """Cache keys backed by attention KV.  The live engine asks its
        DecodePlan; an ArtifactRunner (plan=None) classifies by the
        cache's own structure — attention entries are {"k", "v"} dicts,
        recurrent carries are {"h"(, "c")} — which the sealed rows
        preserve."""
        if self.plan is not None:
            return self.plan.attn_keys()
        return {k for k, v in self._caches.items()
                if isinstance(v, dict) and "k" in v and "v" in v}

    def _observe_finish(self, req, outcome: str):
        """Host-side request accounting at every terminal edge: the
        outcome counter plus the request's span-ring timeline
        (queue-wait → prefill → decode nested under one request span,
        one trace track per request id)."""
        # the stream handle (when one exists) closes at the SAME edge
        # the outcome counter observes — a streaming consumer always
        # gets exactly one terminal frame, whatever ended the request
        self._release_stream(req, outcome)
        self._m_requests.labels(outcome=outcome).inc()
        sub = req.submitted_at
        fin = req.finished_at if req.finished_at is not None \
            else time.monotonic()
        ring = span_ring()
        args = {"id": req.trace_id, "outcome": outcome,
                "prompt_tokens": int(req.prompt.size),
                "n_steps": int(req.n_steps)}
        if req.priority:
            args["priority"] = int(req.priority)
        if req.preemptions:
            args["preemptions"] = int(req.preemptions)
        if req.slot is not None:
            args["slot"] = int(req.slot)
        if req.bucket is not None:
            args["bucket"] = int(req.bucket)
        if self.paged and req.admitted_at is not None:
            args["prefix_start"] = int(req.prefix_start)
        ring.add("request", sub, fin - sub, cat="request",
                 tid=req.trace_id, args=args)
        if req.admitted_at is None:
            ring.add("queue_wait", sub, fin - sub, cat="serve",
                     tid=req.trace_id)
            return
        ring.add("queue_wait", sub, req.admitted_at - sub, cat="serve",
                 tid=req.trace_id)
        if req.first_token_at is not None:
            ring.add("prefill", req.admitted_at,
                     req.first_token_at - req.admitted_at, cat="serve",
                     tid=req.trace_id,
                     args={"bucket": int(req.bucket or 0)})
            ring.add("decode", req.first_token_at,
                     fin - req.first_token_at, cat="serve",
                     tid=req.trace_id)

    # -- stream handles (analysis/registry.py RESOURCE_PAIRS
    # "stream-handles"): acquired in submit(), released at every
    # terminal edge via _observe_finish -------------------------------------
    def _acquire_stream(self, req: _Request) -> _StreamHandle:
        """Open the request's frame feed and register it in the live
        set (``_streams``) — the VR7xx lifecycle rules prove every
        terminal edge releases it.  Frame numbering starts at the
        request's emitted-prefix size, so a failover resume continues
        the interrupted run's numbering."""
        h = _StreamHandle(int(req.gen.size), int(req.prompt.size),
                          self.stream_buffer_tokens)
        with self._qlock:
            self._streams.add(h)
            self._g_stream_active.set(len(self._streams))
        return h

    def _release_stream(self, req: _Request, outcome: str):
        """Close + unregister the request's stream handle (no-op for
        unary requests).  The terminal frame's finish reason maps from
        the request outcome: ok → stop/eos/length, 504 → deadline,
        everything else (shed, crash, stopped) → error."""
        h = req.stream
        if h is None:
            return
        err = None
        if outcome == "ok":
            gen_n = (0 if req.result is None
                     else int(req.result.size) - int(req.prompt.size))
            if req.stop_hit:
                reason = "stop"
            elif (req.eos_id is not None and gen_n
                    and gen_n < int(req.n_steps)
                    and int(req.result[-1]) == int(req.eos_id)):
                reason = "eos"
            else:
                reason = "length"
        elif outcome == "504":
            reason = "deadline"
            err = (str(req.error) if req.error is not None
                   else "request deadline expired")
        else:
            reason = "error"
            err = str(req.error) if req.error is not None else outcome
        h.close(reason, err)
        with self._qlock:
            self._streams.discard(h)
            self._g_stream_active.set(len(self._streams))

    # -- compiled programs --------------------------------------------------
    @staticmethod
    def _sds(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype),
            tree)

    def _make_caches(self, params):
        if self.paged:
            return self.plan.init_caches(
                params, self.slots, self.l_max, self.cache_dtype,
                kv_rows=self.pages + 1, page_size=self.page_size)
        return self.plan.init_caches(
            params, self.slots, self.l_max, self.cache_dtype)

    def _head_width(self, params) -> int:
        S = self.slots
        shallow = dict(self._caches)  # plan.step rebinds top-level keys
        pages_arg = None
        if self.paged:
            pages_arg = (jnp.zeros((S, self.n_ptab), jnp.int32),
                         self.page_size, jnp.zeros(S, bool))
        return int(jax.eval_shape(
            lambda p, c, t, pv: self.plan.step(p, c, t, pv, self._ctx,
                                               pages=pages_arg)[0],
            params, shallow, jnp.zeros(S, jnp.int32),
            jnp.zeros(S, jnp.int32)).shape[-1])

    def _decode_args_sds(self, params):
        args = (params, self._caches, self._toks)
        if self.paged:
            args += (self._ptab,)
        return self._sds(args + (self._pos, self._active, self._temp,
                                 self._topk, self._topp, self._eos,
                                 self._end, self._keys))

    def _prefill_args_sds(self, params, pb: int):
        z32 = np.int32(0)
        if self.paged:
            return self._sds((params, self._caches, self._toks,
                              self._ptab[0], np.zeros((1, pb), np.int32),
                              z32, z32, z32, np.float32(0), z32,
                              np.float32(1), self._keys[0]))
        if self._prefill_start:
            return self._sds((params, self._caches, self._toks,
                              np.zeros((1, pb), np.int32), z32, z32,
                              z32, np.float32(0), z32, np.float32(1),
                              self._keys[0]))
        # sealed dense artifacts from pre-chunking exports: the
        # whole-prompt calling convention (no traced start)
        return self._sds((params, self._caches, self._toks,
                          np.zeros((1, pb), np.int32), z32, z32,
                          np.float32(0), z32, np.float32(1),
                          self._keys[0]))

    def _geometry_key(self):
        """StepCache key suffix: everything shape-determining about the
        cache layout (a paged and a dense engine at the same slots/l_max
        are DIFFERENT programs, as are the gather and fused-kernel read
        paths)."""
        if self.paged:
            return (self.slots, self.l_max, "paged", self.page_size,
                    self.pages) + (("pkernel",) if self.paged_kernel
                                   else ())
        return (self.slots, self.l_max)

    def _compile_decode(self, params):
        psz = self.page_size if self.paged else None
        step, _, _ = self.step_cache.get_step(
            "decode", self._geometry_key(),
            lambda: (make_decode_fn(self.plan, self._ctx, self.slots,
                                    page_size=psz,
                                    paged_kernel=self.paged_kernel),
                     None, None),
            self._decode_args_sds(params), pin=(self.workflow,))
        return step

    def _verify_args_sds(self, params):
        return self._decode_args_sds(params) + (
            jax.ShapeDtypeStruct((self.slots, self.spec_k), jnp.int32),)

    def _compile_verify(self, params):
        psz = self.page_size if self.paged else None
        step, _, _ = self.step_cache.get_step(
            "verify", self._geometry_key() + ("k", self.spec_k),
            lambda: (make_verify_fn(self.plan, self._ctx, self.slots,
                                    self.spec_k, page_size=psz,
                                    paged_kernel=self.paged_kernel),
                     None, None),
            self._verify_args_sds(params), pin=(self.workflow,))
        return step

    def _compile_megastep(self, params):
        # same calling convention as the decode program; N joins the
        # StepCache key the way the verify program's k does, so two
        # engines at different N are different programs, never a
        # recompile of one
        psz = self.page_size if self.paged else None
        step, _, _ = self.step_cache.get_step(
            "megastep", self._geometry_key() + ("mega", self.megastep),
            lambda: (make_megastep_fn(self.plan, self._ctx, self.slots,
                                      self.megastep, page_size=psz,
                                      paged_kernel=self.paged_kernel),
                     None, None),
            self._decode_args_sds(params), pin=(self.workflow,))
        return step

    def _bucket(self, p: int) -> int:
        return prefill_bucket(p, self.bucket_min, self.l_max)

    def _prefill_fn(self, pb: int, params, full_ctx: bool = True):
        """Fetch/compile the prefill program for bucket length ``pb``.
        ``full_ctx=False`` (dense only — the paged program always works
        through the page table) selects the bucket-local fast variant
        for whole-tail ``start == 0`` admissions; chunk slices need the
        full-context form (see :func:`make_prefill_fn`)."""
        psz = self.page_size if self.paged else None
        full_ctx = True if self.paged else bool(full_ctx)
        step, _, _ = self.step_cache.get_step(
            "prefill", (pb, full_ctx) + self._geometry_key(),
            lambda: (make_prefill_fn(self.plan, self._ctx, pb,
                                     self.cache_dtype, page_size=psz,
                                     full_ctx=full_ctx),
                     None, None),
            self._prefill_args_sds(params, pb), pin=(self.workflow,))
        return step

    # -- public API ---------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="decode-engine", daemon=True)
        self._thread.start()
        if self.paged:
            self.info(
                "decode engine: %d slots x L=%d over %d pages x %d "
                "tokens (paged, prefix reuse %s), queue %d",
                self.slots, self.l_max, self.pages, self.page_size,
                "on" if self._prefix_ok else "off", self.queue_depth)
        else:
            self.info("decode engine: %d slots x L=%d, queue %d",
                      self.slots, self.l_max, self.queue_depth)
        return self

    @property
    def started(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self):
        self._stop_evt.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
            if t.is_alive():
                # a wedged scheduler must keep owning the slots: if we
                # forgot it here, a restart would spawn a SECOND
                # scheduler double-donating the same device buffers
                self.warning("scheduler did not exit within 30s; "
                             "engine cannot be restarted until it does")
                return
            self._thread = None

    # -- lifecycle ops: hot swap + drain (runtime/deploy.py drives these) ---
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def swaps(self) -> int:
        return self._swaps

    def swap_params(self, params, *, timeout: Optional[float] = None):
        """Zero-downtime hot weight swap: stage ``params`` on device as a
        double buffer while the current version keeps serving, then flip
        the served tree atomically at a decode-step boundary.

        The new tree must match the live one leaf for leaf in path,
        shape and dtype — the compiled prefill/decode programs are
        reused as-is (the StepCache counters stay flat across a swap); a
        mismatched tree is rejected with a clear error and the old
        version keeps serving.  In-flight slots finish their current
        step on the old buffer; the next step reads the new one (their
        KV caches are model-version-mixed for the remainder of the
        sequence — the standard continuous-serving trade, documented in
        docs/serving.md).  Thread-safe; blocks until the flip happened
        or ``timeout`` (default ``root.common.serve.swap_timeout_s``)
        expired, in which case the staged buffer is withdrawn and the
        old version keeps serving.
        """
        if timeout is None:
            timeout = float(root.common.serve.get("swap_timeout_s", 60.0))
        old_sig = tree_signature(self.wstate["params"])
        new_sig = tree_signature(params)
        if old_sig != new_sig:
            raise ValueError(
                "hot swap rejected — parameter tree does not match the "
                "compiled programs (same-architecture weights only; a "
                "different architecture needs a fresh engine): "
                + signature_mismatch(old_sig, new_sig))
        # fully staged BEFORE the flip: the scheduler must never block
        # a decode step on an in-flight H2D transfer (no-op when the
        # caller pre-placed the tree, e.g. DeployController._stage)
        staged = place_like(params, self.wstate["params"])
        if not self.started:
            self.wstate = dict(self.wstate, params=staged)
            self._swaps += 1
            self._m_swaps.inc()
            self._invalidate_prefix_cache()
            return
        done = threading.Event()
        with self._swap_lock:
            if self._staged is not None:
                raise RuntimeError(
                    "another swap is already staged and not yet applied")
            self._staged = (staged, done)
        self._wake.set()
        if not done.wait(timeout):
            with self._swap_lock:
                if self._staged is not None and self._staged[1] is done:
                    self._staged = None
                    raise TimeoutError(
                        f"swap not applied within {timeout}s (scheduler "
                        "wedged?); the old version keeps serving")
            # the flip landed between the wait timeout and the lock

    def _apply_swap(self):
        """Scheduler-thread only: flip the served params to the staged
        buffer.  Called between decode steps, so no program is mid-step
        — in-flight slots see the new weights from their NEXT token."""
        with self._swap_lock:
            staged, self._staged = self._staged, None
        if staged is None:
            return
        params, done = staged
        self.wstate = dict(self.wstate, params=params)
        self._swaps += 1
        self._m_swaps.inc()
        # cached prefix pages hold KV computed under the OLD weights.
        # In-flight slots finishing on mixed versions is the documented
        # hot-swap trade, but a stale cached prefix would contaminate
        # arbitrarily many NEW requests (and every hit would renew its
        # LRU tick, so it would never age out) — drop the index now.
        self._invalidate_prefix_cache()
        done.set()

    def _invalidate_prefix_cache(self):
        """Unregister every cached prefix page (post-swap: their KV
        belongs to the previous weights).  Refcount-0 pages return to
        the free list; pages still referenced by in-flight slots keep
        serving THOSE slots and are freed by the normal release path
        once they retire (release checks registration at that point)."""
        if not self.paged:
            return
        with self._page_lock:
            for pid in list(self._page_key):
                del self._prefix_index[self._page_key.pop(pid)]
                if self._page_ref[pid] == 0:
                    self._page_free.append(pid)
            # imported pages hold peer KV computed under the OLD
            # weights too — same staleness, same drop
            self._imported_pages.clear()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admissions (``submit`` raises
        :class:`EngineDraining` → the REST layer's 503), let queued and
        in-flight work retire, then stop the scheduler.  Returns True
        when everything retired before ``timeout`` (default
        ``root.common.serve.drain_timeout_s``); on timeout the engine
        stops anyway and leftovers fail with :class:`EngineStopped`."""
        if timeout is None:
            timeout = float(root.common.serve.get("drain_timeout_s", 30.0))
        self._draining = True
        deadline = time.monotonic() + max(0.0, float(timeout))
        while self.started and time.monotonic() < deadline:
            if self._idle():
                break
            time.sleep(0.01)
        # a crashed scheduler also leaves the slots/queue empty — but
        # via _fail_all, which FAILED the work rather than retiring it:
        # that is a dirty drain, never a clean one
        clean = not self._died and self._idle()
        self.stop()
        return clean

    def _idle(self) -> bool:
        """No queued, reserved, or decoding work anywhere.  _slot_req is
        part of the check because a request being prefilled is already
        out of the queue but not yet in _active — drain must not
        declare victory inside that window."""
        with self._qlock:
            queued = bool(self._queue)
        return (not self._active.any() and not queued
                and all(r is None for r in self._slot_req))

    def submit(self, prompt, n_steps: int, *, temperature: float = 0.0,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               eos_id: Optional[int] = None, key=None,
               deadline_s: Optional[float] = None,
               priority: int = 0, batch: bool = False,
               stream: bool = False, emitted_prefix=None,
               stop=None) -> _Request:
        """Enqueue one sequence; returns a request whose ``done`` event
        fires with ``result`` (np.int32, prompt + generated, trimmed at
        eos) or ``error``.

        ``stream=True`` opens an incremental frame feed on
        ``req.stream`` (a :class:`_StreamHandle`): consume
        ``req.stream.events()`` for monotonically numbered token frames
        plus exactly one terminal event (docs/serving.md "Streaming and
        mid-stream failover").  ``emitted_prefix`` is the crash-safe
        RESUME form: pass the ORIGINAL prompt, ORIGINAL ``n_steps`` and
        ORIGINAL ``key`` plus the tokens already emitted by an
        interrupted run, and the continuation is bitwise-identical to
        the uninterrupted run — greedy and sampled — because it rides
        the preemption harvest/re-prefill path, whose sampling keys
        fold in GLOBAL token positions.  Frames of a resume are
        numbered from ``len(emitted_prefix)``, so a router can splice
        the streams gaplessly.  ``stop`` (streaming only) is a list of
        token-id sequences: generation retires early — "stop" finish
        reason — when the generated tail matches one, even across a
        flush boundary.  Raises :class:`EngineOverloaded` when the
        queue is full or the admission controller shed the request (the
        REST layer's 429 with an adaptive Retry-After).  ``priority``
        is the request class, 0 (the default, highest) to
        ``priorities - 1``: higher classes pop first, may displace a
        queued lower-class request from a hard-full queue, may preempt
        a running lower-class slot, and are the last the controller
        sheds (docs/serving.md "Overload survival").

        ``batch=True`` rides the trough-filler class (docs/serving.md
        "Batch lane"): strictly below every interactive priority,
        admitted only while slot headroom and SLO burn leave room
        (429 "trough closed" otherwise), first-preempted when
        interactive traffic arrives, and excluded from the queue-wait/
        TTFT SLO histograms.  ``priority`` is ignored for batch."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        n_steps = int(n_steps)
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        pref = None
        if emitted_prefix is not None:
            pref = np.asarray(emitted_prefix, np.int32).reshape(-1)
            # strictly fewer than n_steps: at == the resume would have
            # nothing left to generate, yet prefill always samples one
            # token — it would emit one PAST the original end_index
            if pref.size >= n_steps:
                raise ValueError(
                    f"emitted_prefix holds {pref.size} tokens but "
                    f"n_steps is {n_steps}; the resume form needs at "
                    "least one token left to generate (pass the "
                    "ORIGINAL n_steps, not the remainder)")
        stop_seqs = ()
        if stop:
            stop_seqs = tuple(np.asarray(s, np.int32).reshape(-1)
                              for s in stop)
            if not stream:
                raise ValueError(
                    "stop sequences ride the streaming path (their "
                    "detection runs at flush time); pass stream=True")
            if len(stop_seqs) > 16:
                raise ValueError(
                    f"at most 16 stop sequences, got {len(stop_seqs)}")
            for s in stop_seqs:
                if not 1 <= s.size <= 32:
                    raise ValueError(
                        "each stop sequence must hold 1..32 tokens, "
                        f"got {s.size}")
        priority = int(priority)
        if batch:
            # the internal lowest class — index self.priorities, one
            # past the submittable range, reserved for the batch lane
            priority = self.priorities
        elif not 0 <= priority < self.priorities:
            raise ValueError(
                f"priority must be in [0, {self.priorities}) "
                f"(serve.priorities classes, 0 = highest), got {priority}")
        # same contract as sample_logits: out-of-domain filters must be
        # a loud 400, not a silently-degenerate sentinel (top_k=0 would
        # make the k-th threshold the MAX logit — greedy in disguise)
        if top_k is not None and int(top_k) < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if prompt.size + n_steps > self.l_max:
            raise ValueError(
                f"prompt {prompt.size} + n_steps {n_steps} exceeds the "
                f"engine's l_max {self.l_max}")
        if key is None:
            key = jax.random.key(0)
        if self._draining:
            # drain contract: in-flight and already-queued work retires,
            # NEW work is refused so the slot set empties (HTTP 503)
            raise EngineDraining(
                "engine is draining; not accepting new requests")
        if self._died:
            raise SchedulerCrashed(
                "engine scheduler crashed earlier; restart the engine "
                "(see the scheduler_crash status event for the cause)")
        if not self.started:
            # a dead scheduler (stopped, or its loop died) would leave
            # the request queued forever with nothing enforcing its
            # deadline — fail the caller loudly instead
            raise EngineStopped("engine is not running (call start())")
        if batch:
            # trough-filler admission: batch enters only while
            # interactive occupancy and SLO burn leave headroom — the
            # 429 tells the job manager to wait the burst out, not to
            # compete with it.  (Queued batch that was admitted before
            # a burst is handled by _admit's gate + preemption.)
            open_, why = self.trough_open()
            if not open_:
                self._count_shed(priority)
                self._m_requests.labels(outcome="429").inc()
                # short re-probe hint, NOT _retry_after(): the trough
                # reopens as soon as a slot frees (milliseconds), so
                # the congestion-derived >=1s interactive hint would
                # park the job manager past every trough worth filling
                raise EngineOverloaded(
                    f"batch trough closed: {why}",
                    float(root.common.serve.jobs.get(
                        "trough_retry_s", 0.05)))
        req = _Request(
            prompt, n_steps, float(temperature),
            None if top_k is None else int(top_k),
            None if top_p is None else float(top_p),
            None if eos_id is None else int(eos_id),
            np.asarray(jax.random.key_data(key)),
            time.monotonic() + (self.deadline_s if deadline_s is None
                                else float(deadline_s)),
            priority=priority, batch=batch)
        if pref is not None and pref.size:
            # the resume form IS the preemption harvest/resume state:
            # admission prefills prompt + prefix and decode continues
            # from the global position the interrupted run reached
            req.gen = pref
        req.stop_seqs = stop_seqs
        if stream:
            h = self._acquire_stream(req)
            req.stream = h
        if self.paged:
            # pool backpressure: when slots are free but the PAGES are
            # gone (long prompts at low slot occupancy), admission could
            # not happen anyway — answer the same 429/Retry-After as a
            # full queue instead of parking work a free slot cannot
            # serve.  A busy slot table falls through to the queue
            # check: pages drain as slots retire, so queued waiting is
            # the normal path there.  Prefix-cache hits are discounted
            # from the need: a request whose system prompt is already
            # resident only allocates its tail — the hot-shared-prefix
            # workload must not be the one spuriously rejected.
            # a resume submit sizes/hashes its EFFECTIVE prompt
            # (prompt + emitted prefix) — the same total span the
            # uninterrupted run held, with the prefix-covered pages
            # eligible for cache hits
            eff = req.effective_prompt()
            need = self._page_span(eff.size, req.end_index
                                   - int(eff.size) + 1)
            hashes = self._prefix_hashes(eff)
            req.page_hashes = hashes    # _reserve_pages reuses them
            with self._page_lock:
                need -= self._prefix_hits_locked(hashes, eff.size)
                avail = self.pages - int(
                    np.count_nonzero(self._page_ref))
            with self._qlock:
                free_slots = self.slots - int(self._active.sum())
                pool_bound = (need > avail
                              and free_slots > len(self._queue))
                if pool_bound and self.preempt and any(
                        r is not None and r.priority > priority
                        for r in self._slot_req):
                    # a strictly-lower-class slot is running: the
                    # scheduler may preempt it to free its pages, so
                    # queueing is the right answer, not a 429 (the read
                    # is advisory — a stale view only costs one queued
                    # wait bounded by the deadline)
                    pool_bound = False
            if pool_bound:
                with self._page_lock:
                    self._pool_rejected += 1
                self._count_shed(priority)
                self._m_requests.labels(outcome="429").inc()
                self._release_stream(req, "429")
                raise EngineOverloaded(
                    f"page pool exhausted ({avail} of {self.pages} "
                    f"pages free, request needs {need} beyond its "
                    "cached prefix)", self._retry_after())
        evicted = None
        with self._qlock:
            # admission decided under the lock; the 429 (which computes
            # Retry-After by re-taking the lock) raises outside it.
            # The controller's window (priority-scaled) bounds what the
            # hard queue_depth used to bound alone: under a sustained
            # SLO burn low classes shed first, then everyone.
            qlen = len(self._queue)
            # batch bypasses the AIMD window (the trough gate above is
            # its admission control) but never the hard queue depth;
            # it also cannot displace anyone — no class sits below it
            limit = (self.queue_depth if batch
                     else min(self.queue_depth,
                              self._admission.allowance(priority)))
            overloaded = qlen >= limit
            if overloaded:
                # full — hard depth or a burn-closed admission window —
                # a higher-class arrival may displace the youngest
                # queued request of a strictly lower class.  Without
                # this the window case would invert the priority
                # contract: a mid-class arrival 429s while
                # strictly-lower-class requests admitted just before
                # the window closed keep their spots.  Under ANY shed
                # the low classes go first, not whoever arrived later;
                # total queue length never grows (one out, one in).
                evicted = self._queue.steal_lower(priority)
                if evicted is not None:
                    self._queue.append(req)
                    overloaded = False
            if not overloaded and evicted is None:
                self._queue.append(req)
        if evicted is not None:
            retry = self._retry_after()
            self._count_shed(evicted.priority)
            # _observe_finish below lands the vt_requests_total 429
            evicted.finish(error=EngineOverloaded(
                "shed from a full queue by a higher-priority arrival",
                retry))
            self._observe_finish(evicted, "429")
        if overloaded:
            self._count_shed(priority)
            self._m_requests.labels(outcome="429").inc()
            self._release_stream(req, "429")
            raise EngineOverloaded(
                f"admission window full ({qlen} pending, window "
                f"{limit} for class {priority} of "
                f"{self.queue_depth} hard depth)", self._retry_after())
        self._wake.set()
        return req

    def generate(self, prompt, n_steps: int, *, temperature: float = 0.0,
                 top_k=None, top_p=None, eos_id=None, key=None,
                 timeout: Optional[float] = None, priority: int = 0,
                 batch: bool = False):
        """Blocking batch decode with the ``generate()`` contract:
        (B, P) int32 -> (B, P + n_steps) int32, rows past their eos
        padded with ``eos_id``.  Each row rides its own slot; row ``r``
        of a multi-row sampled request draws from ``fold_in(key, r)``
        (single-row requests use ``key`` itself, bitwise-matching
        ``generate()``).  ``priority`` is the request class
        (:meth:`submit`)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 2:
            raise ValueError("prompt must be (B, P)")
        B, P = prompt.shape
        if key is None:
            key = jax.random.key(0)
        reqs = []
        try:
            for r in range(B):
                rk = key if B == 1 else jax.random.fold_in(key, r)
                reqs.append(self.submit(
                    prompt[r], n_steps, temperature=temperature,
                    top_k=top_k, top_p=top_p, eos_id=eos_id, key=rk,
                    priority=priority, batch=batch))
            out = np.full((B, P + n_steps),
                          eos_id if eos_id is not None else 0, np.int32)
            for r, req in enumerate(reqs):
                if not req.done.wait(timeout):
                    raise TimeoutError("engine.generate timed out")
                if req.error is not None:
                    raise req.error
                out[r, :len(req.result)] = req.result
            return out
        except BaseException:
            # don't leak the batch's other rows: a mid-batch overflow
            # (or timeout) must not leave already-submitted rows
            # decoding to discarded results while the client retries —
            # expiring their deadline makes the scheduler drop queued
            # ones and retire in-flight ones on the next step
            for req in reqs:
                if not req.done.is_set():
                    req.deadline = 0.0
            raise

    def _pages_summary(self) -> Optional[dict]:
        """One consistent snapshot of the pool: refcounts, the prefix
        index AND the derived numbers under the same lock hold
        (used/cached and hit counters torn across a concurrent admission
        used to disagree — veles-tpu-lint VC201); None when dense."""
        if not self.paged:
            return None
        with self._page_lock:
            used = int(np.count_nonzero(self._page_ref))
            cached = sum(1 for pid in self._page_key
                         if self._page_ref[pid] == 0)
            hit = self._prefix_hit_pages
            miss = self._prefix_miss_pages
            evictions = self._evictions
            cow = self._cow_admissions
            pool_rejected = self._pool_rejected
        lookups = hit + miss
        return {
            "page_size": self.page_size, "pages": self.pages,
            "used": used, "cached": cached,
            "free": self.pages - used - cached,
            "tokens_resident": (used + cached) * self.page_size,
            "prefix_hit_pages": hit,
            "prefix_miss_pages": miss,
            "prefix_hit_rate": round(hit / lookups, 3) if lookups
            else 0.0,
            "prefix_tokens_reused": hit * self.page_size,
            "evictions": evictions,
            "cow_admissions": cow,
            "pool_rejected": pool_rejected,
        }

    def _goodput_summary(self) -> dict:
        """Decode goodput: achieved memory traffic per second against
        the configured HBM peak (model-bandwidth-utilization — the
        honesty check decode perf claims are scored by) and tokens/s
        normalized per local device."""
        ewma = self._step_wall_ewma
        # an idle engine streams nothing: freeze-free gauges report 0
        # once no decode OR verify step ran for a couple of seconds,
        # instead of showing the last load's bandwidth forever
        idle = (self._last_step_at <= 0
                or time.monotonic() - self._last_step_at > 2.0)
        bw = self._bw_ewma if self._bw_ewma > 0 and not idle else 0.0
        peak_gbps = float(
            root.common.observe.get("peak_hbm_gbps", 0.0) or 0.0)
        mbu = bw / (peak_gbps * 1e9) if peak_gbps > 0 else 0.0
        try:
            chips = max(jax.local_device_count(), 1)
        except Exception:
            chips = 1
        return {
            "decode_step_flops": self._decode_flops,
            "decode_step_bytes": self._decode_bytes,
            "decode_step_wall_ewma_s": round(ewma, 6),
            "decode_bandwidth_bytes_per_sec": round(bw, 1),
            "decode_mbu": round(mbu, 5),
            "tokens_per_sec_per_chip": round(
                self._tokens_per_sec / chips, 2),
            # windowed speculative accept rate next to the other
            # throughput-honesty numbers (0.0 when spec is off or idle)
            "spec_accept_rate": round(self._spec_accept_rate, 4),
        }

    def _headroom_slots(self, pages: Optional[dict]) -> int:
        """Max-length requests admissible right now: free slots, further
        bounded (paged) by how many max-length page spans the pool still
        holds — cached refcount-0 pages count as available because the
        allocator evicts them on demand."""
        free_slots = self.slots - int(self._active.sum())
        if pages is None:
            return max(free_slots, 0)
        avail = pages["free"] + pages["cached"]
        return max(min(free_slots, avail // max(self.n_ptab, 1)), 0)

    def trough_open(self) -> Tuple[bool, str]:
        """Batch-lane admission sensor (docs/serving.md "Batch lane"):
        batch work enters only while BOTH hold — interactive occupancy
        leaves at least ``serve.jobs.min_headroom_slots`` admissible
        slots (the vt_memory_headroom_slots signal) and the windowed
        SLO burn sits at or under ``serve.jobs.burn_ceiling`` (below
        the interactive controller's own shed threshold, so batch
        yields BEFORE interactive classes start paying).  Returns
        ``(open, reason)`` — the reason lands in the 429 body."""
        jobs_cfg = root.common.serve.jobs
        min_headroom = int(jobs_cfg.get("min_headroom_slots", 1))
        burn_ceiling = float(jobs_cfg.get("burn_ceiling", 1.0))
        headroom = self._headroom_slots(self._pages_summary())
        return self._trough_open_for(headroom, min_headroom,
                                     burn_ceiling)

    def _trough_open_for(self, headroom: int,
                         min_headroom: Optional[int] = None,
                         burn_ceiling: Optional[float] = None
                         ) -> Tuple[bool, str]:
        """The gate itself, on an already-computed headroom sample (the
        scheduler's _admit re-checks per tick without re-walking the
        pool)."""
        jobs_cfg = root.common.serve.jobs
        if min_headroom is None:
            min_headroom = int(jobs_cfg.get("min_headroom_slots", 1))
        if burn_ceiling is None:
            burn_ceiling = float(jobs_cfg.get("burn_ceiling", 1.0))
        if headroom < min_headroom:
            return False, (f"headroom {headroom} slots < "
                           f"serve.jobs.min_headroom_slots "
                           f"{min_headroom}")
        burn = self._admission.last_burn()
        if burn > burn_ceiling:
            return False, (f"SLO burn {burn:.2f} > "
                           f"serve.jobs.burn_ceiling {burn_ceiling}")
        return True, "ok"

    def _publish_gauges(self) -> dict:
        """Sample the point-in-time gauges (occupancy, queue depth,
        throughput, pool, goodput, memory headroom) into the registry
        and return the one consistent snapshot stats() renders.  Called
        by the scheduler's 0.5s status tick — a bare ``GET /metrics``
        scrape is never stale just because nothing polled ``/engine``
        — and from :meth:`stats`; NOT per decode step: the pool summary
        costs an O(pages) pass under ``_page_lock`` and scrape
        consumers read at ≥1s granularity anyway."""
        now = time.monotonic()
        mark_t, mark_n = self._rate_mark
        if now - mark_t >= 0.5:
            self._tokens_per_sec = ((self._tok_count.n - mark_n)
                                    / max(now - mark_t, 1e-9))
            self._rate_mark = (now, self._tok_count.n)
        b_t, b_n = self._batch_rate_mark
        if now - b_t >= 0.5:
            self._batch_tok_s = ((self._batch_tok_n - b_n)
                                 / max(now - b_t, 1e-9))
            self._batch_rate_mark = (now, self._batch_tok_n)
        s_t, s_prop, s_acc = self._spec_rate_mark
        if now - s_t >= 0.5:
            d_prop = self._spec_proposed.n - s_prop
            d_acc = self._spec_accepted.n - s_acc
            self._spec_accept_rate = d_acc / d_prop if d_prop else 0.0
            self._spec_rate_mark = (now, self._spec_proposed.n,
                                    self._spec_accepted.n)
        pages = self._pages_summary()
        with self._qlock:
            queue_depth = len(self._queue)
        occupancy = int(self._active.sum())
        good = self._goodput_summary()
        headroom = self._headroom_slots(pages)
        self._g_occupancy.set(occupancy)
        self._g_queue_depth.set(queue_depth)
        self._g_tokens_per_sec.set(self._tokens_per_sec)
        self._g_batch_tps.set(self._batch_tok_s)
        self._g_headroom.set(headroom)
        self._g_spec_accept_rate.set(self._spec_accept_rate)
        self._g_decode_bw.set(good["decode_bandwidth_bytes_per_sec"])
        self._g_decode_mbu.set(good["decode_mbu"])
        self._g_tps_chip.set(good["tokens_per_sec_per_chip"])
        if pages is not None:
            self._g_pages_used.set(pages["used"])
            self._g_pages_cached.set(pages["cached"])
            self._g_pages_free.set(pages["free"])
            self._g_prefix_hit_rate.set(pages["prefix_hit_rate"])
        return {"pages": pages, "queue_depth": queue_depth,
                "occupancy": occupancy, "goodput": good,
                "headroom_slots": headroom}

    def stats(self) -> dict:
        """JSON-able gauges for status pages / benches.  The counters
        are ScopedCounter views over the metrics registry, so the same
        increments back this dict, status.json, GET /engine and GET
        /metrics; the sampled gauges (occupancy / queue depth /
        throughput / goodput / headroom) are published to the registry
        here AND on the scheduler's 0.5s tick (:meth:`_publish_gauges`
        — one sample backs both the gauges and this dict)."""
        snap = self._publish_gauges()
        pages = snap["pages"]
        steps = max(self._decode_steps.n, 1)
        queue_depth = snap["queue_depth"]
        occupancy = snap["occupancy"]
        return {
            "slots": self.slots, "l_max": self.l_max,
            "paged": self.paged,
            **({"pages": pages} if pages is not None else {}),
            "occupancy": occupancy,
            "avg_occupancy": round(self._occupancy_sum / steps, 3),
            "queue_depth": queue_depth,
            "queue_limit": self.queue_depth,
            "tokens_per_sec": round(self._tokens_per_sec, 1),
            "tokens_generated": self._tok_count.n,
            "decode_steps": self._decode_steps.n,
            "dispatches": self._dispatches.n,
            "admitted": self._admitted.n, "retired": self._retired.n,
            "rejected": self._rejected.n, "timeouts": self._timeouts.n,
            "swaps": self._swaps, "draining": self._draining,
            "scheduler_crashed": self._died,
            # overload survival (docs/serving.md "Overload survival"):
            # the controller's live window, preemption volume, and shed
            # counts by request class
            "admission": {
                **self._admission.state(),
                "priorities": self.priorities,
                "preempt": self.preempt,
                "prefill_chunk": self.prefill_chunk,
                "preemptions": self._preempted.n,
                "shed_by_class": self._shed_snapshot(),
            },
            "compile": self.step_cache.stats(),
            **({"spec": {
                "k": self.spec_k, "drafter": self.spec_drafter,
                "proposed": self._spec_proposed.n,
                "accepted": self._spec_accepted.n,
                "verify_steps": self._verify_steps,
                "accept_rate": round(
                    self._spec_accepted.n
                    / max(self._spec_proposed.n, 1), 4),
            }} if self.spec else {}),
            **({"megastep": {
                "n": self.megastep,
                "mega_dispatches": self._mega_steps,
            }} if self.megastep > 1 else {}),
            **({"kv_transfer": kvt}
               if (kvt := self._kv_transfer_summary()) is not None
               else {}),
            # batch lane (docs/serving.md "Batch lane"): whether the
            # trough gate would admit right now, and the throughput
            # the SLO histograms deliberately never see
            "batch": {
                "trough_open": self._trough_open_for(
                    snap["headroom_slots"])[0],
                "tokens_generated": self._batch_tok_n,
                "tokens_per_sec": round(self._batch_tok_s, 1),
                "preemptions": self._batch_preempted.n,
            },
            "goodput": snap["goodput"],
            "memory": {
                "headroom_slots": snap["headroom_slots"],
                **self._mem_bytes,          # THIS engine's bytes, not
            },                              # the process ledger's
        }

    def _count_shed(self, priority: int):
        """One shed, every ledger in lockstep: the engine's rejected
        counter, the per-class stats snapshot, and the
        ``vt_shed_total`` series — the three paths that shed (pool
        429, admission-window 429, hard-full displacement) must never
        drift apart on these.  ``vt_requests_total{outcome="429"}`` is
        deliberately NOT counted here: displacement routes it through
        ``_observe_finish`` (the request finishes), the raise paths
        count it at the raise site (no request object ever finishes).
        Takes ``_qlock`` — call outside it."""
        self._rejected.inc()
        self._m_shed.labels(priority=str(priority)).inc()
        with self._qlock:
            self._shed_by_class[priority] = \
                self._shed_by_class.get(priority, 0) + 1

    def _shed_snapshot(self) -> dict:
        """Per-class shed counts as a JSON-able dict (one consistent
        copy under the queue lock)."""
        with self._qlock:
            return {str(k): v
                    for k, v in sorted(self._shed_by_class.items())}

    # -- scheduler ----------------------------------------------------------
    def _retry_after(self) -> float:
        """429 Retry-After estimate, derived from actual congestion so
        clients back off proportionally (the honest-shedding half of
        the overload contract): queued decode work over recent
        throughput, floored by the queue-wait EWMA current admissions
        are really paying, scaled by how far the admission controller
        has closed the window (a half-closed window doubles the hint).
        Bounded to [1, 60] seconds.  Takes the queue lock itself —
        callers raise their 429 AFTER releasing it (iterating the
        queue while submit threads append was a mutation-during-
        iteration crash waiting for load; veles-tpu-lint VC201)."""
        with self._qlock:
            queued = sum(r.n_steps for r in self._queue) or 1
        rate = max(self._tokens_per_sec, 1.0)
        est = max(queued / rate, self._qwait_ewma)
        est *= self._admission.backoff_factor()
        return min(60.0, max(1.0, est))

    def _loop(self):
        from . import faults
        try:
            while not self._stop_evt.is_set():
                self._maybe_report()
                if faults.enabled():
                    plan = faults.get_plan()
                    if plan.admission_burst \
                            and faults.fire_once("admission_burst"):
                        # synthetic queue flood (runtime/faults.py):
                        # the controller-shed rehearsal's backlog
                        self._inject_burst(int(plan.admission_burst))
                    # lint: disable=VC201 bool(deque) is atomic under
                    # the GIL; a stale wakeup read only costs one 50ms
                    # tick
                    if (self._queue or self._active.any()) \
                            and plan.scheduler_crash \
                            and faults.fire_once("scheduler_crash"):
                        # injected crash point (tests/test_faults.py):
                        # fire only with work pending so the crash
                        # exercises the fail-all path, once per arming
                        raise faults.FaultInjected(
                            "injected decode-scheduler crash")
                # decode-step boundary: no program is running right now,
                # so a staged weight swap flips here atomically — and
                # staged KV-page imports land on the same boundary (the
                # scheduler thread owns every _caches write)
                self._apply_swap()
                self._apply_kv_imports()
                # lint: disable=VC201 bool(deque) is atomic under the
                # GIL; a stale wakeup read only costs one 50ms tick
                if not self._active.any() and not self._queue \
                        and not self._chunking:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                if not self._active.any() and not self._chunking \
                        and self.window_s > 0:
                    # batching window: concurrent arrivals get admitted
                    # together and share the first decode steps instead
                    # of the first request racing its slot ahead
                    time.sleep(self.window_s)
                self._expire_queue()
                self._admit()  # mid-flight too: no drain barrier
                # chunked prefill: ONE bounded slice per mid-prefill
                # slot per iteration, so a long prompt and the decode
                # step below take turns instead of the prompt
                # monopolizing the scheduler for its whole length
                self._advance_prefills()
                if self._active.any():
                    self._advance_once()
                self._maybe_report()
        except Exception as e:  # noqa: BLE001 — a dead scheduler must
            # fail pending work loudly, not hang every client forever
            self._died = True
            self.exception("decode engine scheduler died")
            if self.status is not None:
                try:
                    self.status.record_event(
                        "scheduler_crash",
                        error=f"{type(e).__name__}: {e}")
                except Exception:  # status must never mask the crash
                    pass
            # queued AND mid-flight requests all fail with the same
            # clearly-typed error (HTTP 500 in restful.py, not the 503
            # a drain answers) naming the original exception
            self._fail_all(SchedulerCrashed(
                f"engine scheduler crashed: {type(e).__name__}: {e}"))
        finally:
            # a swap staged during shutdown still flips (harmless) so
            # its waiter is released instead of blocking to timeout;
            # staged KV imports drain for the same reason
            self._apply_swap()
            self._apply_kv_imports()
            self._fail_all(EngineStopped("engine stopped"))

    def _fail_all(self, err: Exception):
        outcome = "crash" if isinstance(err, SchedulerCrashed) \
            else "stopped"
        with self._qlock:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            req.finish(error=err)
            self._observe_finish(req, outcome)
        for s, req in enumerate(self._slot_req):
            if req is not None:
                req.finish(error=err)
                self._slot_req[s] = None
                self._observe_finish(req, outcome)
            self._release_slot_pages(s)
        self._chunking.clear()
        self._active[:] = False

    def _expire_queue(self):
        """Fail queued requests whose deadline passed while they waited
        behind a full slot set (they'd otherwise only be checked when a
        slot freed)."""
        now = time.monotonic()
        expired = []
        with self._qlock:
            if self._queue and any(now > r.deadline
                                   for r in self._queue):
                expired = self._queue.remove_if(
                    lambda r: now > r.deadline)
        for r in expired:
            self._timeouts.inc()
            r.finish(error=TimeoutError(
                "request deadline expired while queued"))
            self._observe_finish(r, "504")

    def _free_slot(self) -> Optional[int]:
        """A slot that is neither decoding nor mid-(chunked-)prefill —
        ``_slot_req`` is the occupancy truth; ``_active`` alone would
        hand a chunking slot to a second request."""
        for s in range(self.slots):
            if not self._active[s] and self._slot_req[s] is None:
                return s
        return None

    def _pick_victim(self, priority: int) -> Optional[int]:
        """Preemption victim for an arrival of class ``priority``: the
        occupied slot of the LOWEST class strictly below it (largest
        class index), youngest run among ties (latest admission — the
        one losing the least progress).  None when preemption is off or
        nothing strictly lower is running."""
        if not self.preempt:
            return None
        best, best_key = None, None
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None or req.priority <= priority:
                continue
            k = (req.priority, req.run_started_at or 0.0)
            if best is None or k > best_key:
                best, best_key = s, k
        return best

    def _preempt_can_free(self, req) -> bool:
        """Upper-bound feasibility of preempting for pages: could
        evicting EVERY strictly-lower-class slot possibly free enough
        pages for ``req``?  A victim's distinct mapped pages bound
        what its release can return (shared-prefix pages stay
        referenced elsewhere), so False means preemption can never
        satisfy the need — requeue instead of futilely mass-evicting
        victims that each lose their progress.  Scheduler thread only
        (``_ptab``/``_slot_req`` are its state)."""
        eff = req.effective_prompt()
        P = int(eff.size)
        need = self._page_span(P, req.end_index - P + 1)
        hashes = req.page_hashes or self._prefix_hashes(eff)
        reclaimable = set()
        for s in range(self.slots):
            r = self._slot_req[s]
            if r is not None and r.priority > req.priority:
                reclaimable.update(
                    int(p) for p in np.unique(self._ptab[s])
                    if p != self._scratch)
        with self._page_lock:
            need -= self._prefix_hits_locked(hashes, P)
            avail = self.pages - int(np.count_nonzero(self._page_ref))
        return need <= avail + len(reclaimable)

    def _preempt(self, slot: int):
        """Retire-and-requeue the slot so a higher-priority request can
        take its place: harvest the tokens generated so far into
        ``req.gen`` (a later resume re-prefills prompt + gen, so the
        final stream is bitwise an uninterrupted run — the prefill's
        sampling-key folds are global-position), release the refcounted
        KV pages, and put the victim back at the FRONT of its own
        class.  Scheduler thread only."""
        req = self._slot_req[slot]
        if self._active[slot]:
            eff_len = int(req.prompt.size) + int(req.gen.size)
            pos = int(self._pos[slot])
            fresh = np.asarray(self._toks[slot, eff_len:pos + 1],
                               np.int32)
            if fresh.size:
                req.gen = np.concatenate([req.gen, fresh])
        self._active[slot] = False
        self._chunking.discard(slot)
        self._slot_req[slot] = None
        self._release_slot_pages(slot)
        req.slot = None
        req.page_row = None
        req.prefix_start = 0
        req.page_hashes = ()
        req.chunk_next = 0
        req._eff = None                 # prompt grew by the harvest
        req.preemptions += 1
        self._preempted.inc()
        if req.batch:
            # the batch lane yielding to interactive traffic — the
            # instant-yield half of the trough-filler contract
            self._batch_preempted.inc()
        with self._qlock:
            self._queue.appendleft(req)

    def _admit(self) -> int:
        """Move queued requests into free slots (prefill); returns the
        number admitted.  Runs on the scheduler thread only.  When the
        head of the queue outranks a running slot and no capacity is
        free — slots, or pages under the paged layout — the scheduler
        may preempt (docs/serving.md "Overload survival")."""
        n = 0
        while True:
            with self._qlock:
                req = self._queue.popleft()
            if req is None:
                return n
            now = time.monotonic()
            if now > req.deadline:
                self._timeouts.inc()
                req.finish(error=TimeoutError(
                    "request deadline expired while queued"))
                self._observe_finish(req, "504")
                continue
            if req.batch:
                # trough gate, re-checked at admission time: batch that
                # queued during a lull must keep waiting when a burst
                # arrived in between.  Batch is the LOWEST class, so
                # popleft only surfaces it once no interactive request
                # is queued — requeue-at-front and stop admitting.
                open_, _why = self.trough_open()
                if not open_:
                    with self._qlock:
                        self._queue.appendleft(req)
                    return n
            slot = self._free_slot()
            if slot is None:
                victim = self._pick_victim(req.priority)
                if victim is None or (
                        self.paged and not self._preempt_can_free(req)):
                    # no capacity and nothing preemptible — or, on the
                    # paged layout, preemption could free the SLOT but
                    # provably never enough PAGES (the same feasibility
                    # bound the page-reservation loop below applies): a
                    # victim evicted here would lose its progress to a
                    # full re-prefill for an admission that still
                    # cannot happen.  Requeue at the FRONT of its class
                    # and stop admitting.
                    with self._qlock:
                        self._queue.appendleft(req)
                    return n
                self._preempt(victim)
                slot = self._free_slot()
            ok = True
            while self.paged and not self._reserve_pages(req):
                # the pool cannot host it right now: preempt a strictly
                # lower class to free its pages, else requeue — pages
                # free as slots retire, deadlines bound the wait.  But
                # only preempt when preemption can plausibly SATISFY
                # the need: mass-evicting every lower slot (each losing
                # its progress to a full re-prefill) for a request the
                # pool still cannot host would be pure waste
                victim = self._pick_victim(req.priority)
                if victim is None or not self._preempt_can_free(req):
                    with self._qlock:
                        self._queue.appendleft(req)
                    ok = False
                    break
                self._preempt(victim)
            if not ok:
                return n
            self._prefill(int(slot), req)
            n += 1

    def _inject_burst(self, n: int):
        """``faults.admission_burst``: append ``n`` synthetic minimal
        lowest-class requests straight to the queue — deliberately
        bypassing submit()'s shed gate, because the rehearsal is "the
        backlog already exists; prove the controller sheds and
        re-opens" (tests/test_chaos.py).  Nobody waits on their done
        events; they decode and retire like any request."""
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        for _ in range(int(n)):
            r = _Request(np.asarray([0], np.int32), 2, 0.0, None, None,
                         None, kd, time.monotonic() + self.deadline_s,
                         priority=self.priorities - 1)
            with self._qlock:
                self._queue.append(r)

    # -- page pool (scheduler thread owns mutation; _page_lock guards the
    # cross-thread reads in submit() and stats()) ---------------------------
    def _touch(self, pid: int):  # requires-lock: self._page_lock
        self._tick += 1
        self._page_tick[pid] = self._tick

    def _page_span(self, P: int, n_steps: int) -> int:
        """Worst-case pages a request can ever reference: KV lands at
        positions ``0 .. P + n_steps - 2`` (the final sampled token is
        emitted but its KV is never computed — the slot retires at
        ``end = P + n_steps - 1``), so the span is one cell SHORT of
        the token count; counting the full count would strand a page
        per request whenever the true span is page-aligned."""
        return -(-(P + n_steps - 1) // self.page_size)

    def _prefix_hashes(self, prompt):
        """Chained content hashes of the prompt's FULL pages
        (:func:`prefix_page_hashes` — shared with the fleet router's
        affinity dispatch so both sides key the same bytes)."""
        if not self._prefix_ok:
            return []
        return prefix_page_hashes(prompt, self.page_size)

    def _prefix_hits_locked(self, hashes, P: int) -> int:  # requires-lock: self._page_lock
        """Leading pages already in the prefix index (caller holds
        ``_page_lock``), capped so at least the LAST prompt token is
        recomputed: the first sampled token needs its logits, and a
        fully-shared prompt would otherwise have nothing to run."""
        hits = 0
        for h in hashes:
            if h not in self._prefix_index:
                break
            hits += 1
        while hits and hits * self.page_size > P - 1:
            hits -= 1
        return hits

    def _reserve_pages(self, req) -> bool:
        """Map the request onto the pool: chained-hash prefix lookup over
        its full prompt pages (hits map shared read-only pages,
        refcount++), fresh pages for the rest of its worst-case span.
        On success ``req.page_row`` / ``req.prefix_start`` /
        ``req.page_hashes`` are set; on shortage every side effect is
        rolled back and False is returned (the caller requeues).  A
        preemption resume reserves for its EFFECTIVE prompt (original +
        generated-so-far) and the steps still owed — the same total
        span the uninterrupted run held."""
        psz = self.page_size
        eff = req.effective_prompt()
        P = int(eff.size)
        need = self._page_span(P, req.end_index - P + 1)
        full = P // psz                          # whole-prompt pages
        # submit() already hashed the prompt; () is also legitimate
        # (short prompt / prefix reuse off / a preemption resume, whose
        # effective prompt grew) and free to recompute
        hashes = req.page_hashes or self._prefix_hashes(eff)
        with self._page_lock:
            hits = self._prefix_hits_locked(hashes, P)
            row = np.full(self.n_ptab, self._scratch, np.int32)
            taken = []
            remote = 0
            for i in range(hits):
                pid = self._prefix_index[hashes[i]]
                self._page_ref[pid] += 1
                self._touch(pid)
                row[i] = pid
                taken.append(pid)
                if pid in self._imported_pages:
                    remote += 1
            for i in range(hits, need):
                pid = self._alloc_page_locked()
                if pid is None:          # shortage: roll back, requeue
                    for p in taken:
                        self._page_ref[p] -= 1
                        if self._page_ref[p] <= 0:
                            self._page_ref[p] = 0
                            if p not in self._page_key:
                                self._page_free.append(p)
                    return False
                row[i] = pid
                taken.append(pid)
            self._prefix_hit_pages += hits
            self._prefix_miss_pages += max(full - hits, 0)
            if remote:
                # the hit landed on pages a peer prefilled and shipped
                # over (import_pages) — the fleet-wide prefix-sharing
                # payoff signal (vt_prefix_remote_hits_total)
                self._remote_hit_pages += remote
                self._m_remote_hits.inc(remote)
            if hits:
                # copy-on-write admission: a shared prefix was mapped
                # read-only and the first divergent token onward is
                # recomputed into private pages
                self._cow_admissions += 1
        req.page_row = row
        req.prefix_start = hits * psz
        req.page_hashes = hashes
        return True

    def _alloc_page_locked(self):  # requires-lock: self._page_lock
        """One free page, evicting the least-recently-used CACHED page
        (refcount 0 but still registered in the prefix index) when the
        free list is empty; None when the pool is truly exhausted."""
        if self._page_free:
            pid = self._page_free.pop()
            self._page_ref[pid] = 1
            self._imported_pages.discard(pid)
            self._touch(pid)
            return pid
        best, best_tick = None, None
        for pid in self._page_key:
            if self._page_ref[pid] == 0 and (
                    best is None or self._page_tick[pid] < best_tick):
                best, best_tick = pid, self._page_tick[pid]
        if best is None:
            return None
        del self._prefix_index[self._page_key.pop(best)]
        self._evictions += 1
        self._page_ref[best] = 1
        self._imported_pages.discard(best)
        self._touch(best)
        return best

    def _register_prefix_pages(self, req):
        """After a prefill: publish the request's freshly computed FULL
        prompt pages in the prefix index so the next request sharing the
        prefix prefills only its tail.  Pages holding the prompt's
        partial tail or generated tokens stay private (their content is
        not a pure function of a whole-page prompt prefix)."""
        psz = self.page_size
        full = int(req.effective_prompt().size) // psz
        hits = req.prefix_start // psz
        with self._page_lock:
            for i in range(hits, min(full, len(req.page_hashes))):
                h = req.page_hashes[i]
                pid = int(req.page_row[i])
                if h not in self._prefix_index:
                    self._prefix_index[h] = pid
                    self._page_key[pid] = h
                self._touch(pid)

    def _release_slot_pages(self, slot: int):
        """Drop the slot's references; refcount-0 pages return to the
        free list unless the prefix index still caches them (a cached
        page stays resident, serving future prefix hits, until LRU
        eviction reclaims it)."""
        if not self.paged:
            return
        with self._page_lock:
            for pid in self._ptab[slot]:
                pid = int(pid)
                if pid == self._scratch:
                    continue
                self._page_ref[pid] -= 1
                if self._page_ref[pid] <= 0:
                    self._page_ref[pid] = 0
                    if pid not in self._page_key:
                        self._page_free.append(pid)
            self._ptab[slot] = self._scratch

    # -- KV-page transfer: serialized prefix-page export/import across
    # replicas (docs/serving.md "Disaggregated prefill/decode").  The
    # wire format is magic + length-prefixed JSON header (page_size,
    # weights version, per-entry dtype/shape layout, per-page integrity
    # sha256) + concatenated raw page rows; pages are keyed by the same
    # chained content hashes the prefix index uses, so an imported page
    # is bitwise the page a local prefill would have computed. ---------

    def _require_transfer(self):
        """KV-page transfer needs content-addressed pages: dense caches
        and recurrent chains reject LOUDLY (the REST layer's 400) —
        shipping rows whose content is not a pure function of a prompt
        prefix would silently corrupt the importer's decode."""
        if not self.paged:
            raise ValueError(
                "KV-page transfer requires the paged KV layout "
                "(serve.paged=True); dense caches have no "
                "content-addressed pages to ship")
        if not self._prefix_ok:
            raise ValueError(
                "KV-page transfer requires prefix reuse, which "
                "recurrent units disable (their cache content is not a "
                "pure function of a whole-page prompt prefix)")

    @property
    def kv_wver(self) -> str:
        """Weights-version token stamped into every exported blob: the
        parameter-tree signature hash joined with the hot-swap counter.
        Import refuses a mismatch — pages computed under other weights
        must never enter the prefix index (the same staleness rule that
        makes :meth:`_apply_swap` invalidate the local cache)."""
        return f"{self._kv_sig}.{self._swaps}"

    def _kv_xfer_entries(self) -> list:
        """Per-entry wire layout ``(name, part, dtype, row_shape)`` over
        the attention caches, in deterministic order — the header both
        sides must agree on byte for byte."""
        if self._kv_entry_cache is None:
            ents = []
            for name in sorted(self._attn_cache_keys()):
                for part in ("k", "v"):
                    arr = self._caches[name][part]
                    ents.append((name, part, str(np.dtype(arr.dtype)),
                                 tuple(int(d) for d in arr.shape[1:])))
            self._kv_entry_cache = ents
        return self._kv_entry_cache

    def _kv_page_bytes(self) -> int:
        """Wire payload bytes of ONE page (all cache entries)."""
        return sum(int(np.dtype(dt).itemsize) * int(np.prod(shape))
                   for _n, _p, dt, shape in self._kv_xfer_entries())

    @staticmethod
    def _norm_hash(h) -> bytes:
        """Page hashes are raw sha256 digests internally; the wire and
        query-string forms are hex."""
        return bytes.fromhex(h) if isinstance(h, str) else bytes(h)

    def hot_page_hashes(self, k: int) -> list:
        """The K hottest cached prefix pages (refcount desc, then LRU
        recency) as raw digests — the rolling drain's pre-warm set.
        Pages ship independently, so a truncated chain still serves
        hits up to its first missing page."""
        self._require_transfer()
        with self._page_lock:
            ranked = sorted(
                self._page_key.items(),
                key=lambda it: (int(self._page_ref[it[0]]),
                                int(self._page_tick[it[0]])),
                reverse=True)
            return [h for _pid, h in ranked[:max(int(k), 0)]]

    def export_pages(self, prefix_hashes) -> bytes:
        """Serialize the requested prefix pages (those present; unknown
        hashes are silently omitted) into the transfer wire format.
        Requested pages are pinned (refcount++) for the gather so
        eviction cannot recycle a row mid-read — registered pages are
        written only by their original prefill, so the pinned rows are
        immutable."""
        self._require_transfer()
        t0 = time.monotonic()
        pinned = []
        with self._page_lock:
            seen = set()
            for h in prefix_hashes:
                h = self._norm_hash(h)
                pid = self._prefix_index.get(h)
                if pid is None or h in seen:
                    continue
                seen.add(h)
                self._page_ref[pid] += 1
                self._touch(pid)
                pinned.append((h, pid))
        try:
            entries = self._kv_xfer_entries()
            caches = self._caches
            rows = []
            if pinned:
                pids = np.asarray([pid for _h, pid in pinned], np.int32)
                rows = [np.asarray(caches[name][part][pids])
                        for name, part, _dt, _shape in entries]
            pages = []
            payload = bytearray()
            for i, (h, _pid) in enumerate(pinned):
                page = b"".join(np.ascontiguousarray(r[i]).tobytes()
                                for r in rows)
                pages.append({"hash": h.hex(),
                              "sha256": hashlib.sha256(page).hexdigest()})
                payload += page
        finally:
            # unpin: same discipline as _release_slot_pages — a page a
            # concurrent swap unregistered while we held it goes back
            # to the free list here
            with self._page_lock:
                for _h, pid in pinned:
                    self._page_ref[pid] -= 1
                    if self._page_ref[pid] <= 0:
                        self._page_ref[pid] = 0
                        if pid not in self._page_key:
                            self._page_free.append(pid)
        hdr = json.dumps({
            "page_size": self.page_size, "wver": self.kv_wver,
            "entries": [[n, p, dt, list(s)] for n, p, dt, s in entries],
            "pages": pages,
        }).encode()
        blob = _KV_MAGIC + len(hdr).to_bytes(4, "little") + hdr \
            + bytes(payload)
        with self._page_lock:
            self._kv_exported_pages += len(pinned)
            self._kv_export_bytes += len(blob)
        self._m_kv_exported.inc(len(pinned))
        self._m_kv_bytes.labels(direction="out").inc(len(blob))
        self._m_kv_seconds.labels(direction="out").observe(
            time.monotonic() - t0)
        return blob

    def _decode_pages_blob(self, blob) -> list:
        """Validate a wire blob against the LOCAL geometry and weights
        version; returns ``[(hash, [row arrays in entry order]), ...]``.
        Every defect is a loud ValueError (the REST layer's 400) — a
        page of someone else's KV silently entering the prefix index
        would break bitwise identity for every request hitting it."""
        blob = bytes(blob)
        if blob[:len(_KV_MAGIC)] != _KV_MAGIC:
            raise ValueError("not a KV-page blob (bad magic)")
        off = len(_KV_MAGIC)
        if len(blob) < off + 4:
            raise ValueError("truncated KV-page blob (no header)")
        n = int.from_bytes(blob[off:off + 4], "little")
        off += 4
        try:
            hdr = json.loads(blob[off:off + n].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"corrupt KV-page header: {e}") from e
        off += n
        if int(hdr.get("page_size", -1)) != self.page_size:
            raise ValueError(
                f"page_size mismatch: blob {hdr.get('page_size')} vs "
                f"local {self.page_size}")
        if str(hdr.get("wver")) != self.kv_wver:
            raise ValueError(
                f"weights-version mismatch: blob {hdr.get('wver')!r} "
                f"vs local {self.kv_wver!r} — pages computed under "
                "other weights cannot serve here")
        local = [[n_, p, dt, list(s)]
                 for n_, p, dt, s in self._kv_xfer_entries()]
        if hdr.get("entries") != local:
            raise ValueError(
                "cache-entry layout mismatch (names/dtypes/shapes "
                "differ from the local paged caches)")
        sizes = [(np.dtype(dt), tuple(s),
                  int(np.dtype(dt).itemsize) * int(np.prod(s)))
                 for _n, _p, dt, s in self._kv_xfer_entries()]
        page_bytes = sum(sz for _dt, _s, sz in sizes)
        pages_hdr = hdr.get("pages") or []
        if len(blob) - off != page_bytes * len(pages_hdr):
            raise ValueError(
                f"payload size mismatch: {len(blob) - off} bytes for "
                f"{len(pages_hdr)} pages of {page_bytes}")
        out = []
        for meta in pages_hdr:
            page = blob[off:off + page_bytes]
            off += page_bytes
            if hashlib.sha256(page).hexdigest() != meta.get("sha256"):
                raise ValueError(
                    "page integrity check failed for "
                    f"{meta.get('hash')!r}")
            rows, p_off = [], 0
            for dt, shape, sz in sizes:
                rows.append(np.frombuffer(
                    page[p_off:p_off + sz], dtype=dt).reshape(shape))
                p_off += sz
            out.append((self._norm_hash(str(meta.get("hash"))), rows))
        return out

    def import_pages(self, blob, *, timeout: float = 30.0) -> dict:
        """Deserialize a peer's prefix pages into the local pool.
        Validation (geometry, weights version, per-page integrity) is
        all-or-nothing and raises ValueError; the APPLY is per-page
        best-effort: already-resident hashes are skipped, and when the
        pool is fully referenced the page is dropped rather than the
        transfer failed.  The device writes land on the scheduler
        thread at a decode-step boundary (the swap discipline), so this
        blocks until the next tick applies them.  Imported pages enter
        the prefix index refcount-0 — cached, evictable, and dropped by
        a swap's invalidation exactly like locally-prefilled ones."""
        self._require_transfer()
        t0 = time.monotonic()
        pages = self._decode_pages_blob(blob)
        box = {"applied": None, "error": None}
        done = threading.Event()
        with self._kv_import_lock:
            self._kv_imports.append((pages, box, done))
        if self.started:
            self._wake.set()
        else:
            self._apply_kv_imports()
        deadline = time.monotonic() + float(timeout)
        while not done.wait(0.05):
            if not self.started:
                # the scheduler stopped between the enqueue and its
                # drain: apply inline (the deque pop under
                # _kv_import_lock makes concurrent drains safe)
                self._apply_kv_imports()
            elif time.monotonic() > deadline:
                raise TimeoutError(
                    f"KV-page import not applied within {timeout}s "
                    "(scheduler wedged?)")
        if box["error"]:
            raise ValueError(
                f"KV-page import failed mid-apply: {box['error']}")
        imported, skipped, dropped, hashes = box["applied"]
        with self._page_lock:
            self._kv_imported_pages += imported
            self._kv_import_bytes += len(blob)
        self._m_kv_imported.inc(imported)
        self._m_kv_bytes.labels(direction="in").inc(len(blob))
        self._m_kv_seconds.labels(direction="in").observe(
            time.monotonic() - t0)
        return {"imported": imported, "skipped": skipped,
                "dropped": dropped,
                "hashes": [h.hex() for h in hashes]}

    def _claim_import_page(self):
        """One pool page claimed (``self._page_ref`` goes 1) for an
        in-flight KV-page import; None when every page is referenced
        by a live slot.  The "kv-transfer" acquire (analysis registry
        RESOURCE_PAIRS): every exit must reach
        :meth:`_abort_import_page` or hand the page to
        :meth:`_register_import_page`."""
        with self._page_lock:
            return self._alloc_page_locked()

    def _abort_import_page(self, pid: int):
        """Return a claimed-but-unregistered import page to
        ``self._page_free`` (the "kv-transfer" release): the apply
        aborted and the page never entered the prefix index."""
        with self._page_lock:
            self._page_ref[pid] = 0
            self._page_free.append(pid)

    def _register_import_page(self, pid: int, h: bytes):
        """Publish an imported page in the prefix index exactly like a
        locally-prefilled one: refcount back to 0 (cached state —
        evictable under pressure, freed by release-path bookkeeping
        once unregistered) plus the imported-page attribution set."""
        with self._page_lock:
            self._page_ref[pid] = 0
            self._prefix_index[h] = pid
            self._page_key[pid] = h
            self._imported_pages.add(pid)
            self._touch(pid)

    def _apply_kv_imports(self):
        """Drain staged KV-page imports (scheduler thread at a decode-
        step boundary, or inline on a stopped engine).  Per page:
        skip duplicates, claim a pool page, write the device rows,
        register.  A write failure releases the claimed page and fails
        THAT import's caller — never the scheduler every other request
        shares."""
        while True:
            with self._kv_import_lock:
                if not self._kv_imports:
                    return
                pages, box, done = self._kv_imports.popleft()
            imported = skipped = dropped = 0
            hashes = []
            entries = self._kv_xfer_entries()
            try:
                for h, rows in pages:
                    with self._page_lock:
                        pid0 = self._prefix_index.get(h)
                        if pid0 is not None:
                            self._touch(pid0)
                    if pid0 is not None:
                        skipped += 1
                        hashes.append(h)
                        continue
                    pid = self._claim_import_page()
                    if pid is None:
                        # every page is referenced by a live slot:
                        # drop this page rather than fail the
                        # transfer — the peer's prefix simply stays
                        # cold here
                        dropped += 1
                        continue
                    try:
                        for (name, part, _d, _s), row in zip(entries,
                                                             rows):
                            self._caches[name][part] = \
                                self._caches[name][part].at[pid].set(row)
                    except Exception:
                        self._abort_import_page(pid)
                        raise
                    self._register_import_page(pid, h)
                    imported += 1
                    hashes.append(h)
            except Exception as e:  # noqa: BLE001 — surface on the
                # importer's call, never crash the shared scheduler
                box["error"] = f"{type(e).__name__}: {e}"
            box["applied"] = (imported, skipped, dropped, hashes)
            done.set()

    def _kv_transfer_summary(self) -> Optional[dict]:
        """The ``stats()["kv_transfer"]`` group: transfer volume, the
        remote-hit attribution, and the two numbers the fleet router's
        fetch-payoff policy scrapes (wire bytes per page and the
        prefill-throughput EWMA)."""
        if not self.paged:
            return None
        with self._page_lock:
            out = {
                "exported_pages": self._kv_exported_pages,
                "imported_pages": self._kv_imported_pages,
                "export_bytes": self._kv_export_bytes,
                "import_bytes": self._kv_import_bytes,
                "remote_hit_pages": self._remote_hit_pages,
            }
        out["page_bytes"] = self._kv_page_bytes() if self._prefix_ok \
            else 0
        out["prefill_tok_s"] = round(self._prefill_tok_s, 1)
        out["wver"] = self.kv_wver
        return out

    def _prefill(self, slot: int, req: _Request):
        """Admit ``req`` into ``slot``.  Short tails prefill in one
        program call; a tail longer than ``prefill_chunk`` instead
        REGISTERS the slot for chunked prefill — one bounded slice per
        scheduler iteration, interleaved with decode steps — so a long
        prompt costs everyone bounded latency instead of a monopolized
        scheduler (docs/serving.md "Overload survival")."""
        # reserve the slot BEFORE the device program runs: between the
        # queue pop and _active[slot] going true the request must stay
        # visible to drain()'s idleness check (and to _fail_all)
        self._slot_req[slot] = req
        req.slot = slot
        now = time.monotonic()
        req.run_started_at = now
        if req.admitted_at is None:
            # first admission only: a preemption resume is not a fresh
            # queue wait (its wait was already observed once)
            req.admitted_at = now
            wait = now - req.submitted_at
            if not req.batch:
                # batch never lands in the SLO histograms (the tracker
                # snapshots whole registry histograms, so exclusion
                # must happen here) nor in the Retry-After EWMA — a
                # deliberately-parked bulk prompt would poison both
                self._m_queue_wait.observe(wait)
                self._qwait_ewma = wait if self._qwait_ewma <= 0 \
                    else 0.9 * self._qwait_ewma + 0.1 * wait
            self._admitted.inc()
        eff = req.effective_prompt()
        P = int(eff.size)
        # the bucket is sized by the UN-SHARED tail: a prefix-cache hit
        # turns a long prompt into a short prefill
        start = req.prefix_start if self.paged else 0
        req.chunk_first = start
        if self.paged:
            self._ptab[slot] = req.page_row
        if self.prefill_chunk > 0 and self._chunk_capable \
                and P - start > self.prefill_chunk:
            req.chunk_next = start
            self._chunking.add(slot)
            return
        self._prefill_call(slot, req, eff, start, P - start, last=True)

    def _advance_prefills(self):
        """One chunk slice per mid-prefill slot (scheduler thread):
        the long-prompt/decode interleave, plus the mid-prefill
        deadline sweep (a chunking slot is neither queued nor active,
        so neither other sweep would ever fail it)."""
        for slot in sorted(self._chunking):
            req = self._slot_req[slot]
            if req is None:             # defensive: state went away
                self._chunking.discard(slot)
                continue
            if time.monotonic() > req.deadline:
                self._chunking.discard(slot)
                self._slot_req[slot] = None
                self._release_slot_pages(slot)
                self._timeouts.inc()
                req.finish(error=TimeoutError(
                    "request deadline expired mid-prefill"))
                self._observe_finish(req, "504")
                continue
            eff = req.effective_prompt()
            P = int(eff.size)
            cur = req.chunk_next
            n = min(self.prefill_chunk, P - cur)
            last = cur + n >= P
            self._prefill_call(slot, req, eff, cur, n, last=last)
            req.chunk_next = cur + n
            if last:
                self._chunking.discard(slot)

    def _prefill_call(self, slot: int, req: _Request, eff, start: int,
                      new_len: int, *, last: bool):
        """ONE prefill program call over ``eff[start:start+new_len]``
        (an unchunked admission, or one chunk slice).  ``last`` runs
        the admission bookkeeping: the call's sampled token is the
        request's next real token exactly when the slice ends at the
        prompt end — intermediate slices' samples land at positions
        nothing reads."""
        params = self.wstate["params"]
        pb = self._bucket(new_len)
        temp = np.float32(req.temperature)
        # sentinels: see _sample_slots
        topk = np.int32(req.top_k if req.top_k is not None
                        else self._vocab)
        topp = np.float32(req.top_p if req.top_p is not None else 1.0)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :new_len] = eff[start:start + new_len]
        # chunk slices (and their finals) continue from earlier
        # positions and need the full-context program; a whole-tail
        # admission at start == 0 takes the bucket-local fast variant
        fn = self._prefill_fn(pb, params,
                              full_ctx=(start > 0 or not last))
        if self.paged:
            self._caches, self._toks, first = fn(
                params, self._caches, self._toks, req.page_row, padded,
                np.int32(new_len), np.int32(start), np.int32(slot),
                temp, topk, topp, req.key_data)
        elif self._prefill_start:
            self._caches, self._toks, first = fn(
                params, self._caches, self._toks, padded,
                np.int32(new_len), np.int32(start), np.int32(slot),
                temp, topk, topp, req.key_data)
        else:
            # sealed dense artifacts from pre-chunking exports: the
            # whole-prompt calling convention (start is always 0 and
            # chunking is gated off by _chunk_capable)
            self._caches, self._toks, first = fn(
                params, self._caches, self._toks, padded,
                np.int32(new_len), np.int32(slot), temp, topk, topp,
                req.key_data)
        if not last:
            return
        if self.paged:
            self._register_prefix_pages(req)
        first = int(first)
        # int(first) above synced on the prefill result, so this is the
        # honest host-side time-to-first-token boundary
        now = time.monotonic()
        # metric label: the bucket of the WHOLE tail this admission
        # prefilled, not the final slice's — a chunked 8k prompt whose
        # last slice fit bucket 16 must not land its multi-second
        # duration in the small-prefill latency series (for unchunked
        # calls the slice IS the whole tail, so the label is ``pb``)
        lab = self._bucket(max(1, start + new_len - req.chunk_first))
        req.bucket = lab
        self._m_prefill.labels(bucket=lab).observe(
            now - req.run_started_at)
        # prefill-throughput EWMA (tokens/s over the whole tail) — the
        # fleet router's fetch-vs-reprefill payoff reads this off
        # stats()["kv_transfer"] to estimate what a local re-prefill of
        # N tokens would cost (scheduler thread only)
        rate = max(1, start + new_len - req.chunk_first) \
            / max(now - req.run_started_at, 1e-9)
        self._prefill_tok_s = rate if self._prefill_tok_s <= 0 \
            else 0.8 * self._prefill_tok_s + 0.2 * rate
        if req.first_token_at is None:
            # chunked or not, preempted-before-first-token or not: TTFT
            # is observed exactly once, at the ACTUAL first token —
            # and never for batch (SLO exclusion, see _prefill)
            req.first_token_at = now
            if not req.batch:
                self._m_ttft.labels(bucket=lab).observe(
                    now - req.submitted_at)
        P = int(eff.size)
        self._pos[slot] = P
        self._temp[slot] = temp
        self._topk[slot] = topk
        self._topp[slot] = topp
        self._eos[slot] = -1 if req.eos_id is None else req.eos_id
        # the FINAL token index is invariant across preemptions:
        # original prompt + n_steps, however much of it already sits in
        # req.gen
        self._end[slot] = req.end_index
        self._keys[slot] = req.key_data
        if self.spec:
            # drafter history: the full effective prompt (prefills never
            # write the prompt region of _toks) + the first token
            self._hist[slot, :P] = eff
            self._hist[slot, P] = first
            self._hist_pos[slot] = P
        self._tok_count.inc()
        if req.batch:
            self._batch_tok_n += 1
        if req.stream is not None:
            # the first token is already host-side (int(first) above):
            # its frame streams now, not at the next dispatch's flush
            if req.stream.push(int(req.gen.size), (first,)):
                self._m_stream_frames.inc()
        done = (P >= req.end_index
                or (req.eos_id is not None and first == req.eos_id))
        if not done and req.stop_seqs:
            tail = np.concatenate(
                [req.gen, np.asarray([first], np.int32)])
            if self._match_stop(req, tail, int(req.gen.size)) is not None:
                # the first generated token completed a stop sequence
                # (possibly one spanning into the resume prefix):
                # retiring here keeps it — same shape as an eos hit
                req.stop_hit = True
                done = True
        self._active[slot] = not done
        if done:
            self._retire(slot)

    def _advance_once(self):
        """One scheduler advance of every active slot: a speculative
        verify step when the drafter proposed AND the measured payoff
        test passes (slots without a draft ride along on their ``-1``
        rows and advance exactly one token — the decode-step
        behavior), else the plain decode step.  When even a best-case
        draft could not pay (``_spec_worthwhile``), the drafter and its
        history sync are skipped entirely — a workload the drafter
        cannot predict decays to plain decode plus one drafting attempt
        every ``_SPEC_PROBE_TICKS`` ticks (the attempt counter resets
        whether or not a draft was found, so an undraftable stream can
        never degrade to per-tick host overhead)."""
        draft = None
        if self.spec and self._spec_worthwhile():
            probe = self._ticks_since_attempt >= _SPEC_PROBE_TICKS \
                and self._verify_wall_ewma > 0
            draft = self._spec_drafts()
            self._ticks_since_attempt = 0   # attempt consumed either way
            self._spec_attempts += 1
            # a parked-regime probe that FOUND a draft runs the verify
            # unconditionally — its purpose is refreshing the accept
            # EWMA the payoff test reads; in the profitable regime the
            # per-matrix payoff test still arbitrates
            if draft is not None and not probe \
                    and not self._verify_pays(draft):
                draft = None
        if draft is not None:
            self._verify_once(draft)
        elif self._mega is not None and self._mega_ready():
            self._megastep_once()
        else:
            self._step_once()

    def _mega_ready(self) -> bool:
        """May this iteration fuse N micro-steps?  Only when nothing
        could want the scheduler back sooner: every slot busy (a free
        slot means the next arrival's admission — and any preemption
        on its behalf — would wait out the block), the queue empty,
        and no slot mid-chunked-prefill (its next slice interleaves
        with single steps).  Any pending work drops this iteration to
        N=1, so interactive latency, overload reflexes, and the
        spec-decode interleave (which already claimed this tick if a
        draft was worth verifying) never wait on a fused block."""
        # lint: disable=VC201 bool(deque) is atomic under the GIL; a
        # stale read only defers fusion by one iteration
        return (bool(self._active.all()) and not self._queue
                and not self._chunking)

    def _spec_worthwhile(self) -> bool:
        """Cheap pre-draft gate: could a verify step pay even if EVERY
        active slot drafted at the recent accept rate?  Same economics
        as :meth:`_verify_pays` with drafted == active (its upper
        bound), so a false here implies _verify_pays would refuse any
        actual draft matrix — skipping the drafter is free.  Three
        regimes: profitable (measured EWMAs, payoff positive) drafts
        every tick; cold (no verify step has measured the walls yet)
        measures-first on every tick but only for a BOUNDED attempt
        budget — a stream whose history never recurs must not pay
        drafter + history-sync per tick forever; parked (or cold
        budget spent) rations attempts to one per
        ``_SPEC_PROBE_TICKS``."""
        if self._verify_wall_ewma > 0 and self._step_wall_ewma > 0:
            ratio = self._verify_wall_ewma / max(self._step_wall_ewma,
                                                 1e-9)
            if 1 + self.spec_k * self._accept_ewma >= ratio:
                return True     # profitable regime: draft every tick
        elif self._spec_attempts < 64:
            return True         # cold phase: measure first, boundedly
        return self._ticks_since_attempt >= _SPEC_PROBE_TICKS

    def _verify_pays(self, draft) -> bool:
        """Interleave policy: one verify step must be expected to emit
        at least what the SAME wall spent on decode steps would —
        ``active + proposed·accept_ewma  >=  active · (verify wall /
        decode wall)``, all three factors measured on THIS engine (the
        verify/decode cost ratio is workload- and hardware-shaped:
        near ``1`` where per-step dispatch dominates — small models on
        CPU, bandwidth-bound decode on real accelerators — and near
        ``k+1`` where per-position compute does).  Until both EWMAs
        exist the answer is yes (measure first); re-qualification after
        parking is the probe path in :meth:`_advance_once`."""
        active = int(self._active.sum())
        # REAL proposal count, not drafted·k: rows are capped by the
        # slot's length bound and the continuation the n-gram found
        proposed = int((draft >= 0).sum())
        if self._verify_wall_ewma <= 0 or self._step_wall_ewma <= 0:
            return True
        ratio = self._verify_wall_ewma / max(self._step_wall_ewma, 1e-9)
        expected = active + proposed * self._accept_ewma
        return expected >= active * ratio

    def _spec_drafts(self):
        """(S, K) int32 draft matrix from the n-gram drafter over each
        active slot's host-side token history, or None when no slot
        drafted (the scheduler then runs a plain decode step).  ``-1``
        rows/entries never match, so an undrafted slot still advances
        one token through the verify program."""
        self._sync_hist()
        draft = None
        for s in np.flatnonzero(self._active):
            req = self._slot_req[s]
            if req is None:
                continue
            pos, end = int(self._pos[s]), int(self._end[s])
            # remaining == 1 finishes on the first emitted token: a
            # draft could accept nothing, so don't pay for one
            if end - pos < 2:
                continue
            row = ngram_draft(self._hist[s, :pos + 1], self.spec_k)
            if row is None:
                continue
            # proposals past the slot's length bound are dead weight
            keep = min(self.spec_k, end - pos - 1)
            row[keep:] = -1
            if not (row >= 0).any():
                continue
            if draft is None:
                draft = np.full((self.slots, self.spec_k), -1, np.int32)
            draft[s] = row
        return draft

    def _sync_hist(self):
        """LAZILY mirror freshly written tokens into the host-side
        history the drafter reads (one bulk D2H of the token matrix,
        paid only on ticks that actually draft — a parked speculative
        engine costs nothing per step).  ``_hist_pos`` tracks how far
        each slot's mirror is valid; the prompt region stays the host
        copy _prefill wrote, because paged prefills never write the
        possibly-shared prompt rows of ``_toks``."""
        stale = [int(s) for s in np.flatnonzero(self._active)
                 if self._hist_pos[s] < self._pos[s]]
        if not stale:
            return
        htoks = np.asarray(self._toks)
        for s in stale:
            lo, hi = int(self._hist_pos[s]), int(self._pos[s])
            self._hist[s, lo + 1:hi + 1] = htoks[s, lo + 1:hi + 1]
            self._hist_pos[s] = hi

    @staticmethod
    def _match_stop(req: _Request, gen_all, start: int):
        """Earliest count ``n`` of generated tokens to KEEP such that a
        stop sequence ends at ``gen_all[n - 1]``, scanning only match
        ends at index >= ``start`` — earlier ends were scanned at
        earlier flushes, so a sequence SPANNING a flush boundary still
        matches (its end is new even though its head streamed already).
        ``gen_all`` is every generated token including the resume
        prefix.  None = no match."""
        for j in range(int(start), int(gen_all.size)):
            for seq in req.stop_seqs:
                ln = int(seq.size)
                if ln <= j + 1 and np.array_equal(
                        gen_all[j + 1 - ln:j + 1], seq):
                    return j + 1
        return None

    def _stop_retire(self, slot: int, n_keep: int):
        """Early retirement on a stop-sequence match: the slot frees
        like any retire, the result keeps the generated tokens THROUGH
        the match (``n_keep``, counting the resume prefix), and
        ``stop_hit`` routes the terminal frame's finish reason."""
        req = self._slot_req[slot]
        self._active[slot] = False
        self._slot_req[slot] = None
        self._release_slot_pages(slot)
        req.stop_hit = True
        P = int(req.prompt.size) + int(req.gen.size)
        fresh = np.asarray(
            self._toks[slot, P:P + n_keep - int(req.gen.size)],
            np.int32)
        self._retired.inc()
        req.finish(result=np.concatenate([req.prompt, req.gen, fresh]))
        self._observe_finish(req, "ok")

    def _flush_streams(self):
        """Push every streaming slot's freshly decoded tokens as frames
        — ONE bulk token-matrix D2H per dispatch, paid only while a
        streaming request is active (the same discipline as
        :meth:`_sync_hist`).  Runs once per dispatch whatever the
        dispatch shape, so a megastep/verify block flushes its whole
        emitted run in one pass — the megastep-aware "flush every N
        micro-steps" cadence falls out for free.  Stop sequences are
        matched here BEFORE pushing, so no frame past the stop point
        ever streams."""
        htoks = None
        for slot in range(self.slots):
            req = self._slot_req[slot]
            # a mid-chunked-prefill slot still carries the PREVIOUS
            # occupant's _pos — nothing to flush until its final slice
            if req is None or req.stream is None \
                    or slot in self._chunking:
                continue
            h = req.stream
            total = int(self._pos[slot]) + 1 - int(req.prompt.size)
            start = h.next_i    # scheduler thread is the sole writer
            if total <= start:
                continue
            if htoks is None:
                htoks = np.asarray(self._toks)
            P = int(req.prompt.size)
            lim = total
            n_keep = None
            if req.stop_seqs and not req.stop_hit:
                gen_all = np.concatenate([
                    req.gen,
                    htoks[slot, P + int(req.gen.size):P + total]])
                n_keep = self._match_stop(req, gen_all, start)
                if n_keep is not None:
                    lim = n_keep
            if lim > start:
                n = h.push(start, htoks[slot, P + start:P + lim])
                if n:
                    self._m_stream_frames.inc(n)
            if n_keep is not None:
                self._stop_retire(slot, n_keep)

    def _post_step(self, finished):
        """Retirement + mid-flight deadline sweep shared by the decode
        and verify steps."""
        # stream flush FIRST: _slot_req still maps every slot that just
        # emitted, and a deadline expiry below must deliver the tokens
        # this dispatch produced before its terminal frame.
        with self._qlock:
            flush = bool(self._streams)
        if flush:
            self._flush_streams()
        now = time.monotonic()
        for slot in np.flatnonzero(np.asarray(finished)):
            self._retire(int(slot))
        # mid-flight deadline: a wedged client must not hold a slot
        for slot in np.flatnonzero(self._active):
            req = self._slot_req[slot]
            if req is not None and now > req.deadline:
                self._active[slot] = False
                self._slot_req[slot] = None
                self._release_slot_pages(int(slot))
                self._timeouts.inc()
                req.finish(error=TimeoutError(
                    "request deadline expired while decoding"))
                self._observe_finish(req, "504")

    def _note_batch_tokens(self, per_slot):
        """Attribute one dispatch's per-slot emitted counts to the
        batch-lane token total (vt_batch_tokens_per_sec's feed).
        Scheduler thread, called BEFORE _post_step — ``_slot_req``
        still maps every slot that just emitted."""
        for slot, n in enumerate(np.asarray(per_slot)):
            if n:
                req = self._slot_req[slot]
                if req is not None and req.batch:
                    self._batch_tok_n += int(n)

    def _step_once(self):
        from . import faults
        t0 = time.monotonic()
        if faults.enabled():
            plan = faults.get_plan()
            if plan.decode_stall_ms \
                    and faults.fire_once("decode_stall"):
                # injected tail-latency spike (runtime/faults.py): one
                # artificially slow decode step, inside the timed
                # window so it lands in vt_decode_step_seconds and the
                # wall EWMAs exactly like a real stall would
                time.sleep(plan.decode_stall_ms / 1e3)
        args = (self.wstate["params"], self._caches, self._toks)
        if self.paged:
            args += (self._ptab,)
        self._caches, self._toks, pos, active, finished = self._decode(
            *args, self._pos, self._active, self._temp, self._topk,
            self._topp, self._eos, self._end, self._keys)
        n_active = int(self._active.sum())
        self._decode_steps.inc()
        self._dispatches.inc()
        self._occupancy_sum += n_active
        self._tok_count.inc(n_active)
        # pre-step mask: every then-active slot emitted exactly one
        self._note_batch_tokens(self._active.astype(np.int64))
        # np.array (copy): asarray would alias the read-only device view
        self._pos = np.array(pos)
        self._active = np.array(active)
        # the np.array copies above synced on the step result, so this
        # wall time is the real per-token decode latency under load
        wall = time.monotonic() - t0
        self._m_decode_step.observe(wall)
        # bandwidth-utilization denominator: a light EWMA smooths the
        # per-step jitter without hiding a sustained slowdown
        self._step_wall_ewma = wall if self._step_wall_ewma <= 0 \
            else 0.9 * self._step_wall_ewma + 0.1 * wall
        rate = self._decode_bytes / max(wall, 1e-9)
        self._bw_ewma = rate if self._bw_ewma <= 0 \
            else 0.9 * self._bw_ewma + 0.1 * rate
        self._last_step_at = time.monotonic()
        if self.spec:
            self._ticks_since_attempt += 1
        self._post_step(finished)

    def _verify_once(self, draft):
        """One speculative verify step: every active slot scores its
        ``k + 1`` positions in one program call and advances by its
        accepted prefix + the bonus token (1 .. k+1 tokens; undrafted
        slots advance exactly 1).  Bitwise the decode path's tokens —
        the program's sampler picks every emitted token; the draft only
        decides how many picks one call makes."""
        t0 = time.monotonic()
        old_pos = self._pos.copy()
        args = (self.wstate["params"], self._caches, self._toks)
        if self.paged:
            args += (self._ptab,)
        (self._caches, self._toks, pos, active, finished,
         accepted) = self._verify(
            *args, self._pos, self._active, self._temp, self._topk,
            self._topp, self._eos, self._end, self._keys, draft)
        self._pos = np.array(pos)
        self._active = np.array(active)
        emitted = int((self._pos - old_pos).sum())
        self._tok_count.inc(emitted)
        self._note_batch_tokens(self._pos - old_pos)
        self._verify_steps += 1
        self._dispatches.inc()
        proposed = int((draft >= 0).sum())
        acc = int(np.asarray(accepted).sum())
        self._spec_proposed.inc(proposed)
        self._spec_accepted.inc(acc)
        # the np.array copies synced on the result: honest wall time
        wall = time.monotonic() - t0
        self._m_spec_verify.observe(wall)
        # policy state (see _verify_pays): verify wall + accept EWMAs
        self._verify_wall_ewma = wall if self._verify_wall_ewma <= 0 \
            else 0.9 * self._verify_wall_ewma + 0.1 * wall
        if proposed:
            self._accept_ewma = (0.8 * self._accept_ewma
                                 + 0.2 * acc / proposed)
        # a verify step IS decode traffic: keep the achieved-bandwidth
        # gauge live (its cost analysis over its wall) and the idle
        # detector fed — an engine serving pure speculative load must
        # never scrape as bandwidth-0 (the decode-wall EWMA itself
        # stays decode-only: it is the payoff test's denominator)
        if self._verify_bytes > 0:
            rate = self._verify_bytes / max(wall, 1e-9)
            self._bw_ewma = rate if self._bw_ewma <= 0 \
                else 0.9 * self._bw_ewma + 0.1 * rate
        self._last_step_at = time.monotonic()
        self._post_step(finished)

    def _megastep_once(self):
        """One megastep dispatch: every slot advances up to N tokens in
        one program call, with in-program eos/length retirement between
        micro-steps (bitwise the N=1 path's tokens — same sampler, same
        per-position key folds).  The host pays ONE scheduler pass —
        retirement, deadline sweep, accounting — for the whole block:
        ``toks`` already holds each slot's emitted buffer and
        ``emitted`` its count, so :meth:`_post_step` consumes the block
        in bulk exactly like a verify step's accepted run."""
        t0 = time.monotonic()
        args = (self.wstate["params"], self._caches, self._toks)
        if self.paged:
            args += (self._ptab,)
        (self._caches, self._toks, pos, active, finished,
         emitted) = self._mega(
            *args, self._pos, self._active, self._temp, self._topk,
            self._topp, self._eos, self._end, self._keys)
        self._pos = np.array(pos)
        self._active = np.array(active)
        n_emitted = int(np.asarray(emitted).sum())
        self._tok_count.inc(n_emitted)
        self._note_batch_tokens(np.asarray(emitted))
        # per-micro-step accounting so occupancy and per-token latency
        # stay comparable across N: N micro-steps ran, their summed
        # live-slot count IS the emitted total, and the per-token wall
        # is the dispatch wall over N
        self._decode_steps.inc(self.megastep)
        self._dispatches.inc()
        self._occupancy_sum += n_emitted
        self._mega_steps += 1
        wall = time.monotonic() - t0
        per_tok = wall / self.megastep
        self._m_decode_step.observe(per_tok)
        self._step_wall_ewma = per_tok if self._step_wall_ewma <= 0 \
            else 0.9 * self._step_wall_ewma + 0.1 * per_tok
        if self._mega_bytes > 0:
            rate = self._mega_bytes / max(wall, 1e-9)
            self._bw_ewma = rate if self._bw_ewma <= 0 \
                else 0.9 * self._bw_ewma + 0.1 * rate
        self._last_step_at = time.monotonic()
        if self.spec:
            self._ticks_since_attempt += 1
        self._post_step(finished)

    def _retire(self, slot: int):
        req = self._slot_req[slot]
        self._active[slot] = False
        self._slot_req[slot] = None
        self._release_slot_pages(slot)
        if req is None:
            return
        # prefill never writes the (possibly shared) prompt region of
        # the token row, so assemble from the request's own prompt +
        # whatever a preemption already harvested + this run's tokens
        P = int(req.prompt.size) + int(req.gen.size)
        gen = np.asarray(self._toks[slot, P:int(self._pos[slot]) + 1],
                         np.int32)
        self._retired.inc()
        req.finish(result=np.concatenate([req.prompt, req.gen, gen]))
        self._observe_finish(req, "ok")

    def _maybe_report(self):
        # every tick: the SLO window ring rotates (cheap — it appends a
        # snapshot at most once per slice).  The gauges publish on the
        # 0.5s branch below, so a bare GET /metrics or /slo.json scrape
        # is never stale — no dependence on anything polling /engine or
        # a StatusReporter being attached (e.g. --serve --artifact
        # boots status-less) — while the per-decode-step hot path never
        # pays the O(pages) pool summary.
        self._slo.tick()
        # the admission controller evaluates on the same heartbeat
        # (internally rate-limited to serve.admission.interval_s): its
        # sensor is the ring the line above just rotated
        self._admission.tick()
        now = time.monotonic()
        if now - self._status_mark < 0.5:
            return
        self._status_mark = now
        stats = self.stats()    # publishes the sampled gauges
        if self.status is None:
            return
        try:
            self.status.update(engine=stats)
        except Exception:  # status must never take the engine down
            pass
