"""Snapshotter: checkpoint/resume.

Reference parity (reference: veles/snapshotter.py:84,360,428 — pickle of the
whole workflow with gz/bz2/xz codecs, time/interval throttling :159-174,
``_current`` symlink :397-409, size warning with per-unit breakdown
:203-225; restore at CLI veles/__main__.py:539-589).

TPU redesign: instead of pickling live objects, the checkpoint is the
explicit state contract (SURVEY.md §5.4): the workflow state pytree
(params / unit state / optimizer state / step / PRNG key), loader state,
decision state, PRNG registry state, and the config snapshot. Tensors go
into one ``npz`` (compressed = the codec knob); structure into a JSON
manifest. This keeps checkpoints host-readable and independent of Python
object layout — and resharding on load is just device_put under a new mesh
(8→1 chip resume).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..logger import Logger


class SnapshotCorruptError(RuntimeError):
    """A snapshot failed integrity verification (checksum mismatch,
    truncated/unreadable tensors blob, unparseable manifest).  Restore
    paths catch it and walk back to the newest VALID snapshot
    (:func:`restore_with_walkback`)."""


def _fsync_file(path: str) -> None:
    """Flush a finished file's bytes to stable storage — the atomic
    _current symlink flip is only a valid commit point if the files it
    names survive a crash (docs/robustness.md: torn-write discipline)."""
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    """Persist directory entries (the rename/symlink metadata)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _commit_bytes(target: str, data: bytes) -> None:
    """Stage ``data`` as an fsynced ``*.tmp`` sibling and rename it
    into place — a crash leaves either the old file or the new one,
    never a torn hybrid.  Shared by the snapshot manifest commit and
    the package exporter (docs/robustness.md: torn-write discipline;
    the VR704 lint rule pins the idiom)."""
    tmp = target + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, target)


def _flatten(tree, prefix="", out=None):
    out = {} if out is None else out
    if isinstance(tree, dict):
        if not tree:
            out[f"{prefix}/__emptydict__"] = np.zeros(0)
            return out
        for k in sorted(tree):
            _flatten(tree[k], f"{prefix}/{k}" if prefix else str(k), out)
    elif isinstance(tree, (list, tuple)):
        out[f"{prefix}/__seq__"] = np.asarray(
            [len(tree), int(isinstance(tree, tuple))])
        for i, v in enumerate(tree):
            _flatten(v, f"{prefix}/{i}", out)
    else:
        out[prefix] = np.asarray(tree)
    return out


def _to_numpy(tree):
    """device_get with PRNG typed keys unwrapped to raw uint32 data."""
    def conv(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype,
                                                  jax.dtypes.prng_key):
            x = jax.random.key_data(x)
        return np.asarray(jax.device_get(x))
    return jax.tree.map(conv, tree)


def _unflatten(flat: Dict[str, np.ndarray]):
    root: dict = {}
    seqs = set()
    for key, value in flat.items():
        parts = key.split("/")
        if parts[-1] == "__seq__":
            path = "/".join(parts[:-1])
            seqs.add(path)
            node = root  # materialize the node even for empty sequences
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            continue
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        if parts[-1] == "__emptydict__":
            continue  # parent dict already materialized (possibly empty)
        node[parts[-1]] = value

    def fix(node, path=""):
        if not isinstance(node, dict):
            return node
        out = {k: fix(v, f"{path}/{k}" if path else k) for k, v in
               node.items()}
        if path in seqs:
            n = len(out)
            seq = [out[str(i)] for i in range(n)]
            meta = flat[f"{path}/__seq__"]
            return tuple(seq) if meta[1] else seq
        return out

    return fix(root)


class Snapshotter(Logger):
    """Save/restore checkpoints with interval+time throttling and
    best/current symlinks."""

    def __init__(self, prefix: str, directory: Optional[str] = None, *,
                 compression: bool = True, interval: int = 1,
                 time_interval: float = 0.0):
        if directory is None:
            # root.common.snapshot_dir is the config-tree form of the
            # constructor arg (docs/configuration.md); same default
            from ..config import root
            directory = str(root.common.get("snapshot_dir", "snapshots")
                            or "snapshots")
        self.prefix = prefix
        self.directory = directory
        self.compression = compression
        self.interval = interval          # epochs between snapshots
        self.time_interval = time_interval  # min seconds between snapshots
        # the throttle is a read-modify-write pair: two concurrent
        # tick() calls (trainer + a GC/maintenance caller) may not both
        # pass the time gate, or one epoch double-snapshots
        self._lock = threading.Lock()
        self._last_time = 0.0             # guarded-by: self._lock
        self._counter = 0                 # guarded-by: self._lock
        self.last_path: Optional[str] = None

    def tick(self, *, best: bool = False) -> bool:
        """Advance the throttle and report whether this epoch snapshots
        (reference: veles/snapshotter.py:159-174). Deterministic given the
        call sequence — on multi-host every host ticks identically, so
        all hosts can agree to skip the (collective) payload gather."""
        with self._lock:
            self._counter += 1
            now = time.time()
            if not best:
                if self._counter % max(self.interval, 1) != 0:
                    return False
                if now - self._last_time < self.time_interval:
                    return False
            self._last_time = now
            return True

    def maybe_save(self, tag: str, payload: Dict[str, Any], *,
                   best: bool = False) -> Optional[str]:
        """Throttled save."""
        if not self.tick(best=best):
            return None
        return self.save(tag, payload, best=best)

    def save(self, tag: str, payload: Dict[str, Any], *,
             best: bool = False) -> str:
        """Write tensors npz + JSON manifest, fsync both, THEN flip the
        ``_current``/``_best`` symlinks — so the symlinks only ever name
        snapshots whose bytes are on stable storage.  The manifest
        records the tensors blob's sha256 (``tensors_sha256``); restore
        verifies it and walks back past corruption
        (:func:`restore_with_walkback`).  ``root.common.snapshot_keep``
        > 0 garbage-collects all but the newest K snapshots after a
        successful save (symlink targets are never collected)."""
        from ..config import root
        os.makedirs(self.directory, exist_ok=True)
        base = f"{self.prefix}_{tag}"
        npz_path = os.path.join(self.directory, base + ".npz")

        tensors = _flatten(_to_numpy(payload.get("wstate", {})))
        saver = np.savez_compressed if self.compression else np.savez
        saver(npz_path, **tensors)
        _fsync_file(npz_path)

        manifest = {k: v for k, v in payload.items() if k != "wstate"}
        manifest["tensors"] = base + ".npz"
        manifest["tensors_sha256"] = sha256_files([npz_path])
        manifest["saved_at"] = time.time()
        man_path = os.path.join(self.directory, base + ".json")
        _commit_bytes(man_path,
                      json.dumps(manifest, indent=1,
                                 default=repr).encode())

        for link, active in (("_current", True), ("_best", best)):
            if not active:
                continue
            lpath = os.path.join(self.directory, self.prefix + link + ".json")
            tmp = lpath + ".tmp"
            if os.path.lexists(tmp):
                os.remove(tmp)
            os.symlink(os.path.basename(man_path), tmp)
            os.replace(tmp, lpath)
        _fsync_dir(self.directory)

        size = os.path.getsize(npz_path)
        self.info("snapshot %s (%.1f MiB)%s", man_path, size / 2**20,
                  " [best]" if best else "")
        self.last_path = man_path

        keep = int(root.common.get("snapshot_keep", 0) or 0)
        if keep > 0:
            self._gc(keep)

        # fault harness: simulate a torn write discovered only at
        # restore time (docs/robustness.md fault-injection knobs)
        from .faults import get_plan
        if get_plan().truncate_snapshot:
            with open(npz_path, "rb+") as f:
                f.truncate(max(size // 2, 1))
            self.warning("fault injection: truncated %s to %d bytes",
                         npz_path, max(size // 2, 1))
        return man_path

    def _gc(self, keep: int) -> None:
        """Keep-last-K retention over THIS prefix's snapshots.  The
        ``_current``/``_best`` symlink targets are exempt no matter how
        old — a walk-back restore needs the newest chain, and the best
        checkpoint must outlive the window."""
        snaps = list_snapshots(self.directory, prefix=self.prefix + "_")
        if len(snaps) <= keep:
            return
        protected = set()
        for link in ("_current", "_best"):
            lp = os.path.join(self.directory,
                              self.prefix + link + ".json")
            if os.path.lexists(lp):
                protected.add(os.path.realpath(lp))
        removed = []
        for ent in snaps[:-keep]:
            if os.path.realpath(ent["path"]) in protected:
                continue
            npz = os.path.join(self.directory, ent["tensors"])
            for p in (ent["path"], npz):
                try:
                    os.remove(p)
                except OSError:
                    pass
            removed.append(ent["tag"])
        if removed:
            self.info("snapshot GC (keep-last-%d): removed %s", keep,
                      ", ".join(removed))

    @staticmethod
    def load(path: str, *, verify: bool = True) -> Dict[str, Any]:
        """Restore a checkpoint from its manifest path (or the _current/_best
        symlink), from a ``sqlite://db.sqlite#id`` URI written by
        SnapshotterToDB, or from an ``http(s)://`` manifest URL (reference:
        the CLI's http snapshot source, veles/__main__.py:539-589). Returns
        the payload with 'wstate' as numpy pytree; call ``jax.device_put``
        (optionally with shardings) to place it.

        ``verify`` (filesystem manifests only) checks the tensors blob
        against the manifest's recorded ``tensors_sha256``; any
        integrity failure — checksum mismatch, truncated/unreadable
        blob, unparseable manifest — raises
        :class:`SnapshotCorruptError` so callers can walk back
        (:func:`restore_with_walkback`) instead of crashing on, or
        silently training from, torn bytes."""
        if path.startswith("sqlite://"):
            return SnapshotterToDB.load_uri(path)
        if path.startswith(("http://", "https://")):
            return Snapshotter._load_http(path)
        try:
            with open(path) as f:
                manifest = json.load(f)
            if not isinstance(manifest, dict) or "tensors" not in manifest:
                raise SnapshotCorruptError(
                    f"{path}: not a snapshot manifest")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise SnapshotCorruptError(
                f"{path}: unparseable manifest ({e})") from e
        npz_path = os.path.join(os.path.dirname(path), manifest["tensors"])
        want = manifest.get("tensors_sha256")
        if verify and want:
            try:
                got = sha256_files([npz_path])
            except OSError as e:
                raise SnapshotCorruptError(
                    f"{path}: tensors blob unreadable ({e})") from e
            if got != want:
                raise SnapshotCorruptError(
                    f"{path}: tensors checksum mismatch (manifest "
                    f"{want[:12]}…, blob {got[:12]}…)")
        try:
            with np.load(npz_path, allow_pickle=False) as z:
                flat = {k: z[k] for k in z.files}
        except (OSError, ValueError, EOFError,
                zipfile.BadZipFile) as e:
            raise SnapshotCorruptError(
                f"{path}: tensors blob unreadable ({e})") from e
        payload = dict(manifest)
        payload["wstate"] = _unflatten(flat)
        return payload

    #: manifest JSON cap (MiB) — structure only, tensors live in the npz
    _HTTP_MANIFEST_MAX_MB = 64

    @staticmethod
    def _read_capped(resp, limit: int, what: str, knob: str) -> bytes:
        """Chunked read that refuses to exceed ``limit`` bytes — an
        http(s):// snapshot URI points at a remote the caller may not
        control (compare_snapshots on user-supplied URLs), so an
        unbounded ``r.read()`` is a memory/denial surface.  The declared
        Content-Length fails fast; a lying/chunked response is caught by
        the running total.  ``knob`` names the limit's origin in the
        error so the operator raises the RIGHT setting."""
        try:  # a hostile server may declare garbage; the running total
            declared = int(resp.headers.get("Content-Length", ""))
        except ValueError:  # below still enforces the cap
            declared = None
        if declared is not None and declared > limit:
            raise ValueError(
                f"{what} declares {declared} bytes, over the "
                f"{limit}-byte cap ({knob})")
        chunks, total = [], 0
        while True:
            chunk = resp.read(1 << 20)
            if not chunk:
                return b"".join(chunks)
            total += len(chunk)
            if total > limit:
                raise ValueError(
                    f"{what} exceeded the {limit}-byte cap ({knob})")
            chunks.append(chunk)

    @staticmethod
    def _load_http(url: str) -> Dict[str, Any]:
        """Fetch manifest + tensors npz over HTTP; the tensors reference in
        the manifest is resolved relative to the manifest URL.  Both
        downloads are size-capped (``root.common.snapshot_http_max_mb``
        for the tensors blob)."""
        import io
        import urllib.parse
        import urllib.request
        from ..config import root
        from .deploy import http_retry  # late: deploy imports this module
        max_bytes = int(float(root.common.get(
            "snapshot_http_max_mb", 2048)) * 2**20)

        def fetch(u, limit, what, knob):
            # connection errors / 5xx retry with the shared backoff
            # shape; 4xx fail fast (a missing snapshot will not appear
            # because we asked four times)
            def once():
                with urllib.request.urlopen(u, timeout=30.0) as r:
                    return Snapshotter._read_capped(r, limit, what, knob)
            return http_retry(once, what=what)

        manifest = json.loads(fetch(
            url, Snapshotter._HTTP_MANIFEST_MAX_MB << 20,
            f"snapshot manifest {url}",
            "Snapshotter._HTTP_MANIFEST_MAX_MB"))
        tensors_url = urllib.parse.urljoin(url, manifest["tensors"])
        buf = io.BytesIO(fetch(
            tensors_url, max_bytes, f"snapshot tensors {tensors_url}",
            "root.common.snapshot_http_max_mb"))
        with np.load(buf, allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
        payload = dict(manifest)
        payload["wstate"] = _unflatten(flat)
        return payload

    @staticmethod
    def restore_wstate(payload: Dict[str, Any], like: Optional[dict] = None,
                       shardings=None):
        """Rebuild the on-device workflow state, casting dtypes to match a
        template (PRNG keys need their key dtype restored)."""
        wstate = payload["wstate"]
        if like is not None:
            def cast(saved, template):
                if hasattr(template, "dtype") and jnp.issubdtype(
                        template.dtype, jax.dtypes.prng_key):
                    return jax.random.wrap_key_data(
                        jnp.asarray(saved, jnp.uint32))
                return jnp.asarray(saved).astype(template.dtype)
            try:
                wstate = jax.tree.map(cast, wstate, like)
            except (ValueError, AttributeError) as e:
                raise ValueError(
                    "snapshot state structure does not match this "
                    "workflow's (different optimizer or architecture? "
                    "the checksum only covers graph topology): "
                    f"{e}") from e
        if shardings is not None:
            from ..parallel.distributed import (is_multihost,
                                                place_global_state)
            if is_multihost():
                # device_put refuses non-addressable shardings; rebuild
                # the global arrays from the host-identical restored state.
                return place_global_state(wstate, shardings)
            return jax.device_put(wstate, shardings)
        return jax.device_put(wstate)


class SnapshotterToDB(Snapshotter):
    """Snapshot into a sqlite database instead of the filesystem
    (reference: SnapshotterToDB over ODBC, veles/snapshotter.py:428-518 —
    the portable stdlib analog).  Rows carry the manifest JSON and the
    tensor .npz bytes; ``last_path`` is a ``sqlite://db#id`` URI accepted by
    ``Snapshotter.load`` and therefore by ``Trainer.restore``."""

    _SCHEMA = ("CREATE TABLE IF NOT EXISTS snapshots ("
               "id INTEGER PRIMARY KEY AUTOINCREMENT, prefix TEXT, "
               "tag TEXT, saved_at REAL, best INTEGER, manifest TEXT, "
               "tensors BLOB)")

    def __init__(self, prefix: str, db_path: str = "snapshots.sqlite", *,
                 compression: bool = True, interval: int = 1,
                 time_interval: float = 0.0):
        super().__init__(prefix, os.path.dirname(db_path) or ".",
                         compression=compression, interval=interval,
                         time_interval=time_interval)
        self.db_path = db_path

    def _connect(self):
        import sqlite3
        conn = sqlite3.connect(self.db_path)
        conn.execute(self._SCHEMA)
        return conn

    def save(self, tag: str, payload: Dict[str, Any], *,
             best: bool = False) -> str:
        import io
        buf = io.BytesIO()
        tensors = _flatten(_to_numpy(payload.get("wstate", {})))
        saver = np.savez_compressed if self.compression else np.savez
        saver(buf, **tensors)
        manifest = {k: v for k, v in payload.items() if k != "wstate"}
        manifest["saved_at"] = time.time()
        blob = buf.getvalue()
        conn = self._connect()
        try:
            with conn:
                cur = conn.execute(
                    "INSERT INTO snapshots (prefix, tag, saved_at, best, "
                    "manifest, tensors) VALUES (?, ?, ?, ?, ?, ?)",
                    (self.prefix, tag, manifest["saved_at"], int(best),
                     json.dumps(manifest, default=repr), blob))
                rowid = cur.lastrowid
        finally:
            conn.close()
        self.last_path = f"sqlite://{self.db_path}#{rowid}"
        self.info("snapshot %s (%.1f MiB)%s", self.last_path,
                  len(blob) / 2**20, " [best]" if best else "")
        return self.last_path

    @staticmethod
    def load_uri(uri: str) -> Dict[str, Any]:
        """``sqlite://db`` (latest row), ``sqlite://db#<id>`` (exact row) or
        ``sqlite://db#best``/``#current`` (the filesystem symlink analogs).
        The fragment is split at the LAST '#' so db paths containing '#'
        survive."""
        import io
        import sqlite3
        assert uri.startswith("sqlite://"), uri
        rest = uri[len("sqlite://"):]
        head, sep, frag = rest.rpartition("#")
        db_path = head if sep else rest
        if not sep:
            frag = ""
        conn = sqlite3.connect(db_path)
        try:
            if frag == "best":
                row = conn.execute(
                    "SELECT manifest, tensors FROM snapshots WHERE best=1 "
                    "ORDER BY id DESC LIMIT 1").fetchone()
            elif frag and frag != "current":
                row = conn.execute(
                    "SELECT manifest, tensors FROM snapshots WHERE id=?",
                    (int(frag),)).fetchone()
            else:  # latest ("current")
                row = conn.execute(
                    "SELECT manifest, tensors FROM snapshots "
                    "ORDER BY id DESC LIMIT 1").fetchone()
        finally:
            conn.close()
        if row is None:
            raise FileNotFoundError(uri)
        manifest, blob = row
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
        payload = json.loads(manifest)
        payload["wstate"] = _unflatten(flat)
        return payload


def list_snapshots(directory: str,
                   prefix: Optional[str] = None) -> list:
    """Inventory of the snapshot manifests in ``directory``, sorted
    oldest → newest by the manifest's ``saved_at`` (file mtime when the
    field is absent) — the deploy control plane's load-by-version view
    of a snapshot directory (runtime/deploy.py watcher + registry).

    Symlink manifests (the ``_current``/``_best`` conveniences) are
    skipped: their targets are already listed.  Unparseable JSON is
    skipped silently — a snapshot mid-write looks exactly like that and
    will be complete on the next poll."""
    out = []
    if not os.path.isdir(directory):
        return out
    for fn in sorted(os.listdir(directory)):
        path = os.path.join(directory, fn)
        if not fn.endswith(".json") or os.path.islink(path):
            continue
        if prefix and not fn.startswith(prefix):
            continue
        try:
            with open(path) as f:
                man = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        if not isinstance(man, dict) or "tensors" not in man:
            continue  # some other JSON living in the directory
        try:
            saved_at = float(man.get("saved_at") or os.path.getmtime(path))
        except (TypeError, ValueError, OSError):
            saved_at = 0.0
        out.append({"path": path, "tag": fn[:-len(".json")],
                    "saved_at": saved_at, "tensors": man["tensors"]})
    out.sort(key=lambda e: (e["saved_at"], e["path"]))
    return out


def sha256_files(paths) -> str:
    """Streamed sha256 hex digest over the given files' bytes, in
    order — the one hashing loop both the snapshot and export-package
    checksum paths share (runtime/deploy.py registry identities)."""
    import hashlib
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()


def snapshot_checksum(path: str) -> str:
    """sha256 hex digest of the tensors blob a manifest references — the
    registry's cheap version identity (two snapshots with identical
    weights hash identically; a re-save with new weights does not).
    Returns '' when the blob cannot be read (remote URIs, mid-write
    snapshots) — callers treat '' as "unknown", never as a match."""
    try:
        with open(path) as f:
            man = json.load(f)
        npz = os.path.join(os.path.dirname(path), man["tensors"])
        return sha256_files([npz])
    except (OSError, KeyError, TypeError, ValueError,
            json.JSONDecodeError):
        return ""


def restore_with_walkback(path: str) -> Tuple[Dict[str, Any], str, List[dict]]:
    """Load the snapshot at ``path``; on corruption, walk back through the
    retained snapshots in the same directory (newest → oldest by
    ``saved_at``) to the newest VALID one.

    Returns ``(payload, used_path, skipped)`` where ``skipped`` lists
    ``{"path", "reason"}`` for every snapshot rejected on the way — the
    caller logs them and feeds the count to the
    ``snapshot_walkbacks`` gauge.  Raises :class:`SnapshotCorruptError`
    when NOTHING in the directory loads.  Remote URIs (``sqlite://`` /
    ``http(s)://``) have no sibling inventory to walk and load directly."""
    if path.startswith(("sqlite://", "http://", "https://")):
        return Snapshotter.load(path), path, []
    skipped: List[dict] = []
    target = os.path.realpath(path)
    try:
        # Only INTEGRITY failures of the named snapshot trigger the
        # walk-back (load() wraps them all in SnapshotCorruptError); a
        # missing path is most likely a typo, and silently restoring a
        # sibling the operator never named would be worse than failing.
        return Snapshotter.load(path), target, skipped
    except SnapshotCorruptError as e:
        skipped.append({"path": target, "reason": f"{type(e).__name__}: {e}"})
    directory = os.path.dirname(path) or "."
    seen = {target}
    for ent in reversed(list_snapshots(directory)):
        real = os.path.realpath(ent["path"])
        if real in seen:
            continue
        seen.add(real)
        try:
            return Snapshotter.load(ent["path"]), real, skipped
        except (SnapshotCorruptError, OSError, KeyError, ValueError) as e:
            skipped.append(
                {"path": real, "reason": f"{type(e).__name__}: {e}"})
    raise SnapshotCorruptError(
        f"no valid snapshot found in {directory!r}; rejected "
        + "; ".join(f"{s['path']} ({s['reason']})" for s in skipped))


def compare_snapshots(path_a: str, path_b: str) -> Dict[str, Any]:
    """Per-tensor diff of two checkpoints (reference:
    /root/reference/veles/scripts/compare_snapshots.py, which printed
    relative differences between the pickled Arrays of two Snapshotter
    files; here the inputs are this runtime's npz+JSON manifests,
    ``_current``/``_best`` symlinks, or ``sqlite://``/``http(s)://``
    snapshot URIs).

    Returns ``{"rows": [...], "only_a": [...], "only_b": [...],
    "meta": {...}}`` where each row carries key/shape/dtype and
    max|Δ| / mean|Δ| / max relative Δ (0-denominators excluded), a
    ``mismatch`` flag for shape/dtype disagreements, and ``meta`` maps
    differing manifest fields to their (a, b) values."""
    pa, pb = Snapshotter.load(path_a), Snapshotter.load(path_b)
    fa = _flatten(_to_numpy(pa.get("wstate", {})))
    fb = _flatten(_to_numpy(pb.get("wstate", {})))
    rows = []
    for k in sorted(set(fa) & set(fb)):
        a, b = np.asarray(fa[k]), np.asarray(fb[k])
        if a.shape != b.shape or a.dtype != b.dtype:
            rows.append({"key": k, "mismatch": True,
                         "shape_a": list(a.shape), "dtype_a": str(a.dtype),
                         "shape_b": list(b.shape), "dtype_b": str(b.dtype)})
            continue
        af = a.astype(np.float64, copy=False)
        bf = b.astype(np.float64, copy=False)
        d = np.abs(af - bf)
        denom = np.maximum(np.abs(af), np.abs(bf))
        nz = denom > 0
        rows.append({
            "key": k, "mismatch": False,
            "shape": list(a.shape), "dtype": str(a.dtype),
            "max_abs": float(d.max()) if d.size else 0.0,
            "mean_abs": float(d.mean()) if d.size else 0.0,
            "max_rel": float((d[nz] / denom[nz]).max()) if nz.any()
            else 0.0,
        })
    skip = {"tensors", "saved_at", "wstate"}
    meta = {}
    for k in sorted((set(pa) | set(pb)) - skip):
        va, vb = pa.get(k), pb.get(k)
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            continue
        if va != vb:
            meta[k] = [va, vb]
    return {"rows": rows,
            "only_a": sorted(set(fa) - set(fb)),
            "only_b": sorted(set(fb) - set(fa)),
            "meta": meta}
