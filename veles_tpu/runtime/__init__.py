from .decision import Decision
from .generate import DecodePlan, generate
from .snapshotter import Snapshotter, SnapshotterToDB
from .trainer import Trainer
