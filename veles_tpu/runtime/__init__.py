from .decision import Decision
from .generate import DecodePlan, generate, generate_beam
from .snapshotter import Snapshotter, SnapshotterToDB
from .trainer import Trainer
