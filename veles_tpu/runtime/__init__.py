from .decision import Decision
from .snapshotter import Snapshotter
from .trainer import Trainer
