from .decision import Decision
from .snapshotter import Snapshotter, SnapshotterToDB
from .trainer import Trainer
