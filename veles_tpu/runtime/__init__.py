from .decision import Decision
from .engine import DecodeEngine, EngineOverloaded, EngineStopped
from .generate import DecodePlan, generate, generate_beam
from .snapshotter import Snapshotter, SnapshotterToDB
from .step_cache import StepCache, enable_persistent_cache
from .trainer import Trainer
