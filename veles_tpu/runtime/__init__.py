from .artifact import (ArtifactError, ArtifactRunner,
                       ArtifactVersionError)
from .decision import Decision
from .deploy import DeployController, ModelRegistry
from .engine import (DecodeEngine, EngineDraining, EngineOverloaded,
                     EngineStopped, SchedulerCrashed)
from .fleet import FleetRouter, FleetServer, InProcessReplica
from .fleet_client import ReplicaClient, ReplicaUnavailable
from .generate import DecodePlan, generate, generate_beam
from .snapshotter import Snapshotter, SnapshotterToDB
from .step_cache import StepCache, enable_persistent_cache
from .trainer import Trainer
