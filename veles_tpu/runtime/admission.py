"""SLO-driven admission control: the engine's overload *reflex*.

PR 9 gave the serving stack senses — ``vt_slo_burn_rate`` over rolling
windows, queue-wait histograms, headroom-in-slots — but the only reflex
wired to them was the binary ``observe.slo.degrade_ready`` flip: /ready
goes 503 and a load balancer (hopefully) routes around the whole
replica.  That is the wrong shape for graceful degradation: the Veles
pipeline's signature was *partial* shedding — gates close, units park,
the master drops slaves — not all-or-nothing (PAPER.md).  This module
is the modern counterpart for the DecodeEngine
(docs/serving.md "Overload survival"):

* the controller owns an **admission window** — how many queued
  requests the engine currently accepts, from the full
  ``serve.queue_depth`` down to ``serve.admission.min_window``;
* every ``serve.admission.interval_s`` it reads the worst SLO **burn
  rate** (runtime/slo.py ``SloTracker.max_burn`` — windowed, sample-
  count-guarded) and applies AIMD-style control with **hysteresis**:
  burn at/over ``observe.slo.burn_threshold`` shrinks the window
  multiplicatively (``admission.decrease``); burn must stay under HALF
  the threshold for ``admission.hold_s`` before the window regrows
  (``admission.increase``), and the band in between holds steady — so
  the window neither flaps on a blip nor re-opens into a still-burning
  tail;
* the window is **priority-aware**: while it is fully open every class
  gets the hard ``queue_depth`` (a healthy engine, or one with no SLO
  target, sheds nobody); once a burn closes it, class 0 (the highest)
  keeps the hard bound — the controller never sheds it — while lower
  classes scale with the window, the lowest class down to ``window /
  priorities``: under overload the low classes shed first and
  hardest, which is exactly the contract priority classes sell;
* the shed path stays *honest*: the engine's 429 Retry-After scales by
  :meth:`AdmissionController.backoff_factor` (how far the window is
  closed), so clients back off proportionally to actual congestion.

Everything here is host-side and jax-free; the clock and the burn
source are injectable, so the hysteresis behavior is pinned by fast
deterministic tests (tests/test_overload.py).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Optional

from ..config import root


class AdmissionController:
    """AIMD admission-window controller over an SLO burn-rate signal.

    ``burn_fn`` is the sensor (typically ``SloTracker.max_burn``; None
    reads as burn 0 — the window stays fully open).  ``gauge`` is an
    optional registry gauge (``vt_admission_window``) kept in sync with
    the window.  All knob defaults come from
    ``root.common.serve.admission.*`` / ``observe.slo.burn_threshold``.
    Thread-safety: ``tick`` runs on the engine scheduler thread;
    ``allowance`` / ``backoff_factor`` / ``window`` are read from REST
    worker threads — the window is guarded by a lock, and the sensor is
    consulted outside it (it takes registry locks of its own).
    """

    def __init__(self, *, queue_depth: int, priorities: int = 1,
                 burn_fn: Optional[Callable[[], float]] = None,
                 clock=time.monotonic, gauge=None,
                 enabled: Optional[bool] = None,
                 min_window: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 hold_s: Optional[float] = None,
                 decrease: Optional[float] = None,
                 increase: Optional[float] = None,
                 burn_threshold: Optional[float] = None):
        adm = root.common.serve.admission
        self.queue_depth = max(1, int(queue_depth))
        self.priorities = max(1, int(priorities))
        self.enabled = bool(adm.get("enabled", True)
                            if enabled is None else enabled)
        self.min_window = min(self.queue_depth, max(1, int(
            adm.get("min_window", 2)
            if min_window is None else min_window)))
        self.interval_s = float(adm.get("interval_s", 0.25)
                                if interval_s is None else interval_s)
        self.hold_s = float(adm.get("hold_s", 2.0)
                            if hold_s is None else hold_s)
        self.decrease = float(adm.get("decrease", 0.5)
                              if decrease is None else decrease)
        self.increase = float(adm.get("increase", 1.5)
                              if increase is None else increase)
        if not 0.0 < self.decrease < 1.0:
            raise ValueError(
                f"admission.decrease must be in (0, 1), got {self.decrease}")
        if self.increase <= 1.0:
            raise ValueError(
                f"admission.increase must be > 1, got {self.increase}")
        self.burn_threshold = float(
            root.common.observe.slo.get("burn_threshold", 2.0)
            if burn_threshold is None else burn_threshold)
        self._burn_fn = burn_fn
        self._clock = clock
        self._gauge = gauge
        self._lock = threading.Lock()
        self._window = float(self.queue_depth)  # guarded-by: self._lock
        self._last_eval = None  # guarded-by: self._lock
        self._good_since = None  # guarded-by: self._lock
        self._last_burn = 0.0  # guarded-by: self._lock
        if gauge is not None:
            gauge.set(self._window)

    # -- control loop (engine scheduler thread) ------------------------------
    def tick(self, now: Optional[float] = None) -> float:
        """One controller evaluation, internally rate-limited to
        ``interval_s`` (call as often as you like).  Returns the current
        window."""
        now = self._clock() if now is None else now
        with self._lock:
            due = self.enabled and (self._last_eval is None
                                    or now - self._last_eval
                                    >= self.interval_s)
            if due:
                self._last_eval = now
        if not due:
            return self.window()
        # the sensor takes its own (registry) locks — read it unlocked
        burn = float(self._burn_fn()) if self._burn_fn is not None else 0.0
        with self._lock:
            self._last_burn = burn
            w = self._window
            if burn >= self.burn_threshold:
                # overload: shed multiplicatively, forget any recovery
                w = max(float(self.min_window), w * self.decrease)
                self._good_since = None
            elif burn <= 0.5 * self.burn_threshold:
                # recovered — but only re-open after the recovery HELD
                # for hold_s (hysteresis: a window that re-opens into a
                # still-cooling tail just re-burns and flaps)
                if self._good_since is None:
                    self._good_since = now
                elif now - self._good_since >= self.hold_s \
                        and w < self.queue_depth:
                    w = min(float(self.queue_depth),
                            max(w * self.increase, w + 1.0))
            else:
                # the band between half-threshold and threshold: hold
                # the window and keep the recovery clock unarmed
                self._good_since = None
            self._window = w
            if self._gauge is not None:
                self._gauge.set(w)
            return w

    # -- cross-thread reads --------------------------------------------------
    def window(self) -> float:
        with self._lock:
            return self._window if self.enabled else float(self.queue_depth)

    def last_burn(self) -> float:
        with self._lock:
            return self._last_burn

    def allowance(self, priority: int = 0) -> int:
        """The TOTAL queue length (across all classes) at which an
        arrival of class ``priority`` is refused — the engine compares
        it against ``len(queue)``, not against the class's own
        occupancy, so a lower class stops being admitted as soon as the
        whole backlog reaches its (smaller) bound: the backlog that
        remains under a closed window is the work the high classes see
        ahead of them.  (An arrival refused by its bound may still
        displace a queued strictly-lower-class request — the engine's
        rule, not this controller's.)  With the window fully open —
        healthy, disabled, or no SLO target declared — EVERY class gets
        the hard ``queue_depth``: the controller is a true no-op until
        a burn actually closed the window.  Once it has, class 0 (the
        highest) keeps the hard bound — the controller never sheds it —
        while lower classes scale with the window, the lowest hardest:
        class p of P gets ``window * (P - p) / P``.  Under overload the
        low classes shed first and most, which is exactly the contract
        priority classes sell.  Always at least 1 (the hard
        ``queue_depth`` cap is applied by the caller)."""
        p = min(max(int(priority), 0), self.priorities - 1)
        w = self.window()
        if w >= self.queue_depth:
            return self.queue_depth
        if self.priorities == 1:
            # a single class: there is no higher class to protect, so
            # the window bounds everyone — admission control still
            # works with the priority feature off (anything else
            # would leave tick() closing a window nobody reads, with
            # the gauge and stats claiming sheds that never happen)
            return max(1, int(math.ceil(w)))
        if p == 0:
            return self.queue_depth
        share = (self.priorities - p) / self.priorities
        return max(1, int(math.ceil(w * share)))

    def backoff_factor(self) -> float:
        """How far the window is closed (>= 1.0) — the adaptive
        Retry-After multiplier: a half-closed window doubles the
        suggested client backoff."""
        return self.queue_depth / max(self.window(), 1.0)

    def state(self) -> dict:
        """JSON-able controller snapshot for ``stats()`` / benches."""
        w = self.window()
        return {
            "enabled": self.enabled,
            "window": round(w, 2),
            "queue_depth": self.queue_depth,
            "min_window": self.min_window,
            "shedding": w < self.queue_depth,
            "burn": round(self.last_burn(), 3),
            "burn_threshold": self.burn_threshold,
        }
