"""Model lifecycle control plane: versioned registry, zero-downtime hot
weight swaps, graceful drain, and a snapshot watcher.

The reference treated trained models as first-class deployable
artifacts — Snapshotter checkpoints, a versioned Forge store, workflow
packages consumed by a standalone serving runtime — but the rebuild's
serving path (``DecodeEngine`` + ``RestfulServer``) was born with one
immutable ``wstate``: updating weights meant killing the process and
recompiling everything.  This module closes the training→serving loop
without ever paying that outage:

* a **versioned model registry**: every weight set this process has
  served gets an entry (monotonic version id, source path/URI, sha256
  checksum of the tensors blob, load timestamp); ``GET /models`` on the
  REST server renders it with the active version marked;
* **zero-downtime hot swaps**: new weights are loaded from a
  Snapshotter snapshot (file manifest, ``sqlite://`` or ``http(s)://``
  URI), an ``export_package()`` directory/zip, or a Forge store
  (``forge://<root>/<name>[@version]``), cast against the live template,
  staged to device as a *double buffer* while the old version keeps
  serving, then flipped atomically at a decode-step boundary
  (:meth:`DecodeEngine.swap_params`).  Same shapes/dtypes reuse the
  engine's compiled programs — the StepCache counters stay flat across a
  swap, and a mismatched tree is rejected with a clear error while the
  old version keeps serving.  Any failure during the flip swaps the
  previous buffer back (rollback);
* **graceful drain** (``POST /admin/drain`` and the SIGTERM handler):
  stop admissions → ``GET /ready`` answers 503 → in-flight slots retire
  → the engine stops → :meth:`DeployController.wait` releases so the
  process can exit cleanly;
* an optional **snapshot watcher** thread polling a directory for newer
  snapshots (by ``saved_at``, deduplicated by tensors checksum) with
  exponential retry backoff, swapping automatically — the CLI's
  ``--model-dir --watch`` (docs/serving.md).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import root
from ..logger import Logger
from .engine import EngineDraining, place_like, signature_mismatch
from .snapshotter import (Snapshotter, list_snapshots, sha256_files,
                          snapshot_checksum)
from .step_cache import tree_signature

#: Shared retry-backoff shape: the snapshot watcher, the forge HTTP
#: client and Snapshotter http loads all grow their delay by
#: BACKOFF_FACTOR per consecutive failure and add up to BACKOFF_JITTER
#: of uniform spread (so a fleet of retriers doesn't re-stampede the
#: endpoint that just failed).  The HTTP_* pair bounds the per-attempt
#: delay for request-scale retries (the watcher's ceiling is the
#: config's ``watch_backoff_max_s``).
BACKOFF_FACTOR = 2.0
BACKOFF_JITTER = 0.25
HTTP_RETRY_BASE_S = 0.25
HTTP_RETRY_MAX_S = 4.0


def http_retry(fn, *, what: str = "http request",
               retries: Optional[int] = None, log=None,
               base_s: float = HTTP_RETRY_BASE_S):
    """Call ``fn()``, retrying TRANSIENT failures — connection errors and
    HTTP 5xx — up to ``retries`` times (default
    ``root.common.net.http_retries``) with exponential backoff + jitter.
    4xx responses re-raise immediately: the client is wrong, not
    unlucky, and asking again just hammers the server."""
    import random
    import urllib.error
    if retries is None:
        retries = int(root.common.net.get("http_retries", 3))
    attempt = 0
    while True:
        try:
            return fn()
        except urllib.error.HTTPError as e:
            if e.code < 500 or attempt >= retries:
                raise
            reason = f"HTTP {e.code}"
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            if attempt >= retries:
                raise
            reason = f"{type(e).__name__}: {e}"
        delay = min(base_s * BACKOFF_FACTOR ** attempt, HTTP_RETRY_MAX_S)
        delay *= 1.0 + random.random() * BACKOFF_JITTER
        if log is not None:
            log.warning("%s failed (%s); retry %d/%d in %.2fs", what,
                        reason, attempt + 1, retries, delay)
        time.sleep(delay)
        attempt += 1


def _shape_signature(tree, *, unwrap_keys: bool = False) -> Tuple:
    """(path, shape) signature — the structural half of
    :func:`tree_signature`.  Dtypes are deliberately excluded: a
    float32-trained snapshot is castable to a bfloat16 serving template,
    but a shape mismatch means a different architecture and must
    reject.  ``unwrap_keys`` views typed PRNG keys as their raw
    key_data, matching how snapshots store them (Snapshotter._to_numpy)
    so a live template compares against saved trees leaf for leaf."""
    if unwrap_keys:
        tree = jax.tree.map(
            lambda x: jax.random.key_data(x)
            if hasattr(x, "dtype") and jnp.issubdtype(
                x.dtype, jax.dtypes.prng_key) else x, tree)
    return tuple((p, s, "") for p, s, _ in tree_signature(tree))


def _cast_leaf(saved, template):
    """Snapshot leaf → the live template's dtype (PRNG keys rewrap)."""
    if hasattr(template, "dtype") and jnp.issubdtype(
            template.dtype, jax.dtypes.prng_key):
        return jax.random.wrap_key_data(jnp.asarray(saved, jnp.uint32))
    return jnp.asarray(saved).astype(template.dtype)


def _manifest_saved_at(path) -> float:
    """``saved_at`` of a local snapshot manifest, 0.0 when unreadable
    (remote URIs, packages) — the watcher's newness anchor."""
    try:
        with open(str(path)) as f:
            return float(json.load(f).get("saved_at") or 0.0)
    except (OSError, TypeError, ValueError, json.JSONDecodeError):
        return 0.0


class ModelRegistry(Logger):
    """Versioned record of every weight set this process has served.

    Entries are metadata only (version id, label, source, checksum,
    load timestamp) — weights themselves live on device, only the
    active buffer at rest (plus the staged one transiently during a
    swap).  Re-activating an older version reloads it from its
    source."""

    def __init__(self):
        self._entries: List[dict] = []  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.active_version: Optional[int] = None  # guarded-by: self._lock

    def add(self, *, label: str, source: str, kind: str,
            checksum: str) -> dict:
        with self._lock:
            entry = {"version": len(self._entries) + 1,
                     "label": str(label), "source": str(source),
                     "kind": str(kind), "checksum": str(checksum),
                     "loaded_at": time.time()}
            self._entries.append(entry)
        return entry

    def get(self, version) -> dict:
        try:
            version = int(version)
        except (TypeError, ValueError):
            raise KeyError(f"version must be an integer, got {version!r}")
        # iterating while add() appends from another thread (watcher vs
        # manual reload) is the unsynchronized read veles-tpu-lint VC201
        # exists for — snapshot under the lock, raise outside it
        with self._lock:
            for e in self._entries:
                if e["version"] == version:
                    return e
            have = [e["version"] for e in self._entries]
        raise KeyError(
            f"registry has no version {version} (has {have})")

    def activate(self, version: int) -> None:
        with self._lock:
            self.active_version = int(version)

    @property
    def active(self) -> Optional[dict]:
        with self._lock:
            version = self.active_version
        if version is None:
            return None
        return self.get(version)

    def to_doc(self) -> dict:
        """JSON document for ``GET /models``."""
        with self._lock:
            return {"active": self.active_version,
                    "versions": [dict(e, active=(e["version"]
                                                 == self.active_version))
                                 for e in self._entries]}


class DeployController(Logger):
    """The control plane wrapping a live engine and/or REST server.

    ``DeployController(server=srv)`` attaches itself as ``srv.deploy``
    so the server routes ``GET /models`` and ``POST /admin/*`` here;
    ``engine=`` defaults to the server's engine.  A server-less
    controller (``engine=`` only) drives a library-embedded engine; an
    engine-less controller hot-swaps a plain predict server's
    ``wstate`` (the swap is an atomic reference flip the per-request
    handler picks up).
    """

    def __init__(self, *, server=None, engine=None,
                 model_dir: Optional[str] = None, status=None,
                 drain_timeout_s: Optional[float] = None,
                 watch_interval_s: Optional[float] = None,
                 watch_backoff_max_s: Optional[float] = None,
                 boot_label: str = "boot", boot_source: str = "live"):
        if server is None and engine is None:
            raise ValueError(
                "DeployController needs a server and/or an engine")
        serve = root.common.serve
        self.server = server
        self.engine = engine if engine is not None \
            else getattr(server, "engine", None)
        self.status = status
        self.model_dir = model_dir or (serve.get("model_dir") or None)
        self.drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else serve.get("drain_timeout_s", 30.0))
        self.drain_grace_s = float(serve.get("drain_grace_s", 2.0))
        self.watch_interval_s = float(
            watch_interval_s if watch_interval_s is not None
            else serve.get("watch_interval_s", 5.0))
        self.watch_backoff_max_s = float(
            watch_backoff_max_s if watch_backoff_max_s is not None
            else serve.get("watch_backoff_max_s", 300.0))

        self.registry = ModelRegistry()
        self._ck_lock = threading.Lock()
        self._ck_cache = None  # (path, mtime) -> digest memo  # guarded-by: self._ck_lock
        # a boot source that IS a snapshot (file manifest, sqlite://,
        # http://) or a compiled artifact registers as a reloadable
        # version — so POST /admin/reload {"version": 1} can roll back
        # to boot — with its real checksum when the blob is local, which
        # also lets the watcher's dedup see the booted weights (no
        # redundant first swap of the very snapshot the process
        # restored from)
        has_boot_src = boot_source not in (None, "", "live")
        boot_kind = "live"
        boot_checksum = ""
        # the artifact manifest's recorded workflow checksum backs the
        # foreign-workflow reload guard even for forward-only serving
        # (no engine object to ask)
        self._boot_workflow_checksum: Optional[str] = None
        if has_boot_src:
            from .artifact import is_artifact_dir, read_manifest
            src = str(boot_source)
            art = src[len("artifact://"):] \
                if src.startswith("artifact://") else src
            if src.startswith("artifact://") and not is_artifact_dir(art):
                # an explicit artifact source must never silently
                # register as an empty-checksum "snapshot"
                raise ValueError(
                    f"{src}: not a compiled artifact (no manifest)")
            if is_artifact_dir(art):
                boot_kind = "artifact"
                try:
                    bman = read_manifest(art)
                    boot_checksum = bman.get("tensors_sha256", "")
                    self._boot_workflow_checksum = bman.get(
                        "workflow_checksum")
                except Exception:  # noqa: BLE001 — identity only; the
                    pass           # runner's own load does the verifying
            else:
                boot_kind = "snapshot"
                boot_checksum = self._snapshot_checksum(src)
        boot = self.registry.add(
            label=boot_label, source=boot_source,
            kind=boot_kind, checksum=boot_checksum)
        self.registry.activate(boot["version"])

        # re-entrant: _watch_once holds it across its check-then-act
        # (floor/dedup check -> reload()), and reload() takes it again
        self._reload_lock = threading.RLock()
        # two-phase swap staging (the fleet router's coordinated-swap
        # fan-out, runtime/fleet.py): (token, placed wstate, meta) —
        # loaded + validated + on device, NOT yet serving.  Guarded by
        # _reload_lock, which stays a deliberately-unannotated IO
        # mutex: its contract is "one reload-shaped operation at a
        # time, held across the load" (the VC205 carve-out), not a
        # short-critical-section data lock.
        self._staged_swap = None
        self._stage_seq = 0
        self._draining = False
        self._stopped = threading.Event()
        self._drain_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        # newness floor: the watcher only acts on snapshots saved AFTER
        # the one it last swapped in (or the boot snapshot), so a stale
        # file can never ping-pong the endpoint backwards.  Weights from
        # other sources (a 'live' boot, a manual reload of an external
        # path) carry no floor — the watcher's contract is then "newest
        # snapshot in model_dir wins" (docs/serving.md).
        self._watch_floor = _manifest_saved_at(boot_source) \
            if has_boot_src else 0.0
        self.swaps = 0
        self.last_swap_ms: Optional[float] = None
        self.last_error: Optional[str] = None
        # control-plane series in the shared metrics registry
        # (runtime/metrics.py): the swap history /metrics shows is the
        # same one GET /models and status.json report
        from .metrics import registry as _metrics_registry
        _reg = _metrics_registry()
        self._m_swaps = _reg.counter(
            "vt_deploy_swaps_total", "hot weight swaps applied by the "
            "deploy control plane")
        self._m_reload_failures = _reg.counter(
            "vt_deploy_reload_failures_total",
            "reloads rejected with the old version still serving "
            "(the HTTP 409 path)")
        self._g_last_swap_ms = _reg.gauge(
            "vt_deploy_last_swap_ms", "latency of the last hot swap")
        self._g_active_version = _reg.gauge(
            "vt_deploy_active_version", "registry version now serving")

        if server is not None:
            server.deploy = self  # routes /models + /admin/* here
        self._report()

    # -- live state ---------------------------------------------------------
    def _live_wstate(self) -> dict:
        if self.engine is not None:
            return self.engine.wstate
        return self.server.wstate

    def _live_checksum(self) -> Optional[str]:
        """Topology checksum of the served workflow, when known.  An
        artifact-booted engine has no workflow object at all — its
        manifest's recorded checksum plays the same guard role."""
        wf = getattr(self.engine, "workflow", None) \
            or getattr(self.server, "workflow", None)
        try:
            if wf is not None:
                return wf.checksum()
        except Exception:  # noqa: BLE001 — a guard, never a blocker
            return None
        return (getattr(self.engine, "workflow_checksum", None)
                or self._boot_workflow_checksum)

    # -- source loading -----------------------------------------------------
    def _snapshot_checksum(self, path: str) -> str:
        """:func:`snapshot_checksum` memoized on (path, manifest mtime):
        the watcher checks a candidate's checksum and then reload()
        hashes the same blob — a multi-GB npz must not be read twice
        per swap."""
        try:
            key = (path, os.path.getmtime(path))
        except OSError:
            return snapshot_checksum(path)
        # the memo is read/replaced by the watcher thread AND manual
        # reloads: two un-locked reads of the tuple could interleave
        # with a replacement and pair one path with the OTHER path's
        # digest (a wrong checksum in the registry poisons the
        # watcher's dedup) — veles-tpu-lint VC201
        with self._ck_lock:
            if self._ck_cache is not None and self._ck_cache[0] == key:
                return self._ck_cache[1]
        digest = snapshot_checksum(path)
        with self._ck_lock:
            self._ck_cache = (key, digest)
        return digest

    def load_source(self, source: str) -> Tuple[dict, dict]:
        """Resolve a weight source into host trees + registry metadata:
        ``(parts, meta)`` where ``parts`` holds numpy ``params`` (and
        optionally ``state``) and ``meta`` has label/kind/checksum.

        Accepted forms: a Snapshotter manifest path (or the
        ``_current``/``_best`` symlinks), a ``sqlite://`` / ``http(s)://``
        snapshot URI, an ``export_package()`` directory or ``.zip``
        (contents.json + npy), a compiled-artifact directory
        (artifact.json — ``export_compiled()``; the ``artifact://``
        prefix is accepted and optional), ``forge://<store_root>/
        <name>[@version]`` (package or artifact payloads both serve),
        or a snapshot *directory* (its newest manifest is taken)."""
        if not source:
            raise ValueError(
                "reload needs a source (snapshot manifest / package / "
                "artifact path / forge:// URI) or a registry version")
        source = str(source)
        if source.startswith("artifact://"):
            return self._load_artifact(source[len("artifact://"):],
                                       source)
        if source.startswith("forge://"):
            rest = source[len("forge://"):]
            path_part, _, ver = rest.partition("@")
            store_root, _, name = path_part.rpartition("/")
            if not store_root or not name:
                raise ValueError(
                    f"bad forge source {source!r}; expected "
                    "forge://<store_root>/<name>[@version]")
            from ..forge.store import ForgeStore
            store = ForgeStore(store_root)
            # pin the RESOLVED version in the registry: a bare
            # forge://root/name means "latest NOW" — re-activating that
            # entry later must reload the same weights, not whatever
            # the store's latest has become
            resolved = store.resolve_version(name, ver or None)
            vdir = store.version_dir(name, resolved)
            uri = f"forge://{store_root}/{name}@{resolved}"
            from .artifact import is_artifact_dir
            if is_artifact_dir(vdir):
                # an uploaded compiled artifact serves from the store
                # exactly like a package upload does
                return self._load_artifact(vdir, uri, kind="forge")
            return self._load_package(vdir, uri, kind="forge")
        if source.startswith(("sqlite://", "http://", "https://")):
            return self._from_snapshot(Snapshotter.load(source), source,
                                       checksum="")
        if source.endswith(".zip"):
            return self._load_package(source, source)
        if os.path.isdir(source):
            from .artifact import is_artifact_dir
            if is_artifact_dir(source):
                return self._load_artifact(source, source)
            if os.path.isfile(os.path.join(source, "contents.json")):
                return self._load_package(source, source)
            snaps = list_snapshots(source)
            if not snaps:
                raise ValueError(
                    f"{source!r} holds no snapshot manifests and is not "
                    "an export package (no contents.json)")
            newest = snaps[-1]["path"]
            return self._from_snapshot(
                Snapshotter.load(newest), newest,
                checksum=self._snapshot_checksum(newest))
        return self._from_snapshot(
            Snapshotter.load(source), source,
            checksum=self._snapshot_checksum(source))

    def _from_snapshot(self, payload: dict, source: str,
                       checksum: str) -> Tuple[dict, dict]:
        saved = payload.get("workflow_checksum")
        live = self._live_checksum()
        if saved and live and saved != live:
            raise ValueError(
                f"snapshot {source!r} was taken from a different "
                f"workflow (checksum {saved!r} != served {live!r}); "
                "refusing the swap — the old version keeps serving")
        ws = payload.get("wstate") or {}
        parts = {k: ws[k] for k in ("params", "state") if ws.get(k)}
        if not parts.get("params"):
            raise ValueError(f"snapshot {source!r} holds no params")
        label = os.path.basename(source.rstrip("/")) or source
        return parts, {"label": label, "kind": "snapshot",
                       "checksum": checksum, "source": source}

    def _load_artifact(self, path: str, source: str,
                       kind: str = "artifact") -> Tuple[dict, dict]:
        """A compiled-artifact directory as a weight source: the deploy
        flip moves WEIGHTS only — a live engine keeps its own compiled
        programs (flat counters through the swap), an
        :class:`~veles_tpu.runtime.artifact.ArtifactRunner` keeps its
        deserialized ones.  Integrity = the manifest's tensors sha256
        (SnapshotCorruptError propagates into the reload's 409)."""
        from .artifact import load_artifact_weights, read_manifest
        man = read_manifest(path)
        saved = man.get("workflow_checksum")
        live = self._live_checksum()
        if saved and live and saved != live:
            raise ValueError(
                f"artifact {source!r} was exported from a different "
                f"workflow (checksum {saved!r} != served {live!r}); "
                "refusing the swap — the old version keeps serving")
        loaded = load_artifact_weights(path, man)
        parts = {"params": loaded["params"]}
        if loaded.get("state"):
            parts["state"] = loaded["state"]
        if not parts["params"]:
            raise ValueError(f"artifact {source!r} holds no params")
        label = man.get("workflow") \
            or os.path.basename(path.rstrip("/")) or path
        return parts, {"label": label, "kind": kind,
                       "checksum": man.get("tensors_sha256", ""),
                       "source": source}

    def _load_package(self, path: str, source: str,
                      kind: str = "package") -> Tuple[dict, dict]:
        """An export-package (contents.json + npy) as a weight source.
        Tensors are routed into params/state via the LIVE template —
        the export disambiguated collisions with a ``state_`` prefix."""
        from ..export import load_package
        contents = load_package(path)
        saved = contents.get("checksum")
        live = self._live_checksum()
        if saved and live and saved != live:
            raise ValueError(
                f"package {source!r} was exported from a different "
                f"workflow (checksum {saved!r} != served {live!r}); "
                "refusing the swap — the old version keeps serving")
        template = self._live_wstate()
        tparams = template.get("params") or {}
        tstate = template.get("state") or {}
        params: Dict[str, dict] = {}
        state: Dict[str, dict] = {}
        for u in contents.get("units", ()):
            name = u["name"]
            for pname, arr in u.get("tensors", {}).items():
                if pname.startswith("state_") and \
                        pname[len("state_"):] in tstate.get(name, {}):
                    state.setdefault(name, {})[
                        pname[len("state_"):]] = arr
                elif pname in tparams.get(name, {}):
                    params.setdefault(name, {})[pname] = arr
                elif pname in tstate.get(name, {}):
                    state.setdefault(name, {})[pname] = arr
                else:
                    # surfaces in the signature check with a clear path
                    params.setdefault(name, {})[pname] = arr
        if not params:
            raise ValueError(f"package {source!r} holds no unit weights")
        if path.endswith(".zip"):
            checksum = sha256_files([path])
        else:
            files = sorted(
                os.path.join(dp, fn)
                for dp, _, fns in os.walk(path) for fn in fns)
            checksum = sha256_files(files)
        parts = {"params": params}
        if state:
            parts["state"] = state
        label = (contents.get("workflow") or
                 os.path.basename(path.rstrip("/")) or path)
        return parts, {"label": label, "kind": kind,
                       "checksum": checksum, "source": source}

    # -- staging + swap -----------------------------------------------------
    def _stage(self, parts: dict) -> dict:
        """Cast against the live template, enforce the structural
        signature, and place on device — the double buffer; the old
        tree keeps serving throughout.  ``params`` must match exactly;
        a ``state`` tree that does not match is skipped with a warning
        (packages may omit running statistics) rather than rejected."""
        live = self._live_wstate()
        new = dict(live)
        for k in ("params", "state"):
            saved = parts.get(k)
            if not saved:
                continue
            tmpl = live.get(k)
            want = _shape_signature(tmpl, unwrap_keys=True) \
                if tmpl is not None else ()
            got = _shape_signature(saved)
            if want != got:
                diff = signature_mismatch(want, got)
                if k == "state":
                    self.warning(
                        "swap keeps the live 'state' tree (loaded one "
                        "does not match: %s)", diff)
                    continue
                raise ValueError(
                    "hot swap rejected — loaded parameter tree does not "
                    "match the served model (same-architecture weights "
                    f"only): {diff}")
            cast = jax.tree.map(_cast_leaf, saved, tmpl)
            # engine.swap_params re-places against its own live tree;
            # with matching shardings that second device_put is a no-op
            new[k] = place_like(cast, tmpl)
        return new

    def _apply(self, new_wstate: dict) -> None:
        """Flip the served tree: the engine swaps at a decode-step
        boundary (old buffer keeps serving until the flip); the server's
        reference swap is atomic per request."""
        if self.engine is not None:
            self.engine.swap_params(new_wstate["params"])
            if "state" in new_wstate:
                # the engine only reads params, but keep the tree whole
                # so a later _live_wstate() template is coherent
                self.engine.wstate = dict(self.engine.wstate,
                                          state=new_wstate["state"])
        if self.server is not None:
            if self.engine is not None:
                self.server.wstate = dict(self.engine.wstate)
            else:
                self.server.wstate = new_wstate

    def reload(self, source: Optional[str] = None,
               version=None) -> dict:
        """Load + hot-swap a named snapshot/package (the
        ``POST /admin/reload`` handler).  ``version=`` re-activates a
        registry entry by reloading from its recorded source.

        Failure semantics: any load or staging failure leaves the old
        version serving untouched; a failure during the flip itself
        swaps the previous buffer back (rollback) before re-raising."""
        with self._reload_lock:
            if self.draining:
                raise EngineDraining("draining; not accepting reloads")
            t0 = time.monotonic()
            if version is not None:
                entry = self.registry.get(version)
                if entry["kind"] == "live":
                    raise ValueError(
                        f"version {entry['version']} is the boot state "
                        "with no reloadable source")
                source = entry["source"]
            pre = self._compile_marker()
            try:
                parts, meta = self.load_source(source)
                new_wstate = self._stage(parts)
            except KeyError as e:
                # a malformed manifest/package raises KeyError deep in
                # the loaders; surface it as a LOAD failure (409 on the
                # REST side), not as the registry's version-miss 404
                self.last_error = f"KeyError: {e}"
                self._m_reload_failures.inc()
                self._report()
                raise ValueError(
                    f"malformed source {source!r}: missing key "
                    f"{e}") from e
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                self._m_reload_failures.inc()
                self._report()
                raise
            if self.draining:
                # a drain that began while we were loading/staging wins:
                # swapping into a stopping engine would activate a
                # version that never serves
                raise EngineDraining("draining; not accepting reloads")
            return self._flip_locked(new_wstate, meta, t0, pre)

    def _flip_locked(self, new_wstate: dict, meta: dict, t0: float,
                     pre) -> dict:
        """The flip half of a swap: apply the staged tree (rollback on
        a mid-flip failure), record the registry entry, publish the
        gauges.  Shared by :meth:`reload` (load+flip in one call) and
        :meth:`commit_staged` (the fleet's two-phase commit); callers
        hold ``_reload_lock``."""
        prev = self._live_wstate()
        swaps_before = self.engine.swaps if self.engine is not None \
            else None
        try:
            self._apply(new_wstate)
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {e}"
            self._m_reload_failures.inc()
            flipped = (swaps_before is not None
                       and self.engine.swaps != swaps_before)
            if flipped:
                self.exception(
                    "swap failed mid-flip; rolling back to the "
                    "previous buffer")
                try:
                    self._apply(prev)
                except Exception:  # noqa: BLE001
                    self.exception("rollback failed")
            else:
                # the flip never landed (validation / staging /
                # swap timeout): the old version was never
                # displaced, so a "rollback" would only re-stage
                # the identical live tree and block another full
                # swap_timeout_s on an already-wedged scheduler
                self.warning(
                    "swap not applied (%s); old version still "
                    "serving", self.last_error)
            self._report()
            raise
        # prev dies here: only the ACTIVE buffer stays on device
        # (re-activating an older version reloads from its source)
        entry = self.registry.add(
            label=meta["label"], source=meta["source"],
            kind=meta["kind"], checksum=meta["checksum"])
        self.registry.activate(entry["version"])
        self.swaps += 1
        self._m_swaps.inc()
        self.last_swap_ms = round(1e3 * (time.monotonic() - t0), 1)
        self._g_last_swap_ms.set(self.last_swap_ms)
        self.last_error = None
        post = self._compile_marker()
        recompiled = (post - pre) if None not in (pre, post) else 0
        if recompiled:
            self.warning(
                "compile counter moved across a swap (%d new "
                "programs) — shapes should have matched exactly",
                recompiled)
        self.info("hot-swapped to version %d (%s, %s) in %.0f ms",
                  entry["version"], entry["label"], entry["kind"],
                  self.last_swap_ms)
        if self.status is not None:
            try:
                self.status.record_event(
                    "swap", version=entry["version"],
                    label=entry["label"], swap_ms=self.last_swap_ms)
            except Exception:  # noqa: BLE001 — the swap LANDED; a
                pass           # status hiccup must not report failure
        self._report()
        return {"active": dict(entry, active=True),
                "swap_ms": self.last_swap_ms,
                "compiles_during_swap": recompiled}

    # -- two-phase swap (the fleet router's coordinated fan-out) ------------
    def stage(self, source: Optional[str] = None, version=None) -> dict:
        """Phase one of a coordinated swap (``POST /admin/stage``):
        load, validate against the live tree, and place the new weights
        on device as a staged buffer — WITHOUT flipping.  The old
        version keeps serving; :meth:`commit_staged` flips,
        :meth:`abort_staged` withdraws.  Returns ``{"staged": token,
        ...}``; one staging at a time (a second stage before
        commit/abort is refused, so a router fan-out can never orphan
        a placed buffer).  Failure semantics match :meth:`reload`'s
        load phase: any error leaves nothing staged and the old
        version serving (the REST layer's 409)."""
        with self._reload_lock:
            if self.draining:
                raise EngineDraining("draining; not accepting swaps")
            if self._staged_swap is not None:
                raise ValueError(
                    f"swap {self._staged_swap[0]!r} is already staged; "
                    "commit or abort it before staging another")
            if version is not None:
                entry = self.registry.get(version)
                if entry["kind"] == "live":
                    raise ValueError(
                        f"version {entry['version']} is the boot state "
                        "with no reloadable source")
                source = entry["source"]
            try:
                parts, meta = self.load_source(source)
                new_wstate = self._stage(parts)
            except KeyError as e:
                self.last_error = f"KeyError: {e}"
                self._m_reload_failures.inc()
                self._report()
                raise ValueError(
                    f"malformed source {source!r}: missing key "
                    f"{e}") from e
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                self._m_reload_failures.inc()
                self._report()
                raise
            self._stage_seq += 1
            token = f"stage-{self._stage_seq}"
            self._staged_swap = (token, new_wstate, meta)
            return {"staged": token, "label": meta["label"],
                    "kind": meta["kind"], "checksum": meta["checksum"]}

    def commit_staged(self, token: str) -> dict:
        """Phase two: flip the buffer :meth:`stage` placed (``POST
        /admin/commit``).  The token must match the pending staging —
        a commit for a withdrawn or superseded stage is refused.  A
        flip failure rolls back to the previous buffer (the
        :meth:`reload` contract) and the staging is consumed either
        way: the fleet's rollback path re-stages explicitly rather
        than retrying a buffer in an unknown state."""
        with self._reload_lock:
            staged, self._staged_swap = self._staged_swap, None
            if staged is None or staged[0] != str(token):
                if staged is not None:
                    self._staged_swap = staged  # not ours: keep it
                raise ValueError(
                    f"no staged swap with token {token!r} "
                    "(stage again before committing)")
            if self.draining:
                raise EngineDraining("draining; not accepting swaps")
            _tok, new_wstate, meta = staged
            return self._flip_locked(new_wstate, meta,
                                     time.monotonic(),
                                     self._compile_marker())

    def abort_staged(self, token: Optional[str] = None) -> dict:
        """Withdraw a pending staging (``POST /admin/abort``): the
        placed buffer is dropped, the old version was never displaced.
        With no token, aborts whatever is staged (the router's
        fan-out cleanup); idempotent — aborting nothing is fine."""
        with self._reload_lock:
            staged = self._staged_swap
            if staged is not None and (token is None
                                       or staged[0] == str(token)):
                self._staged_swap = None
                return {"aborted": staged[0]}
            return {"aborted": None}

    @property
    def staged_token(self) -> Optional[str]:
        with self._reload_lock:
            return self._staged_swap[0] if self._staged_swap is not None \
                else None

    def _compile_marker(self) -> Optional[int]:
        if self.engine is not None:
            return int(self.engine.step_cache.compiles)
        return None

    # -- drain / shutdown ---------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining or (self.engine is not None
                                  and self.engine.draining)

    def begin_drain(self, handoff: Optional[str] = None) -> dict:
        """Async drain (the ``POST /admin/drain`` handler): flips
        ``/ready`` to 503 immediately, retires in-flight work on a
        background thread, then releases :meth:`wait`.  ``handoff``
        names a successor replica's base URL: the engine's hottest
        prefix pages ship there (``PUT /kv/pages``) before the engine
        stops, so sessions landing on the successor keep their warm
        TTFT (docs/serving.md "Disaggregated prefill/decode")."""
        self._draining = True
        if self._drain_thread is None or not self._drain_thread.is_alive():
            self._drain_thread = threading.Thread(
                target=self.drain, kwargs={"handoff": handoff},
                name="deploy-drain", daemon=True)
            self._drain_thread.start()
        return {"draining": True,
                "drain_timeout_s": self.drain_timeout_s,
                **({"handoff": handoff} if handoff else {})}

    def _handoff_pages(self, url: str) -> Optional[dict]:
        """Ship the engine's hottest prefix pages to the successor at
        ``url`` — the drain-side half of the rolling drain's pre-warm.
        Best-effort end to end: any failure (dense layout, transfer
        fault, unreachable successor, rejected blob) logs and returns
        None; it must never delay or fail the drain itself."""
        eng = self.engine
        if eng is None or not getattr(eng, "paged", False):
            return None
        kvt = root.common.serve.kv_transfer
        top = int(kvt.get("prewarm_pages", 64))
        if top <= 0:
            return None
        try:
            from . import faults
            if faults.enabled():
                plan = faults.get_plan()
                if plan.kv_transfer_slow_ms:
                    time.sleep(plan.kv_transfer_slow_ms / 1e3)
                if plan.kv_transfer_drop \
                        and faults.fire_once("deploy_kv_handoff"):
                    raise OSError("fault: kv_transfer_drop")
            hashes = eng.hot_page_hashes(top)
            if not hashes:
                return None
            blob = eng.export_pages(hashes)
            from .fleet_client import ReplicaClient
            status, doc = ReplicaClient(
                url, timeout_s=float(kvt.get("timeout_s", 5.0))
            ).put_pages(blob)
            if status == 200 and isinstance(doc, dict):
                self.info("drain handoff: %d pages -> %s",
                          int(doc.get("imported", 0))
                          + int(doc.get("skipped", 0)), url)
                return doc
            self.warning("drain handoff rejected by %s (HTTP %s: %s)",
                         url, status, doc)
        except Exception as e:  # noqa: BLE001 — the drain proceeds
            self.warning("drain handoff to %s failed: %s", url, e)
        return None

    def drain(self, timeout: Optional[float] = None,
              handoff: Optional[str] = None) -> bool:
        """Graceful drain: stop admissions (503 on ``/ready``), stop the
        watcher, let in-flight slots retire, stop the engine, release
        :meth:`wait`.  Returns True when everything retired before the
        deadline.  ``timeout=0`` skips the grace window (Ctrl-C).
        ``handoff`` pre-warms a successor (see :meth:`begin_drain`)
        while the engine is still alive to serve its pages."""
        self._draining = True
        if handoff:
            self._handoff_pages(handoff)
        self.stop_watcher()
        timeout = timeout if timeout is not None else self.drain_timeout_s
        t0 = time.monotonic()
        clean = True
        if self.engine is not None:
            clean = self.engine.drain(timeout)
        # hold /ready at 503 for at least drain_grace_s (even when the
        # engine retired instantly, or there is no engine to observe in-
        # flight work on) so load balancers see the flip BEFORE the
        # listener closes; requests keep being served during the hold.
        # timeout=0 (the CLI's Ctrl-C) skips it.
        grace = min(float(timeout), self.drain_grace_s) \
            - (time.monotonic() - t0)
        if grace > 0:
            time.sleep(grace)
        try:
            if self.status is not None:
                self.status.record_event("drain", clean=clean)
            self._report()
        except Exception:  # noqa: BLE001 — a status hiccup must never
            pass           # leave wait() blocked with the engine down
        self.info("drained%s", "" if clean else " (dirty: timeout or "
                  "scheduler death; leftovers failed with EngineStopped)")
        self._stopped.set()
        return clean

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until drained/stopped (SIGTERM or ``/admin/drain``) —
        the CLI serve loop parks here instead of sleeping forever."""
        return self._stopped.wait(timeout)

    def install_signal_handlers(self) -> bool:
        """SIGTERM → graceful drain → clean exit.  Only possible from
        the main thread; returns whether the handler was installed."""

        def _on_sigterm(signum, frame):  # noqa: ARG001
            self.info("SIGTERM: draining before exit")
            self.begin_drain()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
            return True
        except ValueError:
            self.warning(
                "not the main thread; SIGTERM handler not installed")
            return False

    # -- snapshot watcher ---------------------------------------------------
    @property
    def watching(self) -> bool:
        return (self._watch_thread is not None
                and self._watch_thread.is_alive())

    def start_watcher(self):
        """Poll ``model_dir`` for a snapshot saved after the one the
        watcher last swapped in (the boot snapshot anchors the floor)
        and swap automatically — "newest snapshot in model_dir wins",
        so a manual reload from an OUTSIDE source is superseded on the
        next newer arrival.  Failures (mid-write snapshots, rejected
        trees, IO errors) retry with exponential backoff up to
        ``watch_backoff_max_s``; a success resets the cadence to
        ``watch_interval_s``."""
        if self.model_dir is None:
            raise ValueError("snapshot watcher needs a model_dir")
        if self.watching:
            return self
        self._watch_stop.clear()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name="snapshot-watcher", daemon=True)
        self._watch_thread.start()
        self.info("watching %s every %.1fs", self.model_dir,
                  self.watch_interval_s)
        return self

    def stop_watcher(self):
        self._watch_stop.set()
        t = self._watch_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10)
            if t.is_alive():
                # mid-reload (hashing / staging a big snapshot): keep
                # the reference so ``watching`` stays true — a
                # start_watcher() now must NOT spawn a second thread;
                # the straggler exits after its current attempt
                self.warning("watcher still mid-attempt; it will exit "
                             "after the current reload")
                return
        self._watch_thread = None

    def _watch_loop(self):
        delay = self.watch_interval_s
        while not self._watch_stop.wait(delay):
            try:
                self._watch_once()
                delay = self.watch_interval_s
            except Exception as e:  # noqa: BLE001 — the watcher must
                # outlive any single bad snapshot; backoff, retry
                delay = min(max(delay, self.watch_interval_s)
                            * BACKOFF_FACTOR, self.watch_backoff_max_s)
                self.last_error = f"{type(e).__name__}: {e}"
                self.warning("snapshot watcher: %s (retrying in %.1fs)",
                             self.last_error, delay)
                self._report()

    def _watch_once(self):
        snaps = list_snapshots(self.model_dir)
        if not snaps:
            return
        newest = snaps[-1]
        if newest["saved_at"] <= self._watch_floor:
            return  # nothing newer than what the watcher last swapped
        checksum = self._snapshot_checksum(newest["path"])
        # the dedup check and the swap must be one atomic step: without
        # the lock a manual reload landing between "active checksum
        # differs" and reload() made the watcher re-swap weights that
        # were already serving (veles-tpu-lint VC201 audit, ISSUE 8).
        # _reload_lock is re-entrant, so reload()'s own acquire nests.
        with self._reload_lock:
            active = self.registry.active
            if active is not None and checksum \
                    and checksum == active.get("checksum"):
                # already serving these exact weights (e.g. a re-save)
                self._watch_floor = newest["saved_at"]
                return
            self.info("watcher: newer snapshot %s", newest["path"])
            self.reload(newest["path"])  # raises -> backoff + retry
            self._watch_floor = newest["saved_at"]

    # -- observability ------------------------------------------------------
    def models_doc(self) -> dict:
        """The ``GET /models`` document: registry + control-plane
        state."""
        doc = self.registry.to_doc()
        doc.update(self._gauges())
        return doc

    def _gauges(self) -> dict:
        return {"swaps": self.swaps, "last_swap_ms": self.last_swap_ms,
                "draining": self.draining, "watching": self.watching,
                "model_dir": self.model_dir,
                "staged": self.staged_token,
                "last_error": self.last_error}

    def _report(self):
        active_now = self.registry.active or {}
        self._g_active_version.set(active_now.get("version") or 0)
        if self.status is None:
            return
        try:
            active = self.registry.active or {}
            self.status.update(deploy={
                "active_version": active.get("version"),
                "active_label": active.get("label"),
                "versions": len(self.registry.to_doc()["versions"]),
                **self._gauges()})
        except Exception:  # noqa: BLE001 — status must never take the
            pass           # control plane down
