"""Fleet router: multi-replica serving with load- and prefix-affinity
dispatch, coordinated hot swap, and rolling drain (docs/serving.md
"Fleet serving").

Veles's defining L7 capability was the master–slave runtime that turned
one box into a cluster — a Twisted TCP control channel plus ZeroMQ
payload pipes fanning minibatches out to slave processes and re-owning
their work when they died (PAPER.md).  This module is that layer reborn
for serving: a lightweight **router process** fronting N replica
workers, where each replica is the existing single-process serving
stack (``DecodeEngine`` or ``ArtifactRunner`` behind ``RestfulServer``
with a ``DeployController`` attached) spawned in-process for tests, as
CLI children (``--serve PORT --fleet N``), or as independent processes
that ``--join ROUTER_URL`` themselves in.  Everything the router does
composes per-replica primitives that already exist — drain, two-phase
swap staging, ``/ready``, the burn-rate SLO, ``/metrics`` — into the
fleet-level behaviors horizontal scale needs:

* **dispatch** by scraped replica load (queue depth, occupancy,
  ``vt_memory_headroom_slots``, admission-window state from ``/engine``)
  *composed with* **prefix-cache affinity**: the router computes the
  same chained-sha256 page hashes as the engine's prefix index
  (:func:`~.engine.prefix_page_hashes` — one function, so the two can
  never drift) over the prompt head and routes same-system-prompt
  sessions to the replica already holding those pages, falling back to
  a hash ring for cold prefixes so a new prefix *converges* on one
  replica instead of smearing its pages across all of them.  Routing
  has **hysteresis**: the incumbent keeps a request stream until a
  rival's load score beats it by a margin, so scrape staleness cannot
  flap traffic between replicas; the router's own live outstanding
  counts sharpen the stale scrape numbers;
* **coordinated hot swap**: one fan-out that *stages* the new version
  on every replica (``POST /admin/stage`` — loaded, validated, placed,
  not serving), flips only after ALL staged successfully, and rolls
  back everywhere when any flip fails (committed replicas reload their
  previous registry version, uncommitted stagings abort) — the fleet
  either serves the new version everywhere or the old one everywhere;
* **rolling drain** for zero-downtime restarts: drain one replica
  (router stops routing to it, waits for its in-flight work), restart
  it — in-process/child replicas reboot through their restart handle,
  e.g. from the sealed compiled artifact; ``--join``ed replicas are
  drained for their external supervisor — readmit on ``/ready``,
  proceed to the next;
* **graceful degradation**: per-replica health checks with the
  ``deploy.http_retry`` backoff shape, ejection after consecutive
  transport failures with idempotent resubmission of the failed
  dispatch to survivors (unary requests resubmit whole; streams
  resume from their last delivered token — see
  :meth:`FleetRouter.handle_generate_stream`),
  per-replica **429 Retry-After honored as router-level backpressure**
  (a shedding replica is backed off for its hinted window; class-0
  requests are instead routed to the least-burned replica), and
  automatic readmission when an ejected replica answers ``/ready``
  again;
* **disaggregated prefill/decode + fleet-wide prefix sharing**
  (docs/serving.md "Disaggregated prefill/decode"): serialized KV-page
  transfer between replicas (``GET/PUT /kv/pages``) lets the router
  place a request's prefix pages BEFORE dispatching it — a fetch from
  the affinity-known holder when the routed replica is cold (gated by
  a measured fetch-vs-reprefill payoff: wire bytes over the link
  bandwidth EWMA against the replica's scraped prefill throughput), or
  a full disagg leg when ``fleet.role = prefill`` capacity exists (the
  prefill replica runs the chunked prefill, its finished pages ship to
  the decode target, whose admission then starts at the shipped
  length); the rolling drain pushes the victim's hottest pages to a
  successor and repoints their affinity so post-drain warm-TTFT holds.
  Every transfer failure falls back to local prefill — the path is an
  optimization, never a dependency;
* **aggregated observability**: fleet ``/metrics`` (the ``vt_fleet_*``
  family, per-replica labels), a merged ``/slo.json`` whose windowed
  quantiles come from summing the replicas' scraped cumulative
  histogram buckets through the same
  ``Histogram.aggregate_snapshot``-shaped interface the process
  :class:`~.metrics.HistogramWindow` consumes, and ``GET /fleet.json``
  — the topology document.

In-process replicas share this process's metrics registry, so the SLO
merge groups replicas by ``registry_key`` and counts each process's
histograms once — a single-process test fleet and a many-process
production fleet both merge honestly.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..config import root
from ..logger import Logger
from .deploy import BACKOFF_FACTOR, BACKOFF_JITTER, HTTP_RETRY_BASE_S
from .engine import prefix_page_hashes
from .fleet_client import ReplicaClient, ReplicaUnavailable
from .metrics import (cumulative_buckets, fraction_over, HistogramWindow,
                      parse_samples, quantile_from_cumulative, registry)

#: replica lifecycle states as the router tracks them
ACTIVE = "active"
DRAINING = "draining"
EJECTED = "ejected"

#: score penalty for a replica that answers but is not /ready
#: (draining, SLO-degraded): routable as a last resort, never preferred
_NOT_READY_PENALTY = 100.0


class Replica:
    """One replica serving stack as the router sees it.  All mutable
    fields are owned by the router and mutated only under its lock;
    the ``client`` is used outside it (HTTP must never run under the
    routing lock — veles-tpu-lint VC205)."""

    def __init__(self, rid: str, client: ReplicaClient, *,
                 registry_key: Optional[str] = None,
                 restart: Optional[Callable[[], str]] = None,
                 kill: Optional[Callable[[], None]] = None,
                 role: str = "mixed"):
        self.id = rid
        self.client = client
        #: capacity class (docs/serving.md "Disaggregated
        #: prefill/decode"): "mixed" serves everything; "prefill"
        #: replicas absorb prefill work and ship pages, never taking
        #: normal dispatch while a non-prefill replica is up; "decode"
        #: replicas receive shipped pages
        self.role = role
        #: replicas sharing a metrics registry (in-process fleets)
        #: share a key; the SLO merge counts each key once
        self.registry_key = registry_key or client.base_url
        #: () -> new base url: rebuild this replica in place (rolling
        #: drain); None for --join'ed replicas an external supervisor
        #: restarts
        self.restart = restart
        #: () -> None: hard-stop (the fault harness's crash handle)
        self.kill = kill
        self.state = ACTIVE
        self.ready = False
        self.active_version = None  # scraped /models active id
        self.fails = 0
        self.backoff_until = 0.0    # 429 Retry-After honor window
        self.outstanding = 0        # router-tracked in-flight dispatches
        self.dispatched = 0
        self.load: dict = {}        # last scraped /engine stats
        self.metrics_text = ""      # last scraped /metrics (group leader)
        self.last_scrape = 0.0
        self.last_error: Optional[str] = None

    def doc(self) -> dict:
        """JSON-able snapshot for ``/fleet.json`` (caller holds the
        router lock)."""
        st = self.load or {}
        return {
            "id": self.id, "url": self.client.base_url,
            "role": self.role,
            "state": self.state, "ready": self.ready,
            "outstanding": self.outstanding,
            "dispatched": self.dispatched,
            "fails": self.fails,
            "backoff_remaining_s": round(
                max(0.0, self.backoff_until - time.monotonic()), 3),
            "restartable": self.restart is not None,
            "load": {k: st.get(k) for k in
                     ("slots", "occupancy", "queue_depth",
                      "tokens_per_sec")
                     if k in st},
            "last_error": self.last_error,
        }


class _FleetHistogram:
    """A ``Histogram``-shaped view (``buckets`` +
    ``aggregate_snapshot()``) summing one series across the fleet's
    scraped ``/metrics`` texts, one text per registry group — exactly
    the interface :class:`~.metrics.HistogramWindow` consumes, so the
    fleet's rolling SLO windows reuse the process machinery unchanged.
    Returns per-bucket counts (incl. +Inf), sum and count, like
    ``Histogram.aggregate_snapshot``.

    Cross-process replicas restart (rolling drain!) and come back with
    zeroed cumulative buckets; feeding the raw sum to the window would
    drive its delta NEGATIVE against the pre-restart baseline and the
    merged quantiles/burn would read 0 exactly when an operator needs
    them.  So per-group **counter-reset correction** applies: when a
    group's cumulative count decreases, the last-seen values fold into
    that group's standing offset — the aggregate stays monotonic, the
    standard Prometheus reset treatment."""

    def __init__(self, router: "FleetRouter", name: str):
        self._router = router
        self.name = name
        self._buckets: Tuple[float, ...] = ()
        self._lock = threading.Lock()
        #: group key -> [offset (buckets dict, sum, count),
        #:               last raw (buckets dict, sum, count)]
        self._groups: Dict[str, list] = {}  # guarded-by: self._lock

    @property
    def buckets(self) -> Tuple[float, ...]:
        return self._buckets

    @staticmethod
    def _add(into: Dict[float, float], frm: Dict[float, float]):
        for le, c in frm.items():
            into[le] = into.get(le, 0.0) + c

    def aggregate_snapshot(self):
        agg: Dict[float, float] = {}
        total, count = 0.0, 0
        for key, samples in self._router._group_samples():
            raw_b = dict(cumulative_buckets(samples, self.name))
            raw_s, raw_c = 0.0, 0
            for n, _labels, v in samples:
                if n == self.name + "_sum":
                    raw_s += v
                elif n == self.name + "_count":
                    raw_c += int(v)
            with self._lock:
                off, last = self._groups.setdefault(
                    key, [({}, 0.0, 0), ({}, 0.0, 0)])
                if raw_c < last[2]:
                    # the group's process restarted: its history is
                    # gone from the scrape but not from the window —
                    # fold the last sight of it into the offset
                    off_b = dict(off[0])
                    self._add(off_b, last[0])
                    off = (off_b, off[1] + last[1], off[2] + last[2])
                self._groups[key] = [off, (raw_b, raw_s, raw_c)]
                self._add(agg, off[0])
                total += off[1]
                count += off[2]
            self._add(agg, raw_b)
            total += raw_s
            count += raw_c
        if not agg:
            return [0], 0.0, 0
        pairs = sorted(agg.items())
        uppers = tuple(le for le, _c in pairs if le != float("inf"))
        self._buckets = uppers
        counts, prev = [], 0.0
        for _le, c in pairs:
            counts.append(int(c - prev))
            prev = c
        if len(counts) == len(uppers):     # no +Inf sample scraped
            counts.append(0)
        return counts, total, count

    def snapshot_or_none(self):
        """None until any replica scraped a ``/metrics`` text — the
        ``HistogramWindow`` late-binding contract (a cheap existence
        check; the window calls ``aggregate_snapshot`` itself)."""
        return self if self._router._has_group_texts() else None


class FleetRouter(Logger):
    """The router over N :class:`Replica` handles.  Thread model: one
    daemon scrape thread (health + load + metrics text), dispatch on
    the HTTP server's worker threads, swaps/drains serialized on an
    operations mutex.  ``self._lock`` guards the topology and every
    replica's mutable fields; no network IO ever runs under it."""

    def __init__(self, *, scrape_interval_s: Optional[float] = None,
                 hysteresis: Optional[float] = None,
                 affinity_pages: Optional[int] = None,
                 affinity_max: Optional[int] = None,
                 eject_failures: Optional[int] = None,
                 page_size: Optional[int] = None):
        fleet = root.common.serve.fleet
        serve = root.common.serve
        self.scrape_interval_s = float(
            fleet.get("scrape_interval_s", 0.5)
            if scrape_interval_s is None else scrape_interval_s)
        self.hysteresis = float(fleet.get("hysteresis", 0.5)
                                if hysteresis is None else hysteresis)
        self.affinity_pages = int(fleet.get("affinity_pages", 4)
                                  if affinity_pages is None
                                  else affinity_pages)
        self.affinity_max = int(fleet.get("affinity_max", 4096)
                                if affinity_max is None else affinity_max)
        self.eject_failures = max(1, int(
            fleet.get("eject_failures", 2)
            if eject_failures is None else eject_failures))
        self.drain_poll_s = float(fleet.get("drain_poll_s", 0.05))
        self.restart_timeout_s = float(
            fleet.get("restart_timeout_s", 120.0))
        self.drain_timeout_s = float(serve.get("drain_timeout_s", 30.0))
        # a dispatched /generate may legitimately run for the whole
        # per-request deadline — classifying a slow-but-healthy
        # request as a transport failure would duplicate it AND eject
        # a healthy replica, so the dispatch timeout must dominate the
        # replica-side deadline (plus slack for the answer itself)
        self.dispatch_timeout_s = float(
            serve.get("deadline_s", 120.0)) + 30.0
        # the prompt-head page geometry must match the replicas' prefix
        # index (engine.prefix_page_hashes) or affinity keys never hit
        self.page_size = int(serve.get("page_size", 16)
                             if page_size is None else page_size)
        # KV-page transfer policy (docs/serving.md "Disaggregated
        # prefill/decode"): fetch-vs-reprefill is a measured payoff
        # call, never a correctness one — every transfer failure falls
        # back to local prefill
        kvt = root.common.serve.kv_transfer
        self.kv_transfer_enabled = bool(kvt.get("enabled", True))
        self.kv_min_pages = int(kvt.get("min_pages", 2))
        self.kv_timeout_s = float(kvt.get("timeout_s", 5.0))
        self.prewarm_pages = int(kvt.get("prewarm_pages", 64))
        # streaming failover policy (docs/serving.md "Streaming and
        # mid-stream failover"): how many mid-stream resubmissions one
        # request may spend, and the capped exponential backoff between
        # them — together they bound a failover storm
        stream_cfg = root.common.serve.stream
        self.stream_retry_budget = int(
            stream_cfg.get("retry_budget", 3))
        self.stream_backoff_s = float(stream_cfg.get("backoff_s", 0.05))
        self.stream_backoff_max_s = float(
            stream_cfg.get("backoff_max_s", 2.0))
        # router-side default for a streaming request naming no
        # deadline_s of its own: the same per-request deadline the
        # replicas enforce (the router's failover loop must terminate
        # within it)
        self.stream_deadline_s = float(serve.get("deadline_s", 120.0))
        #: replicas added without an explicit role class
        self.default_role = str(fleet.get("role", "mixed"))

        self._lock = threading.Lock()
        self._replicas: List[Replica] = []  # guarded-by: self._lock
        self._samples_cache: Dict[str, tuple] = {}  # guarded-by: self._lock
        self._affinity: "dict" = {}  # prefix hash -> replica id (LRU)  # guarded-by: self._lock
        self._pending: Dict[str, set] = {}  # replica id -> dispatch seqs  # guarded-by: self._lock
        self._dispatch_seq = 0  # guarded-by: self._lock
        self._route_count = 0  # guarded-by: self._lock
        self._last_pick: Optional[str] = None  # guarded-by: self._lock
        self._affinity_hits = 0  # guarded-by: self._lock
        self._affinity_requests = 0  # guarded-by: self._lock
        # KV-transfer payoff inputs: link bandwidth EWMA over measured
        # transfers (the spec-decode _spec_worthwhile idiom — the first
        # few transfers are optimistic probes that seed the estimate)
        self._kv_bw_ewma = 0.0  # bytes/s  # guarded-by: self._lock
        self._kv_transfers = 0  # guarded-by: self._lock
        self._kv_drops = 0  # fault-plan drop budget used  # guarded-by: self._lock
        self._draining = False
        self._stop_evt = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        # swap / rolling-drain serialization: an operations mutex held
        # across replica HTTP calls BY DESIGN (deliberately unannotated
        # — the VC205 "short critical section" contract is for data
        # locks; this one's contract is "one fleet operation at a time")
        self._ops_mutex = threading.Lock()
        self._last_swap: dict = {"swapped": None}
        self._last_drain: dict = {"completed": None}
        self._drain_thread: Optional[threading.Thread] = None
        # batch lane (docs/serving.md "Batch lane"): the fleet-level
        # job manager, attached by FleetServer when serve.jobs.dir (or
        # its jobs_dir arg) names a store — fleet_doc merges its
        # summary so /fleet.json shows the bulk backlog next to the
        # interactive topology
        self.jobs = None
        # experiment control plane (docs/experiments.md): an
        # ExperimentManager attached by FleetServer — fleet_doc merges
        # its summary so /fleet.json shows the optimization loop's
        # progress next to the serving topology
        self.experiments = None

        # the fleet metric family (docs/observability.md table; VM4xx)
        reg = registry()
        self._g_replicas = reg.gauge(
            "vt_fleet_replicas",
            "replicas known to the fleet router, by lifecycle state",
            labels=("state",))
        self._m_requests = reg.counter(
            "vt_fleet_requests_total",
            "requests the router dispatched, by replica",
            labels=("replica",))
        self._g_outstanding = reg.gauge(
            "vt_fleet_outstanding",
            "router-tracked in-flight dispatches, by replica",
            labels=("replica",))
        self._m_resubmissions = reg.counter(
            "vt_fleet_resubmissions_total",
            "dispatches resubmitted to a survivor after a replica "
            "failed mid-request (transport error or scheduler crash)")
        self._m_ejections = reg.counter(
            "vt_fleet_ejections_total",
            "replicas ejected after consecutive health/dispatch "
            "failures")
        self._m_readmissions = reg.counter(
            "vt_fleet_readmissions_total",
            "ejected replicas readmitted after answering /ready again")
        self._m_affinity_requests = reg.counter(
            "vt_fleet_affinity_requests_total",
            "dispatched requests long enough to carry prefix-affinity "
            "hashes (>= one full page of prompt head)")
        self._m_affinity_hits = reg.counter(
            "vt_fleet_affinity_hits_total",
            "affinity-eligible requests routed to the replica already "
            "holding their prefix pages")
        self._g_affinity_hit_rate = reg.gauge(
            "vt_fleet_affinity_hit_rate",
            "affinity hits over affinity-eligible requests since "
            "router start")
        self._m_backpressure = reg.counter(
            "vt_fleet_backpressure_total",
            "replica 429s honored as router-level backpressure "
            "(the replica enters its hinted Retry-After window)")
        self._m_swaps = reg.counter(
            "vt_fleet_swaps_total",
            "coordinated fleet-wide hot swaps committed on every "
            "replica")
        self._m_swap_rollbacks = reg.counter(
            "vt_fleet_swap_rollbacks_total",
            "coordinated swaps rolled back fleet-wide after a stage "
            "or flip failure (the old version kept serving everywhere)")
        self._m_rolling_drains = reg.counter(
            "vt_fleet_rolling_drains_total",
            "completed rolling-drain cycles (every replica drained, "
            "restarted and readmitted in turn)")
        self._m_kv_fetches = reg.counter(
            "vt_fleet_kv_fetches_total",
            "router-initiated KV-page transfers between replicas, by "
            "outcome (ok / skipped by payoff / failed / rejected / "
            "disagg / prewarm)",
            labels=("outcome",))
        # streaming failover (docs/serving.md "Streaming and
        # mid-stream failover")
        self._m_stream_resumes = reg.counter(
            "vt_stream_resumes_total",
            "mid-stream failovers: an interrupted stream resubmitted "
            "to a survivor from its last delivered token (subset of "
            "vt_fleet_resubmissions_total)")
        self._m_stream_splice = reg.histogram(
            "vt_stream_splice_seconds",
            "gap a mid-stream failover added: from the interruption "
            "to the resumed replica accepting the suffix dispatch")
        self._m_stream_retry_exhausted = reg.counter(
            "vt_stream_retry_exhausted_total",
            "streams terminated with an error frame after the "
            "per-request resume retry budget ran out "
            "(serve.stream.retry_budget)")
        self._g_kv_payoff = reg.gauge(
            "vt_fleet_kv_fetch_payoff",
            "last fetch-vs-reprefill payoff estimate (estimated local "
            "prefill seconds over estimated transfer seconds; >= 1 "
            "means fetching beats recomputing; 0 while probing cold)")

        # fleet-merged rolling SLO windows over the scraped histograms
        # (the same HistogramWindow machinery /slo.json uses per
        # process — _FleetHistogram implements the aggregate_snapshot
        # interface over the per-group scrape texts)
        slo = root.common.observe.slo
        self._slo_window_s = float(slo.get("window_s", 60.0))
        self._slo_slices = int(slo.get("slices", 12))
        self._slo_burn_threshold = float(slo.get("burn_threshold", 2.0))
        self._slo_targets_ms = {
            "ttft": float(slo.get("ttft_p99_ms", 0.0) or 0.0),
            "queue_wait": float(slo.get("queue_wait_p99_ms", 0.0)
                                or 0.0),
        }
        self._fleet_hists = {
            "ttft": _FleetHistogram(self, "vt_request_ttft_seconds"),
            "queue_wait": _FleetHistogram(
                self, "vt_request_queue_wait_seconds"),
        }
        self._slo_windows = {
            key: HistogramWindow(hist.snapshot_or_none,
                                 self._slo_window_s, self._slo_slices)
            for key, hist in self._fleet_hists.items()}

    # -- topology ------------------------------------------------------------
    def add_replica(self, url: Optional[str] = None, *,
                    client: Optional[ReplicaClient] = None,
                    registry_key: Optional[str] = None,
                    restart: Optional[Callable[[], str]] = None,
                    kill: Optional[Callable[[], None]] = None,
                    role: Optional[str] = None) -> Replica:
        """Register one replica (by base URL or a prebuilt client).
        New replicas start ACTIVE but un-``ready``; the next scrape (or
        first dispatch) fills in their health.  ``role`` assigns the
        capacity class (mixed | prefill | decode —
        ``serve.fleet.role`` when omitted)."""
        if client is None:
            if not url:
                raise ValueError("add_replica needs a url or a client")
            client = ReplicaClient(url)
        role = self.default_role if role is None else str(role)
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"fleet role must be mixed | prefill | decode, "
                f"got {role!r}")
        with self._lock:
            rid = f"r{len(self._replicas)}"
            rep = Replica(rid, client, registry_key=registry_key,
                          restart=restart, kill=kill, role=role)
            self._replicas.append(rep)
        self.info("fleet: replica %s joined at %s (role %s)", rep.id,
                  client.base_url, role)
        return rep

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def _by_state(self, state: str) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas if r.state == state]

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._scrape_thread is not None \
                and self._scrape_thread.is_alive():
            return self
        self._stop_evt.clear()
        # prime health/load before the first dispatch so a router that
        # starts under traffic doesn't route blind for a full interval
        self._scrape_once()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, name="fleet-scrape", daemon=True)
        self._scrape_thread.start()
        with self._lock:
            n = len(self._replicas)
        self.info("fleet router: %d replicas, scrape every %.2fs", n,
                  self.scrape_interval_s)
        return self

    def stop(self):
        self._stop_evt.set()
        t = self._scrape_thread
        if t is not None:
            t.join(timeout=10)
        self._scrape_thread = None
        # a mid-cycle rolling drain must not race the teardown: its
        # loops watch _stop_evt and bail, and a restart completed
        # after this join is still covered — the restart handle
        # updated its owner's srv, so the owner's stop() stops the
        # REBUILT stack, not a stale reference
        with self._lock:
            dt = self._drain_thread
        if dt is not None and dt is not threading.current_thread():
            dt.join(timeout=30)

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> dict:
        """Fleet shutdown: stop admitting at the router, fan a drain
        out to every replica, release :meth:`wait`.  (The zero-downtime
        restart path is :meth:`rolling_drain`, not this.)"""
        self._draining = True
        for rep in self.replicas():
            try:
                rep.client.drain(timeout=5.0)
            except ReplicaUnavailable:
                pass
        self._stopped.set()
        return {"draining": True}

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    # -- scrape / health loop ------------------------------------------------
    def _scrape_loop(self):
        while not self._stop_evt.wait(self.scrape_interval_s):
            try:
                self._scrape_once()
            except Exception:  # noqa: BLE001 — the scrape loop must
                # outlive any single bad replica answer
                self.exception("fleet scrape tick failed")

    def _scrape_once(self):
        reps = self.replicas()
        # one /metrics scrape per registry group, from a live member —
        # a dead leader must not freeze its group's SLO merge input
        leaders: Dict[str, str] = {}
        for rep in reps:
            if rep.state != EJECTED:
                leaders.setdefault(rep.registry_key, rep.id)
        for rep in reps:
            leaders.setdefault(rep.registry_key, rep.id)
        for rep in reps:
            err = None
            ready = False
            stats: Optional[dict] = None
            text: Optional[str] = None
            models: Optional[dict] = None
            try:
                ready = rep.client.ready(timeout=5.0)
                stats = rep.client.engine_stats(timeout=5.0)
                models = rep.client.models_doc(timeout=5.0)
                if leaders.get(rep.registry_key) == rep.id:
                    text = rep.client.metrics_text(timeout=5.0)
            except ReplicaUnavailable as e:
                err = str(e)
            with self._lock:
                if err is None:
                    rep.fails = 0
                    rep.ready = ready
                    rep.load = stats or {}
                    rep.active_version = (models or {}).get("active")
                    if text is not None:
                        rep.metrics_text = text
                    rep.last_scrape = time.monotonic()
                    if rep.state == EJECTED and ready:
                        rep.state = ACTIVE
                        rep.last_error = None
                        self._m_readmissions.inc()
                        self.info("fleet: replica %s readmitted "
                                  "(/ready again)", rep.id)
                else:
                    rep.last_error = err
                    rep.ready = False
                    rep.fails += 1
                    if rep.state == ACTIVE \
                            and rep.fails >= self.eject_failures:
                        self._eject_locked(rep, err)
        for w in self._slo_windows.values():
            w.tick()
        self._publish_gauges()

    def _eject_locked(self, rep: Replica, reason: str):  # requires-lock: self._lock
        """Eject a failed replica: stop routing to it and RELEASE its
        pending-dispatch ledger entries — the dispatch threads holding
        them observe the failure on their own connections and resubmit
        to survivors (the registry-declared fleet-dispatch exit root:
        ejection must provably empty the ejected replica's ledger)."""
        rep.state = EJECTED
        rep.ready = False
        self._m_ejections.inc()
        for seq in list(self._pending.get(rep.id, ())):
            self._end_dispatch_locked(rep, seq)
        self.warning("fleet: ejected replica %s (%s)", rep.id, reason)

    def _publish_gauges(self):
        with self._lock:
            by_state = {ACTIVE: 0, DRAINING: 0, EJECTED: 0}
            for r in self._replicas:
                by_state[r.state] = by_state.get(r.state, 0) + 1
                self._g_outstanding.labels(replica=r.id).set(
                    r.outstanding)
            hits, reqs = self._affinity_hits, self._affinity_requests
        for state, n in by_state.items():
            self._g_replicas.labels(state=state).set(n)
        self._g_affinity_hit_rate.set(hits / reqs if reqs else 0.0)

    # -- dispatch ledger (registry RESOURCE_PAIRS "fleet-dispatch") ---------
    def _begin_dispatch(self, rep: Replica) -> int:
        with self._lock:
            self._dispatch_seq += 1
            seq = self._dispatch_seq
            self._pending.setdefault(rep.id, set()).add(seq)
            rep.outstanding = len(self._pending[rep.id])
            rep.dispatched += 1
        self._m_requests.labels(replica=rep.id).inc()
        return seq

    def _end_dispatch(self, rep: Replica, seq: int):
        with self._lock:
            self._end_dispatch_locked(rep, seq)

    def _end_dispatch_locked(self, rep: Replica, seq: int):  # requires-lock: self._lock
        pend = self._pending.get(rep.id)
        if pend is not None:
            pend.discard(seq)
        rep.outstanding = len(pend) if pend else 0

    # -- routing -------------------------------------------------------------
    def _head_hashes(self, prompt) -> List[bytes]:
        """Chained page hashes of the prompt head (first row of a
        batch request) — the SAME digests the replicas' prefix index
        keys (engine.prefix_page_hashes), truncated to
        ``affinity_pages``: the system prompt lives at the head, and
        hashing the whole prompt would make every long request
        affinity-unique."""
        if prompt is None or self.affinity_pages <= 0:
            return []
        try:
            row = np.asarray(prompt)
            if row.ndim == 2:
                row = row[0]
            row = row.reshape(-1)
            if not np.issubdtype(row.dtype, np.number):
                return []
            head = row[:self.affinity_pages * self.page_size]
            return prefix_page_hashes(head.astype(np.int64),
                                      self.page_size)
        except (TypeError, ValueError):
            return []    # malformed prompts get their 400 from the
            #              replica; affinity just doesn't apply

    def _score_locked(self, rep: Replica) -> float:  # requires-lock: self._lock
        """Load score, lower = better: scraped queue + occupancy plus
        the router's LIVE outstanding count (which beats scrape
        staleness), normalized by slot count; un-ready replicas carry
        a routable-last penalty."""
        st = rep.load or {}
        slots = max(int(st.get("slots", 1) or 1), 1)
        score = (float(st.get("queue_depth", 0))
                 + float(st.get("occupancy", 0))
                 + float(rep.outstanding)) / slots
        if not rep.ready:
            score += _NOT_READY_PENALTY
        return score

    @staticmethod
    def _burn_locked(rep: Replica) -> float:  # requires-lock: self._lock
        adm = (rep.load or {}).get("admission") or {}
        try:
            return float(adm.get("burn", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def _route(self, priority: int, hashes: List[bytes],
               tried: set) -> Tuple[Optional[Replica], bool]:
        """Pick a replica → ``(replica, affinity_hit)``.  Affinity
        first (the page-holding replica keeps the stream unless its
        load is worse than the best by more than the hysteresis
        margin), then load dispatch with incumbent hysteresis, with a
        hash-ring fallback for cold prefixes.  Backed-off replicas
        (honored 429s) are skipped for classes > 0; class 0 falls back
        to the least-burned replica when everyone is backed off."""
        now = time.monotonic()
        with self._lock:
            cands = [r for r in self._replicas
                     if r.state == ACTIVE and r.id not in tried]
            # capacity classes: prefill-role replicas serve the disagg
            # prefill leg (docs/serving.md "Disaggregated
            # prefill/decode"), not normal dispatch — unless the fleet
            # has NOTHING else (availability beats role purity)
            serving = [r for r in cands if r.role != "prefill"]
            if serving:
                cands = serving
            if not cands:
                return None, False
            open_ = [r for r in cands if r.backoff_until <= now]
            if not open_:
                if priority > 0:
                    return None, False
                # class 0 is never controller-shed per replica; at the
                # router it rides out fleet-wide backpressure on the
                # replica burning its budget slowest
                rep = min(cands, key=lambda r: (self._burn_locked(r),
                                                self._score_locked(r)))
                return rep, False
            cands = open_
            scores = {r.id: self._score_locked(r) for r in cands}
            best = min(cands, key=lambda r: scores[r.id])
            by_id = {r.id: r for r in cands}
            # 1) warm prefix: deepest known page hash wins
            for h in reversed(hashes):
                rid = self._affinity.get(h)
                rep = by_id.get(rid)
                if rep is not None and scores[rep.id] \
                        <= scores[best.id] + self.hysteresis:
                    return rep, True
            # 2) cold prefix: hash ring — the same new prefix
            # converges on one replica instead of warming all of them
            if hashes:
                ring = sorted(cands, key=lambda r: r.id)
                rep = ring[int.from_bytes(hashes[0][:8], "big")
                           % len(ring)]
                if scores[rep.id] <= scores[best.id] + self.hysteresis:
                    return rep, False
                return best, False
            # 3) pure load, with incumbent hysteresis so two stale
            # scrapes can't ping-pong the stream
            inc = by_id.get(self._last_pick)
            if inc is not None and scores[inc.id] \
                    <= scores[best.id] + self.hysteresis:
                return inc, False
            self._last_pick = best.id
            return best, False

    def _record_affinity(self, hashes: List[bytes], rep: Replica):
        """First-touch binding: a prefix keeps its original page
        holder.  A request load-diverted AWAY from the holder warms a
        second copy but must NOT migrate the session — rebinding on
        every success made sessions chase whichever replica was least
        loaded at the moment and collapse onto one.  Only a mapping
        whose holder left the active set rebinds."""
        if not hashes:
            return
        with self._lock:
            active = {r.id for r in self._replicas
                      if r.state == ACTIVE}
            for h in hashes:
                cur = self._affinity.get(h)
                if cur is not None and cur != rep.id \
                        and cur in active:
                    continue
                self._affinity.pop(h, None)      # re-insert = LRU touch
                self._affinity[h] = rep.id
            while len(self._affinity) > self.affinity_max:
                self._affinity.pop(next(iter(self._affinity)))

    def _note_dispatch_failure(self, rep: Replica, reason: str):
        """A dispatch-level transport failure counts toward ejection
        exactly like a failed health scrape — connection-refused from
        a crashed replica must not wait for the next scrape tick."""
        with self._lock:
            rep.last_error = reason
            rep.fails += 1
            if rep.state == ACTIVE and rep.fails >= self.eject_failures:
                self._eject_locked(rep, reason)

    def _note_backpressure(self, rep: Replica, retry_after_s: float):
        with self._lock:
            rep.backoff_until = time.monotonic() \
                + max(0.1, float(retry_after_s))
        self._m_backpressure.inc()

    # -- KV-page transfer (docs/serving.md "Disaggregated
    # prefill/decode"): every helper here is BEST-EFFORT — a failed or
    # rejected transfer means the target replica prefills locally,
    # never an errored request -----------------------------------------------
    def _full_hashes(self, prompt) -> List[bytes]:
        """Chained page hashes of the WHOLE prompt head row — the
        disagg ship set, unlike :meth:`_head_hashes` which truncates to
        ``affinity_pages`` for routing keys."""
        if prompt is None:
            return []
        try:
            row = np.asarray(prompt)
            if row.ndim == 2:
                row = row[0]
            row = row.reshape(-1)
            if not np.issubdtype(row.dtype, np.number):
                return []
            return prefix_page_hashes(row.astype(np.int64),
                                      self.page_size)
        except (TypeError, ValueError):
            return []

    def _kv_fault_drop(self) -> bool:
        """Consult the fault plan's transfer knobs: sleep
        ``kv_transfer_slow_ms`` per transfer, and report True while the
        ``kv_transfer_drop`` budget (first N transfers fail) lasts."""
        from . import faults
        if not faults.enabled():
            return False
        plan = faults.get_plan()
        if plan.kv_transfer_slow_ms:
            time.sleep(plan.kv_transfer_slow_ms / 1e3)
        if plan.kv_transfer_drop:
            with self._lock:
                if self._kv_drops < int(plan.kv_transfer_drop):
                    self._kv_drops += 1
                    return True
        return False

    def _fetch_worthwhile(self, rep: Replica, n_pages: int) -> bool:
        """Fetch-vs-reprefill payoff (the spec-decode _spec_worthwhile
        idiom): estimated transfer wall (page wire bytes over the
        measured link-bandwidth EWMA) against estimated local prefill
        wall (page tokens over the replica's scraped prefill
        throughput).  Cold — no measured bandwidth yet, or the replica
        hasn't scraped transfer geometry — is OPTIMISTIC: probe
        transfers are how the estimate gets seeded."""
        with self._lock:
            cold = self._kv_transfers < 3
            bw = self._kv_bw_ewma
        xfer = (rep.load or {}).get("kv_transfer") or {}
        try:
            page_bytes = float(xfer.get("page_bytes", 0) or 0)
            tok_s = float(xfer.get("prefill_tok_s", 0) or 0)
        except (TypeError, ValueError):
            page_bytes = tok_s = 0.0
        if cold or bw <= 0 or page_bytes <= 0 or tok_s <= 0:
            self._g_kv_payoff.set(0.0)
            return True
        est_fetch_s = n_pages * page_bytes / bw
        est_prefill_s = n_pages * self.page_size / tok_s
        payoff = est_prefill_s / max(est_fetch_s, 1e-9)
        self._g_kv_payoff.set(round(payoff, 4))
        return payoff >= 1.0

    def _transfer_pages(self, src: Replica, dst: Replica, *,
                        hashes: Optional[List[bytes]] = None,
                        top: Optional[int] = None,
                        outcome: str = "ok") -> Optional[dict]:
        """Move pages ``src`` → ``dst`` (named hashes or src's top-K
        hottest); returns dst's import doc on success, None on any
        failure.  The measured wall feeds the bandwidth EWMA."""
        t0 = time.monotonic()
        try:
            if self._kv_fault_drop():
                raise ReplicaUnavailable("fault: kv_transfer_drop")
            status, blob = src.client.fetch_pages(
                hashes, top=top, timeout=self.kv_timeout_s)
            if status != 200 or not blob:
                self._m_kv_fetches.labels(outcome="failed").inc()
                return None
            status2, doc = dst.client.put_pages(
                blob, timeout=self.kv_timeout_s)
            if status2 != 200 or not isinstance(doc, dict):
                # dst REJECTED the blob (geometry/weights-version) —
                # its local prefill is the correct fallback
                self._m_kv_fetches.labels(outcome="rejected").inc()
                return None
        except ReplicaUnavailable:
            self._m_kv_fetches.labels(outcome="failed").inc()
            return None
        wall = max(time.monotonic() - t0, 1e-6)
        with self._lock:
            bw = len(blob) / wall
            self._kv_bw_ewma = bw if self._kv_bw_ewma <= 0 \
                else 0.8 * self._kv_bw_ewma + 0.2 * bw
            self._kv_transfers += 1
        self._m_kv_fetches.labels(outcome=outcome).inc()
        return doc

    def _maybe_fetch_remote(self, rep: Replica,
                            hashes: List[bytes]) -> bool:
        """Fleet-wide prefix-cache sharing: the deepest affinity-known
        holder of the request's prefix pages ships them to the routed
        replica before dispatch, payoff permitting, so the admission
        there hits the imported prefix instead of re-prefilling."""
        if len(hashes) < self.kv_min_pages:
            return False
        with self._lock:
            holder_id = None
            for h in reversed(hashes):
                rid = self._affinity.get(h)
                if rid is not None and rid != rep.id:
                    holder_id = rid
                    break
            holder = next(
                (r for r in self._replicas if r.id == holder_id
                 and r.state in (ACTIVE, DRAINING)), None)
        if holder is None:
            return False
        if not self._fetch_worthwhile(rep, len(hashes)):
            self._m_kv_fetches.labels(outcome="skipped").inc()
            return False
        return self._transfer_pages(holder, rep,
                                    hashes=hashes) is not None

    def _disagg_prefill(self, rep: Replica, body: dict) -> bool:
        """Disaggregated dispatch: a prefill-class replica runs the
        (chunked) prefill — a steps=1 dispatch, whose single decode
        step is the prefill's first token — then its finished pages
        ship to the decode target, whose real admission starts at the
        shipped length via prefix hits.  Any failed leg falls back to
        a plain dispatch (``rep`` prefills locally)."""
        full = self._full_hashes(body.get("prompt"))
        if len(full) < self.kv_min_pages:
            return False
        with self._lock:
            pre = [r for r in self._replicas
                   if r.state == ACTIVE and r.role == "prefill"
                   and r.id != rep.id]
            p = min(pre, key=self._score_locked) if pre else None
        if p is None:
            return False
        pb = dict(body)
        pb["steps"] = 1
        pb.pop("priority", None)  # the prefill leg must not queue-jump
        try:
            status, _doc, _retry = p.client.generate(
                pb, timeout=self.dispatch_timeout_s)
        except ReplicaUnavailable:
            self._m_kv_fetches.labels(outcome="failed").inc()
            return False
        if status != 200:
            self._m_kv_fetches.labels(outcome="failed").inc()
            return False
        self._record_affinity(full[:self.affinity_pages], p)
        return self._transfer_pages(p, rep, hashes=full,
                                    outcome="disagg") is not None

    def _kv_prefetch(self, rep: Replica, body: dict,
                     hashes: List[bytes]):
        """Pre-dispatch page placement, in preference order: the
        disagg prefill leg when prefill-class capacity exists, else a
        remote fetch from the affinity holder.  Never raises — the
        transfer path is an optimization over local prefill, not a
        dependency of the request."""
        if not self.kv_transfer_enabled:
            return
        try:
            if self._disagg_prefill(rep, body):
                return
            self._maybe_fetch_remote(rep, hashes)
        except Exception:  # noqa: BLE001 — local prefill serves
            self.exception("kv prefetch failed; falling back to "
                           "local prefill")

    def handle_generate(self, body: dict) -> Tuple[int, object, Tuple]:
        """Route + forward one ``/generate`` →
        ``(status, doc, extra headers)``.  Failover policy: transport
        failures and replica-fatal statuses (503 stopped/draining, 500
        scheduler-crash) resubmit the request — it is unary and never
        mid-stream — to a survivor; 429s honor the replica's
        Retry-After as backpressure; everything else (including the
        client's own 4xx) is the replica's answer, returned as-is."""
        if self._draining:
            return 503, {"error": "fleet is draining"}, \
                (("Retry-After", "5"),)
        try:
            priority = int(body.get("priority", 0) or 0)
        except (TypeError, ValueError):
            priority = 0
        if body.get("batch"):
            # batch lane (docs/serving.md "Batch lane"): route as a
            # non-zero class so a backed-off replica is SKIPPED, never
            # ridden through its 429 window the way class 0 rides the
            # least-burned replica — batch always defers to whatever
            # interactive pressure caused the backoff
            priority = max(priority, 1)
        hashes = self._head_hashes(body.get("prompt"))
        if hashes:
            self._m_affinity_requests.inc()
            with self._lock:
                self._affinity_requests += 1
        from . import faults
        plan = faults.get_plan() if faults.enabled() else None
        with self._lock:
            self._route_count += 1
            route_n = self._route_count
            n_replicas = len(self._replicas)
        tried: set = set()
        retry_hint: Optional[float] = None
        hit_counted = False
        prefetched = False
        for _attempt in range(n_replicas + 1):
            rep, hit = self._route(priority, hashes, tried)
            if rep is None:
                break
            if hit and not hit_counted:
                # once per REQUEST, not per failover attempt — two
                # routed attempts must not make the hit rate exceed 1
                hit_counted = True
                self._m_affinity_hits.inc()
                with self._lock:
                    self._affinity_hits += 1
            if plan is not None:
                self._inject_faults(plan, rep, route_n)
            if hashes and not hit and not prefetched:
                # cold here (no affinity hit on the routed replica):
                # place the prefix pages there first — a disagg
                # prefill leg or a fetch from the holder — so the
                # admission below skips the re-prefill.  Once per
                # request: a failover retry must not pay twice.
                prefetched = True
                self._kv_prefetch(rep, body, hashes)
            seq = self._begin_dispatch(rep)
            try:
                try:
                    status, doc, retry = rep.client.generate(
                        body, timeout=self.dispatch_timeout_s)
                except ReplicaUnavailable as e:
                    # the replica never answered: resubmit to a
                    # survivor (idempotent — the request is unary and
                    # no partial answer escaped)
                    self._note_dispatch_failure(rep, str(e))
                    self._m_resubmissions.inc()
                    tried.add(rep.id)
                    continue
            finally:
                self._end_dispatch(rep, seq)
            if status == 429:
                # a batch-class 429 is "no headroom for BATCH" — the
                # replica is busy serving interactive, which is the
                # opposite of shedding.  Honoring it as router-level
                # backpressure would let the job manager's trough
                # probes black-hole class-0 traffic (every replica
                # "shedding" while all of them serve fine).
                if not body.get("batch"):
                    self._note_backpressure(rep, retry)
                retry_hint = retry if retry_hint is None \
                    else min(retry_hint, retry)
                tried.add(rep.id)
                continue
            if status == 503 or (status == 500 and isinstance(doc, dict)
                                 and doc.get("kind")
                                 == "scheduler_crash"):
                # the replica is going (drain/stop) or its scheduler
                # died: this request FAILED there — a survivor can
                # serve it
                self._note_dispatch_failure(rep, f"HTTP {status}")
                self._m_resubmissions.inc()
                tried.add(rep.id)
                continue
            if status == 200:
                self._record_affinity(hashes, rep)
            return status, doc, ()
        if retry_hint is None:
            # nothing was dispatched this call, but active replicas
            # sitting out earlier 429 windows are still backpressure:
            # answer with the soonest re-open, not a 503 a balancer
            # would misread as an outage
            now = time.monotonic()
            with self._lock:
                waits = [r.backoff_until - now for r in self._replicas
                         if r.state == ACTIVE and r.backoff_until > now]
            if waits:
                retry_hint = min(waits)
        if retry_hint is not None:
            return 429, {"error": "every replica is shedding "
                                  "(router-level backpressure)",
                         "retry_after_s": round(retry_hint, 3)}, \
                (("Retry-After", str(int(round(max(1.0,
                                                   retry_hint))))),)
        return 503, {"error": "no replica available"}, \
            (("Retry-After", "5"),)

    def handle_generate_stream(self, body: dict
                               ) -> Tuple[int, object, Tuple]:
        """Route + relay one STREAMING ``/generate`` (docs/serving.md
        "Streaming and mid-stream failover") → ``(status, result,
        extra headers)``.  On 200 ``result`` is a GENERATOR of NDJSON
        frame dicts; any pre-stream failure returns the same statuses
        :meth:`handle_generate` would.  The relay records the
        per-request token high-water mark; when a replica dies
        mid-stream (transport cut, or an error terminal frame from a
        crashed/stopped scheduler) it resubmits the SUFFIX — the
        original prompt/steps/seed plus every token already delivered,
        via the engine's ``emitted_prefix`` resume form — to a
        survivor and splices the streams, so the client sees one
        gapless, duplicate-free sequence bitwise-identical to an
        uninterrupted run.  ``serve.stream.retry_budget`` resumes with
        ``serve.stream.backoff_s``-based capped backoff bound the
        failover storm; the budget or the request deadline running out
        yields ONE error/deadline terminal frame, never a hang."""
        if self._draining:
            return 503, {"error": "fleet is draining"}, \
                (("Retry-After", "5"),)
        try:
            priority = int(body.get("priority", 0) or 0)
        except (TypeError, ValueError):
            priority = 0
        hashes = self._head_hashes(body.get("prompt"))
        if hashes:
            self._m_affinity_requests.inc()
            with self._lock:
                self._affinity_requests += 1
        from . import faults
        plan = faults.get_plan() if faults.enabled() else None
        with self._lock:
            self._route_count += 1
            route_n = self._route_count
        # the router-side failover clock: every resume leg must fit
        # inside what remains of the ORIGINAL request deadline (resumed
        # legs get the shrunken remainder as their deadline_s)
        try:
            total_s = float(body.get("deadline_s")
                            or self.stream_deadline_s)
        except (TypeError, ValueError):
            total_s = self.stream_deadline_s
        deadline = time.monotonic() + total_s
        # high-water mark: every token already DELIVERED to the client
        # (seeded by a client-side resume's own prefix); the resume
        # body sends exactly this list, so a survivor numbers its
        # first frame one past it
        tokens: List[int] = [int(t) for t in
                             np.asarray(body.get("emitted_prefix")
                                        if body.get("emitted_prefix")
                                        is not None else [],
                                        np.int64).reshape(-1)]
        state = {"hit_counted": False, "prefetched": False}

        def run_leg(tried: set, leg_body: dict):
            """One routed streaming dispatch, with the unary loop's
            skip/backoff/failover semantics.  Returns ``("stream",
            rep, seq, frames)`` holding the dispatch ledger entry open
            (the relay closes it), ``("status", code, doc, headers)``
            for an answered non-200, or ``("exhausted", retry_hint)``."""
            retry_hint = None
            with self._lock:
                n_replicas = len(self._replicas)
            for _attempt in range(n_replicas + 1):
                rep, hit = self._route(priority, hashes, tried)
                if rep is None:
                    break
                if hit and not state["hit_counted"]:
                    state["hit_counted"] = True
                    self._m_affinity_hits.inc()
                    with self._lock:
                        self._affinity_hits += 1
                if plan is not None:
                    self._inject_faults(plan, rep, route_n)
                if hashes and not hit and not state["prefetched"]:
                    state["prefetched"] = True
                    self._kv_prefetch(rep, leg_body, hashes)
                seq = self._begin_dispatch(rep)
                try:
                    status, result, retry = rep.client.generate_stream(
                        leg_body, timeout=self.dispatch_timeout_s)
                except ReplicaUnavailable as e:
                    self._end_dispatch(rep, seq)
                    self._note_dispatch_failure(rep, str(e))
                    self._m_resubmissions.inc()
                    tried.add(rep.id)
                    continue
                if status == 200:
                    self._record_affinity(hashes, rep)
                    return ("stream", rep, seq, result)
                self._end_dispatch(rep, seq)
                if status == 429:
                    self._note_backpressure(rep, retry)
                    retry_hint = retry if retry_hint is None \
                        else min(retry_hint, retry)
                    tried.add(rep.id)
                    continue
                if status == 503 or (status == 500
                                     and isinstance(result, dict)
                                     and result.get("kind")
                                     == "scheduler_crash"):
                    self._note_dispatch_failure(rep, f"HTTP {status}")
                    self._m_resubmissions.inc()
                    tried.add(rep.id)
                    continue
                return ("status", status, result, ())
            return ("exhausted", retry_hint)

        def leg_body_now() -> dict:
            b = dict(body)
            b["stream"] = True
            b["emitted_prefix"] = list(tokens)
            # the remaining budget, floored just enough to keep the
            # replica's deadline_s validation (> 0) satisfied — the
            # engine, not the router, owns expiry semantics
            b["deadline_s"] = max(0.05, deadline - time.monotonic())
            return b

        first = run_leg(set(), leg_body_now())
        if first[0] == "status":
            return first[1], first[2], first[3]
        if first[0] == "exhausted":
            retry_hint = first[1]
            if retry_hint is None:
                # same soonest-reopen answer as the unary path: backed-
                # off replicas are backpressure, not an outage
                now = time.monotonic()
                with self._lock:
                    waits = [r.backoff_until - now
                             for r in self._replicas
                             if r.state == ACTIVE
                             and r.backoff_until > now]
                if waits:
                    retry_hint = min(waits)
            if retry_hint is not None:
                return 429, {"error": "every replica is shedding "
                                      "(router-level backpressure)",
                             "retry_after_s": round(retry_hint, 3)}, \
                    (("Retry-After", str(int(round(max(
                        1.0, retry_hint))))),)
            return 503, {"error": "no replica available"}, \
                (("Retry-After", "5"),)

        def relay(rep, seq, frames):
            cut_at = plan.stream_cut_at_token if plan is not None else 0
            stall_ms = plan.stream_stall_ms if plan is not None else 0.0
            resumes_left = self.stream_retry_budget
            relayed = 0
            while True:
                failure = None
                try:
                    try:
                        for frame in frames:
                            if frame.get("done"):
                                reason = frame.get("finish_reason")
                                if reason == "error":
                                    # the replica-side request FAILED
                                    # (scheduler crash/stop, shed
                                    # mid-flight): resumable, exactly
                                    # like a transport cut — but the
                                    # replica itself answered, so no
                                    # ejection strike
                                    failure = ("terminal",
                                               str(frame.get("error")))
                                    break
                                yield frame
                                return
                            i = int(frame["i"])
                            if i < len(tokens):
                                continue    # overlap after a resume:
                                #             already delivered, drop
                            if i > len(tokens):
                                # a gap is stream corruption — never
                                # deliver it; resume from the mark
                                failure = ("gap",
                                           f"frame {i} past high-water "
                                           f"mark {len(tokens)}")
                                break
                            tokens.append(int(frame["token"]))
                            relayed += 1
                            if stall_ms:
                                # injected slow consumer
                                # (faults.stream_stall_ms): the relay
                                # lags, the replica-side handle buffers
                                time.sleep(stall_ms / 1e3)
                            yield frame
                            if cut_at and relayed >= cut_at \
                                    and faults.fire_once("stream_cut"):
                                raise ReplicaUnavailable(
                                    f"{rep.id}: injected stream cut "
                                    f"after frame {relayed} "
                                    "(faults.stream_cut_at_token)")
                    except ReplicaUnavailable as e:
                        failure = ("transport", str(e))
                finally:
                    # the leg's ledger entry closes however the leg
                    # ends — clean terminal, failover, or the client
                    # closing the relay generator mid-stream
                    self._end_dispatch(rep, seq)
                if failure is None:
                    # replica closed the stream with no terminal frame:
                    # the transport died between frames
                    failure = ("transport",
                               f"{rep.id}: stream ended without a "
                               "terminal frame")
                cut_at = 0      # the injected cut fires once
                if hasattr(frames, "close"):
                    frames.close()
                if failure[0] == "transport":
                    self._note_dispatch_failure(rep, failure[1])
                interrupted = time.monotonic()
                resumed = None
                while resumes_left > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    resumes_left -= 1
                    attempt = self.stream_retry_budget - resumes_left
                    backoff = min(
                        self.stream_backoff_s * (2 ** (attempt - 1)),
                        self.stream_backoff_max_s, max(remaining, 0.0))
                    if backoff > 0:
                        time.sleep(backoff)
                    self._m_resubmissions.inc()
                    self._m_stream_resumes.inc()
                    # first retry skips the replica that just died; later
                    # retries re-admit it (a restart may have brought it
                    # back inside the backoff window)
                    nxt = run_leg({rep.id} if attempt == 1 else set(),
                                  leg_body_now())
                    if nxt[0] == "stream":
                        resumed = nxt
                        break
                    if nxt[0] == "status":
                        # a survivor ANSWERED with a non-resumable
                        # error (e.g. 400): surface it terminally
                        doc = nxt[2] if isinstance(nxt[2], dict) else {}
                        yield {"done": True, "finish_reason": "error",
                               "error": f"resume failed with HTTP "
                                        f"{nxt[1]}: "
                                        f"{doc.get('error', nxt[2])}"}
                        return
                    # exhausted this pass: let the backoff window give
                    # ejection/readmission a chance before retrying
                if resumed is None:
                    if deadline - time.monotonic() <= 0:
                        yield {"done": True,
                               "finish_reason": "deadline",
                               "error": "request deadline expired "
                                        "during mid-stream failover"}
                        return
                    self._m_stream_retry_exhausted.inc()
                    yield {"done": True, "finish_reason": "error",
                           "error": "mid-stream failover retry budget "
                                    f"exhausted after {failure[1]} "
                                    "(serve.stream.retry_budget)"}
                    return
                self._m_stream_splice.observe(
                    time.monotonic() - interrupted)
                _tag, rep, seq, frames = resumed

        return 200, relay(first[1], first[2], first[3]), ()

    def _inject_faults(self, plan, rep: Replica, route_n: int):
        """Fleet fault knobs (runtime/faults.py): ``replica_slow_ms``
        delays every dispatch to the lowest-id active replica;
        ``replica_crash_at_request`` kills the chosen replica right
        before the Nth dispatch is forwarded (once per arming), so the
        forward fails over through the resubmission path."""
        from . import faults
        if plan.replica_slow_ms:
            with self._lock:
                low = min((r.id for r in self._replicas
                           if r.state == ACTIVE), default=None)
            if rep.id == low:
                time.sleep(plan.replica_slow_ms / 1e3)
        if plan.replica_crash_at_request \
                and route_n >= plan.replica_crash_at_request \
                and rep.kill is not None \
                and faults.fire_once("replica_crash"):
            self.warning("fault: killing replica %s at request %d",
                         rep.id, route_n)
            try:
                rep.kill()
            except Exception:  # noqa: BLE001 — an imperfect kill must
                pass           # not fail the rehearsal's request

    # -- coordinated hot swap ------------------------------------------------
    def coordinated_swap(self, source: Optional[str] = None,
                         version=None) -> dict:
        """Fleet-wide two-phase hot swap: stage the new version on
        EVERY active replica, flip only after all staged successfully,
        roll back everywhere when any flip fails.  The fleet ends on
        the new version everywhere or the old version everywhere —
        never mixed.  Rollback of an already-committed replica reloads
        its previous registry version (which needs a reloadable boot
        source; a 'live'-booted replica logs the gap loudly)."""
        with self._ops_mutex:
            reps = self._by_state(ACTIVE)
            if not reps:
                return {"swapped": False, "phase": "stage",
                        "errors": {"fleet": "no active replicas"}}
            staged: Dict[str, str] = {}
            prev_version: Dict[str, Optional[int]] = {}
            errors: Dict[str, str] = {}
            for rep in reps:        # phase 1: stage everywhere
                try:
                    models = rep.client.models_doc()
                    prev_version[rep.id] = (models or {}).get("active")
                    status, doc = rep.client.stage(source=source,
                                                   version=version)
                    if status == 200 and isinstance(doc, dict) \
                            and doc.get("staged"):
                        staged[rep.id] = doc["staged"]
                    else:
                        errors[rep.id] = f"HTTP {status}: {doc}"
                except ReplicaUnavailable as e:
                    errors[rep.id] = str(e)
            if errors:
                for rep in reps:
                    token = staged.get(rep.id)
                    if token is None and rep.id not in errors:
                        continue
                    # a stage whose REPLY was lost may have landed
                    # server-side and would wedge every later swap on
                    # that replica ("already staged") — a token-less
                    # abort clears whatever is pending (idempotent)
                    try:
                        rep.client.abort(token)
                    except ReplicaUnavailable:
                        pass
                self._m_swap_rollbacks.inc()
                result = {"swapped": False, "phase": "stage",
                          "errors": errors,
                          "staged_then_aborted": sorted(staged)}
                self._last_swap = result
                self.warning("coordinated swap aborted at stage: %s",
                             errors)
                return result
            committed: List[Replica] = []
            for rep in reps:        # phase 2: flip everywhere
                try:
                    status, doc = rep.client.commit(staged[rep.id])
                    if status != 200:
                        # an HTTP error is UNambiguous: commit_staged
                        # either flipped (200) or left the old version
                        # serving (its own rollback) before replying
                        errors[rep.id] = f"HTTP {status}: {doc}"
                        break
                    committed.append(rep)
                except ReplicaUnavailable as e:
                    # ambiguous: the reply was lost, but the flip may
                    # have landed server-side after the timeout — a
                    # committed-but-unrecorded replica skipped by the
                    # rollback would leave the fleet MIXED.  Resolve
                    # by probing the registry it would have advanced.
                    errors[rep.id] = str(e)
                    try:
                        m = rep.client.models_doc()
                        if m is not None and m.get("active") \
                                != prev_version.get(rep.id):
                            committed.append(rep)
                    except ReplicaUnavailable:
                        pass    # still unreachable: nothing flipped a
                        #         working registry forward, and a dead
                        #         replica rejoins via /ready + scrape
                    break
            if errors:
                # roll back: uncommitted stagings abort, committed
                # replicas reload the version they served before
                rolled, rollback_errors = [], {}
                for rep in reps:
                    if rep in committed:
                        continue
                    token = staged.get(rep.id)
                    if token is not None:
                        try:
                            rep.client.abort(token)
                        except ReplicaUnavailable:
                            pass
                for rep in committed:
                    prev = prev_version.get(rep.id)
                    try:
                        status, doc = rep.client.reload(version=prev)
                        if status == 200:
                            rolled.append(rep.id)
                        else:
                            rollback_errors[rep.id] = \
                                f"HTTP {status}: {doc}"
                    except ReplicaUnavailable as e:
                        rollback_errors[rep.id] = str(e)
                self._m_swap_rollbacks.inc()
                result = {"swapped": False, "phase": "commit",
                          "errors": errors, "rolled_back": rolled,
                          "rollback_errors": rollback_errors}
                self._last_swap = result
                self.warning("coordinated swap rolled back: %s",
                             errors)
                return result
            self._m_swaps.inc()
            result = {"swapped": True,
                      "replicas": [r.id for r in committed],
                      "previous_versions": prev_version}
            self._last_swap = result
            self.info("coordinated swap committed on %d replicas",
                      len(committed))
            return result

    # -- rolling drain -------------------------------------------------------
    def begin_rolling_drain(self) -> dict:
        """Async rolling drain (the ``POST /admin/rolling-drain``
        handler): one replica at a time on a background thread; 202 —
        watch ``/fleet.json`` for progress."""
        with self._lock:
            t = self._drain_thread
            if t is not None and t.is_alive():
                return {"rolling": True, "already": True}
            self._drain_thread = threading.Thread(
                target=self.rolling_drain, name="fleet-rolling-drain",
                daemon=True)
            self._drain_thread.start()
        return {"rolling": True}

    def rolling_drain(self) -> dict:
        """Zero-downtime restart cycle: for each replica in turn —
        stop routing to it, wait for its in-flight work to retire,
        restart it (the restart handle; ``--join``ed replicas are
        drained for their external supervisor instead), readmit when
        ``/ready`` answers again, move on.  Survivors keep serving the
        whole time.  EJECTED replicas the router can restart ride the
        cycle too (skipping the idle wait — a crashed replica has
        nothing in flight): the rolling drain is also the repair
        action that rebuilds a dead in-process/child replica."""
        with self._ops_mutex:
            results = []
            with self._lock:
                cycle = [r for r in self._replicas
                         if r.state == ACTIVE
                         or (r.state == EJECTED
                             and r.restart is not None)]
            for rep in sorted(cycle, key=lambda r: r.id):
                if self._draining or self._stop_evt.is_set():
                    # fleet shutdown wins: restarting replicas into a
                    # stopping fleet would leave fresh serving stacks
                    # running past the "clean" exit
                    results.append({"replica": rep.id,
                                    "skipped": "fleet stopping"})
                    continue
                entry = {"replica": rep.id, "restarted": False,
                         "readmitted": False}
                with self._lock:
                    was_ejected = rep.state == EJECTED
                if not was_ejected:
                    # affinity-preserving drain: push the victim's hot
                    # prefix pages to a successor BEFORE routing stops,
                    # so sessions landing elsewhere post-drain keep
                    # their warm TTFT (a dead replica has no pages to
                    # push).  Best-effort like every transfer.
                    entry["prewarm"] = self._prewarm_successor(rep)
                with self._lock:
                    was_ejected = rep.state == EJECTED
                    rep.state = DRAINING
                entry["idle"] = True if was_ejected \
                    else self._wait_replica_idle(rep)
                if rep.restart is not None:
                    try:
                        new_url = rep.restart()
                        if new_url:
                            with self._lock:
                                rep.client = ReplicaClient(str(new_url))
                        entry["restarted"] = True
                    except Exception as e:  # noqa: BLE001 — a failed
                        # restart must strand ONE replica, not the loop
                        entry["error"] = f"{type(e).__name__}: {e}"
                        with self._lock:
                            rep.last_error = entry["error"]
                            self._eject_locked(rep, entry["error"])
                        results.append(entry)
                        continue
                else:
                    try:
                        rep.client.drain(timeout=5.0)
                    except ReplicaUnavailable:
                        pass
                ready = self._wait_ready(rep)
                with self._lock:
                    rep.state = ACTIVE if ready else EJECTED
                    rep.ready = ready
                    rep.fails = 0
                    if ready:
                        rep.load = {}
                entry["readmitted"] = ready
                results.append(entry)
                self.info("rolling drain: %s %s", rep.id,
                          "readmitted" if ready else "NOT ready "
                          "(ejected; the scrape loop readmits it when "
                          "/ready answers)")
            summary = {"completed": bool(results)
                       and all(r.get("readmitted") for r in results),
                       "replicas": results}
            self._last_drain = summary
            if summary["completed"]:
                self._m_rolling_drains.inc()
            return summary

    def _prewarm_successor(self, rep: Replica) -> Optional[dict]:
        """Ship ``rep``'s top-K hottest prefix pages (refcount-ranked
        — ``GET /kv/pages?top=K``) to the least-loaded surviving
        replica and REPOINT the affinity entries that named ``rep`` as
        holder, so post-drain routing lands where the pages now live.
        Returns a summary dict for the drain report, None when skipped
        or failed."""
        if not self.kv_transfer_enabled or self.prewarm_pages <= 0:
            return None
        with self._lock:
            others = [r for r in self._replicas
                      if r.state == ACTIVE and r.id != rep.id
                      and r.role != "prefill"]
            succ = min(others, key=self._score_locked) if others \
                else None
        if succ is None:
            return None
        doc = self._transfer_pages(rep, succ, top=self.prewarm_pages,
                                   outcome="prewarm")
        if doc is None:
            return None
        moved = []
        for hx in doc.get("hashes", ()):
            try:
                moved.append(bytes.fromhex(hx))
            except (TypeError, ValueError):
                continue
        with self._lock:
            for h in moved:
                if self._affinity.get(h) == rep.id:
                    self._affinity[h] = succ.id
        return {"to": succ.id,
                "pages": int(doc.get("imported", 0))
                + int(doc.get("skipped", 0)),
                "dropped": int(doc.get("dropped", 0))}

    def _wait_replica_idle(self, rep: Replica) -> bool:
        """The drained replica's router-tracked in-flight count AND
        its own queue/occupancy must reach zero (a request the router
        dispatched before the drain decision must retire there)."""
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline \
                and not self._stop_evt.is_set():
            with self._lock:
                outstanding = rep.outstanding
            if outstanding == 0:
                try:
                    st = rep.client.engine_stats(timeout=5.0) or {}
                except ReplicaUnavailable:
                    return False        # it died; restart will tell
                if not st or (int(st.get("queue_depth", 0) or 0) == 0
                              and int(st.get("occupancy", 0) or 0)
                              == 0):
                    return True
            time.sleep(self.drain_poll_s)
        return False

    def _wait_ready(self, rep: Replica) -> bool:
        """Probe ``/ready`` with the shared retry-backoff shape
        (deploy.http_retry's curve) until the restart deadline."""
        import random
        deadline = time.monotonic() + self.restart_timeout_s
        delay = HTTP_RETRY_BASE_S
        while time.monotonic() < deadline \
                and not self._stop_evt.is_set():
            try:
                if rep.client.ready(timeout=5.0):
                    return True
            except ReplicaUnavailable:
                pass
            time.sleep(min(delay * (1.0 + random.random()
                                    * BACKOFF_JITTER),
                           max(deadline - time.monotonic(), 0.0)))
            delay = min(delay * BACKOFF_FACTOR, 2.0)
        return False

    # -- aggregated observability -------------------------------------------
    def _group_items(self) -> List[Tuple[str, str]]:
        """One ``(group key, scraped /metrics text)`` per registry
        group — the SLO merge's input.  In-process replicas share a
        registry (and a group), so their already-merged histograms
        count once.  Live members' texts win over an ejected former
        leader's stale snapshot (which would otherwise freeze the
        merged window until readmission); an all-dead group falls back
        to its last sight."""
        with self._lock:
            texts: Dict[str, str] = {}
            for rep in self._replicas:
                if rep.metrics_text and rep.state != EJECTED \
                        and rep.registry_key not in texts:
                    texts[rep.registry_key] = rep.metrics_text
            for rep in self._replicas:
                if rep.metrics_text \
                        and rep.registry_key not in texts:
                    texts[rep.registry_key] = rep.metrics_text
            return list(texts.items())

    def _group_texts(self) -> List[str]:
        return [text for _key, text in self._group_items()]

    def _group_samples(self) -> List[Tuple[str, list]]:
        """Parsed samples per registry group, memoized on the scraped
        text OBJECT (each scrape stores a fresh string): both fleet
        histograms read the same tick's texts, so the full Prometheus
        parse runs once per group per scrape instead of once per
        histogram per read."""
        out = []
        for key, text in self._group_items():
            with self._lock:
                cached = self._samples_cache.get(key)
            if cached is None or cached[0] is not text:
                cached = (text, parse_samples(text))
                with self._lock:
                    self._samples_cache[key] = cached
            out.append((key, cached[1]))
        return out

    def _has_group_texts(self) -> bool:
        with self._lock:
            return any(r.metrics_text for r in self._replicas)

    def merged_slo_doc(self) -> dict:
        """The fleet ``GET /slo.json``: windowed percentiles + burn
        over the MERGED per-replica histograms (scraped cumulative
        buckets summed per registry group, windowed by the same
        HistogramWindow ring the per-process tracker uses)."""
        metrics = {}
        for key, w in self._slo_windows.items():
            _hist, pairs, count, total = w.delta()
            out = {"count": int(count),
                   "sum_seconds": round(float(total), 6)}
            for q in (0.5, 0.95, 0.99):
                out[f"p{int(q * 100)}_ms"] = round(
                    1e3 * quantile_from_cumulative(pairs, q), 3)
            target_ms = self._slo_targets_ms.get(key, 0.0)
            out["target_p99_ms"] = target_ms
            if target_ms > 0 and pairs:
                frac = fraction_over(pairs, target_ms / 1e3)
                burn = frac / 0.01
                out["frac_over_target"] = round(frac, 5)
                out["burn_rate"] = round(burn, 3)
                out["burning"] = burn >= self._slo_burn_threshold \
                    and count >= 10
            else:
                out["frac_over_target"] = 0.0
                out["burn_rate"] = 0.0
                out["burning"] = False
            metrics[key] = out
        return {
            "fleet": True,
            "replica_groups": len(self._group_texts()),
            "window_s": self._slo_window_s,
            "slices": self._slo_slices,
            "burn_threshold": self._slo_burn_threshold,
            "metrics": metrics,
            "burning": any(m["burning"] for m in metrics.values()),
        }

    def fleet_doc(self) -> dict:
        """``GET /fleet.json`` — the topology document: every replica
        with state/load/backoff, the dispatch policy knobs, affinity
        health, and the last swap / rolling-drain outcomes."""
        with self._lock:
            replicas = [r.doc() for r in self._replicas]
            hits, reqs = self._affinity_hits, self._affinity_requests
            affinity_entries = len(self._affinity)
            kv_bw = self._kv_bw_ewma
            kv_transfers = self._kv_transfers
            roles: Dict[str, int] = {}
            for r in self._replicas:
                roles[r.role] = roles.get(r.role, 0) + 1
            # versions come from the scrape cache, NOT live HTTP: the
            # topology document is what operators poll during an
            # incident, and a wedged replica must not make it hang
            versions = {r.id: r.active_version for r in self._replicas
                        if r.state != EJECTED
                        and r.active_version is not None}
        return {
            "role": "fleet-router",
            "draining": self._draining,
            "replicas": replicas,
            "active_versions": versions,
            "dispatch": {
                "scrape_interval_s": self.scrape_interval_s,
                "hysteresis": self.hysteresis,
                "affinity_pages": self.affinity_pages,
                "page_size": self.page_size,
                "eject_failures": self.eject_failures,
            },
            "affinity": {
                "entries": affinity_entries,
                "requests": reqs, "hits": hits,
                "hit_rate": round(hits / reqs, 4) if reqs else 0.0,
            },
            "roles": roles,
            "kv_transfer": {
                "enabled": self.kv_transfer_enabled,
                "min_pages": self.kv_min_pages,
                "timeout_s": self.kv_timeout_s,
                "prewarm_pages": self.prewarm_pages,
                "transfers": kv_transfers,
                "bandwidth_Bps": round(kv_bw, 1),
            },
            "last_swap": self._last_swap,
            "last_rolling_drain": self._last_drain,
            **({"jobs": self.jobs.summary()}
               if self.jobs is not None else {}),
            **({"experiments": self.experiments.summary()}
               if self.experiments is not None else {}),
        }


class FleetServer(Logger):
    """The router's HTTP front: ``POST /generate`` dispatches across
    the fleet; ``GET /fleet.json`` / merged ``/slo.json`` / ``/metrics``
    aggregate it; ``POST /admin/reload`` runs the coordinated two-phase
    swap, ``POST /admin/rolling-drain`` the zero-downtime restart
    cycle, ``POST /admin/join`` registers a new replica by URL, and
    ``POST /admin/drain`` shuts the fleet down.  Same stdlib threading
    server shape as :class:`~.restful.RestfulServer`."""

    def __init__(self, router: FleetRouter, *, port: int = 0,
                 host: str = "127.0.0.1", jobs_dir: Optional[str] = None,
                 experiments=None):
        import http.server

        from ..experiments.manager import handle_experiments_request
        from .jobs import JobManager, handle_jobs_request
        from .restful import (read_json_body, reply_json,
                              reply_metrics_text)
        self.router = router
        # batch lane (docs/serving.md "Batch lane"): a job store dir —
        # explicit arg or root.common.serve.jobs.dir — turns on the
        # fleet-level job API.  Dispatch IS handle_generate: every
        # sharded prompt rides the same affinity routing, failover and
        # idempotent resubmission as interactive traffic, just on the
        # trough class.
        if jobs_dir is None:
            jobs_dir = str(root.common.serve.jobs.get("dir", "") or "")
        self.jobs: Optional[JobManager] = None
        if jobs_dir:
            self.jobs = JobManager(jobs_dir, router.handle_generate)
            router.jobs = self.jobs
        # experiment control plane (docs/experiments.md): an attached
        # ExperimentManager serves /experiments* fleet-wide and shows
        # up in /fleet.json.  The manager is owned by the caller (its
        # trial factory / promotion hook are wired there); this server
        # routes to it and stops it on shutdown.
        self.experiments = experiments
        if experiments is not None:
            router.experiments = experiments
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, obj, code=200, headers=()):
                reply_json(self, obj, code=code, headers=headers)

            def _stream_reply(self, frames):
                """Relay router stream frames as chunkless NDJSON —
                headers first, then one flushed JSON line per frame
                (same wire shape as the replica's own streaming
                ``/generate``); the consumer reads to connection
                close.  A client that disconnects mid-stream closes
                the relay generator, which releases the upstream
                dispatch leg."""
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Cache-Control", "no-store")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for frame in frames:
                        self.wfile.write(
                            (json.dumps(frame) + "\n").encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    pass    # consumer went away; generator close below
                finally:    # ends the upstream leg either way
                    if hasattr(frames, "close"):
                        frames.close()

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    reply_metrics_text(self)
                    return
                if path == "/fleet.json":
                    self._reply(outer.router.fleet_doc())
                    return
                if path == "/slo.json":
                    self._reply(outer.router.merged_slo_doc())
                    return
                if path == "/healthz":
                    self._reply({"status": "alive",
                                 "role": "fleet-router"})
                    return
                if path == "/ready":
                    up = [r for r in outer.router.replicas()
                          if r.state == ACTIVE and r.ready]
                    ok = bool(up) and not outer.router.draining
                    self._reply(
                        {"ready": ok, "replicas_ready": len(up)},
                        code=200 if ok else 503)
                    return
                hit = handle_jobs_request(outer.jobs, "GET",
                                          self.path, None)
                if hit is None:
                    hit = handle_experiments_request(
                        outer.experiments, "GET", self.path, None)
                if hit is not None:
                    self._reply(hit[1], code=hit[0])
                    return
                self.send_error(404)

            def do_DELETE(self):
                hit = handle_jobs_request(outer.jobs, "DELETE",
                                          self.path, None)
                if hit is None:
                    hit = handle_experiments_request(
                        outer.experiments, "DELETE", self.path, None)
                if hit is not None:
                    self._reply(hit[1], code=hit[0])
                    return
                self.send_error(404)

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                try:
                    req = read_json_body(self)  # shared ingress:
                    if req is None:             # cap -> 413 inside
                        return
                    if path == "/generate":
                        hdr = self.headers.get("X-Priority")
                        if hdr is not None:
                            req.setdefault("priority", hdr)
                        if req.get("stream"):
                            code, result, headers = \
                                outer.router.handle_generate_stream(req)
                            if code != 200:
                                self._reply(result, code=code,
                                            headers=headers)
                                return
                            self._stream_reply(result)
                            return
                        code, doc, headers = \
                            outer.router.handle_generate(req)
                        self._reply(doc, code=code, headers=headers)
                        return
                    if path == "/admin/reload":
                        out = outer.router.coordinated_swap(
                            source=req.get("source") or req.get("path"),
                            version=req.get("version"))
                        self._reply(out,
                                    code=200 if out.get("swapped")
                                    else 409)
                        return
                    if path == "/admin/rolling-drain":
                        self._reply(outer.router.begin_rolling_drain(),
                                    code=202)
                        return
                    if path == "/admin/join":
                        url = req.get("url")
                        if not url:
                            self._reply(
                                {"error": 'join needs {"url": ...}'},
                                code=400)
                            return
                        rep = outer.router.add_replica(
                            url=str(url),
                            registry_key=req.get("registry_key"),
                            role=req.get("role"))
                        self._reply({"joined": rep.id,
                                     "url": rep.client.base_url})
                        return
                    if path == "/admin/drain":
                        self._reply(outer.router.begin_drain(),
                                    code=202)
                        return
                    hit = handle_jobs_request(outer.jobs, "POST",
                                              self.path, req)
                    if hit is None:
                        hit = handle_experiments_request(
                            outer.experiments, "POST", self.path, req)
                    if hit is not None:
                        self._reply(hit[1], code=hit[0])
                        return
                    self.send_error(404)
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._reply({"error": str(e)}, code=400)
                except Exception as e:  # noqa: BLE001 — the router
                    # must answer even when a fleet op blows up
                    self._reply({"error": f"{type(e).__name__}: {e}"},
                                code=500)

            def log_message(self, *args):
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port),
                                                     Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetServer":
        self.router.start()
        if self.jobs is not None:
            self.jobs.start()
        if self.experiments is not None:
            # resumes every persisted non-terminal experiment
            self.experiments.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("fleet router serving on http://127.0.0.1:%d "
                  "(/generate, /fleet.json)", self.port)
        return self

    def stop(self):
        if self.experiments is not None:
            # drain the optimization loop first (its sweeps ride the
            # job manager below); state stays "running" on disk for
            # the successor manager's resume
            self.experiments.stop()
        if self.jobs is not None:
            # stop scheduling batch dispatches before the router's
            # replicas go away; committed results resume elsewhere
            self.jobs.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.router.stop()

    def install_signal_handlers(self) -> bool:
        import signal

        def _on_sigterm(signum, frame):  # noqa: ARG001
            self.info("SIGTERM: draining the fleet before exit")
            self.router.begin_drain()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
            return True
        except ValueError:
            self.warning(
                "not the main thread; SIGTERM handler not installed")
            return False


class InProcessReplica:
    """Owns one in-process replica stack built by ``factory`` — a
    zero-arg callable returning a STARTED
    :class:`~.restful.RestfulServer` (deploy control plane attached) —
    and adapts it to the router's handle contract: ``url`` to dispatch
    to, ``kill`` for the fault harness (hard stop, no drain — in-flight
    work fails the way a crashed process would), ``restart`` for the
    rolling drain (tear down, rebuild through the factory — for an
    artifact-booted fleet that is a fresh boot from the sealed
    artifact — and hand the router the new URL)."""

    def __init__(self, factory: Callable[[], object]):
        self.factory = factory
        self.srv = factory()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.srv.port}"

    def kill(self):
        """Crash simulation: the listener closes and the engine stops
        without drain — queued and mid-flight work FAILS (503/500 to
        whoever is on the wire), exactly the shape a SIGKILLed replica
        process presents to the router."""
        self.srv.stop()

    def restart(self) -> str:
        """Rolling-drain reboot: stop the old stack (the router
        already stopped routing to it and waited out its in-flight
        work), rebuild through the factory, return the new URL."""
        try:
            self.srv.stop()
        except Exception:  # noqa: BLE001 — a half-dead old stack must
            pass           # not block its own replacement
        self.srv = self.factory()
        return self.url

    def stop(self):
        try:
            self.srv.stop()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
