"""Memory accounting: HBM gauges + a deterministic component ledger.

Every capacity question on the ROADMAP — can quantization double
slots-per-chip, does ZeRO-style weight-update sharding make the
optimizer state fit, how many more requests does the page pool hold —
starts with "how much HBM is in use and what is it".  Two layers answer
it (docs/observability.md "Memory ledger"):

* **device truth** — ``device.memory_stats()`` where the backend
  provides it (TPU/GPU; CPU backends usually return nothing), sampled
  into ``vt_hbm_bytes_{in_use,peak,limit}`` by an optional poller
  thread (``root.common.observe.memory_poll_s``) and on every
  ``GET /memory.json``;
* **component ledger** — deterministic, CPU-testable byte counts
  computed from avals (shape x itemsize, :func:`tree_bytes`): the
  engine registers its params / KV page pool / slot state, the Trainer
  its params / opt_state / prefetch staging.  The ledger is what the
  device number decomposes INTO — the gap between the two is XLA
  workspace + fragmentation, which is exactly the quantity an operator
  needs named before trusting a "it should fit" estimate.

Everything here is host-side (no trace roots; the analyzer's VT103 rule
keeps it that way) — accounting never touches a compiled program.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ..config import root
from ..logger import Logger
from .metrics import registry


def tree_bytes(tree) -> int:
    """Exact payload bytes of a pytree of arrays / ShapeDtypeStructs /
    scalars: sum of ``prod(shape) * dtype.itemsize`` per leaf.  Works on
    avals — no device sync, no materialization — which is what makes
    the ledger CPU-testable and identical on every backend."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            shape = np.shape(leaf)
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            dtype = np.asarray(leaf).dtype
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


class MemoryMonitor(Logger):
    """Process-wide memory view: device HBM gauges + the component
    ledger, one instance behind :func:`memory_monitor` (components are
    registered by whichever engine/trainer lives in the process; the
    newest registration of a name wins, matching every other
    process-global gauge here)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._components: Dict[str, int] = {}  # guarded-by: self._lock
        self._stamps: Dict[str, int] = {}      # guarded-by: self._lock
        self._next_stamp = 0                   # guarded-by: self._lock
        self._extras: Dict[str, object] = {}   # guarded-by: self._lock
        self._poller: Optional[threading.Thread] = None  # guarded-by: self._lock
        self._last_device: Optional[dict] = None  # guarded-by: self._lock
        reg = registry()
        self._g_in_use = reg.gauge(
            "vt_hbm_bytes_in_use",
            "device memory in use, summed over local devices "
            "(device.memory_stats(); absent backends report nothing)")
        self._g_peak = reg.gauge(
            "vt_hbm_bytes_peak",
            "peak device memory in use since process start, summed over "
            "local devices")
        self._g_limit = reg.gauge(
            "vt_hbm_bytes_limit",
            "device memory capacity, summed over local devices")
        self._g_comp = reg.gauge(
            "vt_memory_component_bytes",
            "aval-derived byte ledger by component (engine params / KV "
            "page pool / slot state, trainer params / opt_state / "
            "prefetch staging)", labels=("component",))

    # -- component ledger ---------------------------------------------------
    def set_component(self, name: str, nbytes: int) -> int:
        """Publish one ledger entry; returns a registration stamp the
        owner passes back to :meth:`drop_component` so a dying OLD
        registrant (a replaced engine being GC'd) can never clobber the
        entry a newer one wrote under the same name.  The gauge write
        happens under the same lock as the stamp, so the ledger and
        ``vt_memory_component_bytes`` can never diverge across a
        drop/re-register race."""
        nbytes = int(nbytes)
        with self._lock:
            self._components[name] = nbytes
            self._next_stamp += 1
            stamp = self._stamps[name] = self._next_stamp
            self._g_comp.labels(component=name).set(nbytes)
        return stamp

    def drop_component(self, name: str,
                       stamp: Optional[int] = None) -> None:
        """Remove a ledger entry — called when its owner's buffers are
        actually released (engines/trainers hook this on finalization).
        With ``stamp`` the drop only applies if the entry still belongs
        to that registration (gauge write under the lock: see
        :meth:`set_component`)."""
        with self._lock:
            if stamp is not None and self._stamps.get(name) != stamp:
                return
            self._components.pop(name, None)
            self._stamps.pop(name, None)
            self._g_comp.labels(component=name).set(0)

    def set_extra(self, name: str, value) -> int:
        """Free-form JSON-able annotations shipped in /memory.json next
        to the ledger (the engine's pool geometry).  Stamped like
        components so a freed owner's finalizer retires its own extras
        without clobbering a newer registrant's."""
        with self._lock:
            self._extras[name] = value
            self._next_stamp += 1
            stamp = self._stamps["extra:" + name] = self._next_stamp
        return stamp

    def drop_extra(self, name: str, stamp: Optional[int] = None) -> None:
        with self._lock:
            if stamp is not None \
                    and self._stamps.get("extra:" + name) != stamp:
                return
            self._extras.pop(name, None)
            self._stamps.pop("extra:" + name, None)

    def components(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._components)

    # -- device truth -------------------------------------------------------
    def sample_device(self) -> Optional[dict]:
        """Sum ``memory_stats()`` over local devices into the HBM gauges;
        None when no local device reports stats (typical CPU)."""
        try:
            import jax
            devices = jax.local_devices()
        except Exception:  # backend not initialized / unavailable
            return None
        in_use = peak = limit = 0
        seen = False
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            seen = True
            in_use += int(stats.get("bytes_in_use", 0))
            peak += int(stats.get("peak_bytes_in_use", 0))
            limit += int(stats.get("bytes_limit",
                                   stats.get("bytes_reservable_limit", 0)))
        if not seen:
            return None
        self._g_in_use.set(in_use)
        self._g_peak.set(peak)
        self._g_limit.set(limit)
        doc = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
               "bytes_limit": limit, "devices": len(devices)}
        with self._lock:
            self._last_device = doc
        return doc

    def ensure_poller(self, interval_s: Optional[float] = None) -> bool:
        """Start the device-stats poller thread once (daemon; a no-op
        when disabled by ``root.common.observe.memory_poll_s = 0`` or
        when the backend reports no stats — a CPU run never spins a
        useless thread).  Idempotent; returns whether a poller runs."""
        if interval_s is None:
            interval_s = float(root.common.observe.get("memory_poll_s", 2.0))
        if interval_s <= 0 or self.sample_device() is None:
            return False
        with self._lock:
            if self._poller is not None and self._poller.is_alive():
                return True

            def loop():
                while True:
                    time.sleep(interval_s)
                    try:
                        self.sample_device()
                    except Exception:  # the poller must never die loudly
                        pass

            self._poller = threading.Thread(
                target=loop, name="hbm-poll", daemon=True)
            self._poller.start()
        return True

    # -- the /memory.json document ------------------------------------------
    def doc(self) -> dict:
        """One consistent JSON view: a fresh device sample (or the last
        one, or null), the component ledger, and the annotations."""
        device = self.sample_device()
        with self._lock:
            if device is None:
                device = self._last_device
            components = dict(self._components)
            extras = dict(self._extras)
        out = {
            "device": device,
            "components": components,
            "component_total_bytes": sum(components.values()),
        }
        if device:
            out["unattributed_bytes"] = max(
                0, device["bytes_in_use"] - out["component_total_bytes"])
        for k, v in extras.items():
            if k not in out:    # extras never shadow the doc's own keys
                out[k] = v
        return out


_MONITOR_LOCK = threading.Lock()
_MONITOR: Optional[MemoryMonitor] = None  # guarded-by: _MONITOR_LOCK


def memory_monitor() -> MemoryMonitor:
    """THE process memory monitor (what ``GET /memory.json`` renders)."""
    global _MONITOR
    with _MONITOR_LOCK:
        if _MONITOR is None:
            _MONITOR = MemoryMonitor()
        return _MONITOR


def drop_stamped_components(stamps: Dict[str, int],
                            extra_stamps: Optional[Dict[str, int]] = None
                            ) -> None:
    """Finalizer hook: drop the ledger entries (and extras) of one
    registration — engines/trainers attach this via ``weakref.finalize``
    so a freed object's bytes AND its geometry annotation leave
    /memory.json; a newer registrant's entries survive the stamp
    check."""
    mon = memory_monitor()
    for name, stamp in stamps.items():
        mon.drop_component(name, stamp)
    for name, stamp in (extra_stamps or {}).items():
        mon.drop_extra(name, stamp)
