"""Autoregressive decoding for the sequence model family: ``generate()``
with a per-layer KV cache.

Round-2 verdict gap #2: the LM path existed end to end (embedding ->
attention -> heads -> per-position CE) but had no sampling loop and no KV
cache — decoding recomputed full-T attention per token, O(T^2) per step.
This module compiles a decode step that attends one query position
against cached K/V (O(T) per step, the standard KV-cache inference
formulation) and wraps it in a ``lax.scan`` token loop with greedy or
temperature sampling.

No reference counterpart (the reference has no attention — SURVEY.md
§5.7); the contract mirrors what users of any LM framework expect:
``generate(wf, wstate, prompt, n_steps)`` -> ``(B, P + n_steps)`` tokens
whose greedy continuation equals the full-forward argmax at every step
(asserted by tests/test_generate.py).

Supported chains (a linear workflow, same rule as the 1F1B compiler):
``embedding`` -> any mix of {attention, rnn/gru/lstm, layer_norm,
per-position all2all, pipeline_stack of those} -> optional ``seq_last``
-> dense heads. The prompt is prefilled through the same cached step
(teacher-forced), so there is exactly one compiled program.

Recurrent units decode with O(1) carried state — the cell functions are
the SAME ones the training scan uses (ops/recurrent.py rnn_cell/
gru_cell/lstm_cell), so decode cannot drift from the forward pass.  A
``return_sequences=False`` recurrent ends the sequence segment the way
``seq_last`` does: the current hidden state IS the last hidden state at
every step (reference capability: Znicz declared-but-untested RNN/LSTM,
docs/source/manualrst_veles_algorithms.rst:115-134 — productized here
through training, decode, export, and the C++ serving runtime).

MoE units decode per position (router + expert FFN are token-local).
Caveat: MoE *capacity* is a training construct whose drops depend on
the whole batch — in a full forward a token can even be dropped because
of LATER positions' routes (capacity is not causal).  Decode therefore
FORCES dropless routing (effective capacity_factor = n_experts, so no
route can ever exceed capacity) regardless of the training
capacity_factor — the standard dropless-inference setting, mirrored by
the C++ runtime.  Greedy continuation matches the full forward exactly
whenever the forward itself dropped nothing.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import rotary_embedding
from ..units.base import Context
from ..units.workflow import WorkflowError


def _attn_cache_init(u, params, B: int, L: int, dtype, *,
                     kv_rows: Optional[int] = None,
                     page_size: Optional[int] = None) -> dict:
    """Dense per-slot KV rows ``(B, L, Hk, Dh)``, or — when ``kv_rows`` /
    ``page_size`` are given — the PAGED pool layout ``(kv_rows,
    page_size, Hk, Dh)``: a flat set of fixed-size pages shared by every
    slot through a per-slot page table (runtime/engine.py; the last pool
    row is the scratch page that absorbs masked-off writes)."""
    Dh = params["wk"].shape[1] // u.n_kv_heads
    if kv_rows is not None:
        shape = (kv_rows, page_size, u.n_kv_heads, Dh)
    else:
        shape = (B, L, u.n_kv_heads, Dh)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _rec_state_init(u, B: int) -> dict:
    """O(1) carried state: hidden (and cell for LSTM), f32 like the
    training scan's carry."""
    from ..units.recurrent import LSTM
    st = {"h": jnp.zeros((B, u.hidden), jnp.float32)}
    if isinstance(u, LSTM):
        st["c"] = jnp.zeros((B, u.hidden), jnp.float32)
    return st


def _rec_decode_step(u, params, st, x_t, write_ok=None):
    """One recurrent step via the training scan's own cell functions.

    ``write_ok`` (B,) bool freezes masked-off rows' carried state: a
    cell iteration is NOT idempotent (h moves every call, unlike a KV
    rewrite), so without the gate an engine decode step would advance
    the carry of an inactive slot — harmless for a retired slot, but a
    slot mid-CHUNKED-prefill continues its next slice from these very
    rows (runtime/engine.py), and a stale-token advance between slices
    would corrupt that continuation.  Active rows' math is untouched
    (the select passes their fresh h through bitwise)."""
    from ..ops import recurrent as rec_ops
    from ..units.recurrent import GRU, LSTM, RNN
    if isinstance(u, LSTM):
        h, c = rec_ops.lstm_cell(x_t, st["h"], st["c"], params["w"],
                                 params["b"],
                                 compute_dtype=u.compute_dtype,
                                 forget_bias=u.forget_bias)
        if write_ok is not None:
            h = jnp.where(write_ok[:, None], h, st["h"])
            c = jnp.where(write_ok[:, None], c, st["c"])
        return h, {"h": h, "c": c}
    if isinstance(u, GRU):
        h = rec_ops.gru_cell(x_t, st["h"], params["w"], params["b"],
                             compute_dtype=u.compute_dtype)
        if write_ok is not None:
            h = jnp.where(write_ok[:, None], h, st["h"])
        return h, {"h": h}
    assert isinstance(u, RNN)
    act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[u.activation]
    h = rec_ops.rnn_cell(x_t, st["h"], params["w"], params["b"],
                         activation=act, compute_dtype=u.compute_dtype)
    if write_ok is not None:
        h = jnp.where(write_ok[:, None], h, st["h"])
    return h, {"h": h}


def _rope_rows(x, pos):
    """RoPE for a one-position activation (B, 1, H, D) where each batch
    row sits at its OWN global position ``pos`` (B,) — the slot-batched
    decode formulation.  The per-row angle ``pos * inv_freq`` is the same
    product the scalar path computes (``(offset + arange(1)) * inv_freq``
    with a zero arange), so a row here is bitwise the scalar-path row."""
    B, T, H, D = x.shape
    half = D // 2
    inv_freq = 10000.0 ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (B, half)
    cos = jnp.cos(ang)[:, None, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, None, :].astype(x.dtype)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(B, T, H, D)


def _attn_decode_step(u, params, cache, x_t, pos, pages=None,
                      write_ok=None, *, paged_kernel=False):
    """One-position attention against the cache.

    x_t: (B, E) activation at position ``pos``; cache k/v: (B, L, Hk, Dh).
    ``pos`` is either a scalar (every row at the same position — the
    ``generate()`` scan) or a (B,) vector of PER-ROW positions (the
    continuous-batching engine, where each slot decodes independently).
    Numerics match MultiHeadAttention.apply (f32 score/prob accumulation,
    scale Dh**-0.5, RoPE at the global position, GQA head grouping,
    sliding window, residual).

    ``pages`` switches the per-row path to the PAGED cache layout
    (runtime/engine.py): ``(ptab, page_size, write_ok)`` where the cache
    k/v are a flat page pool ``(pages + 1, page_size, Hk, Dh)`` (last
    row = scratch), ``ptab`` (B, n_ptab) int32 maps each row's logical
    pages to physical pool rows, and the tuple's ``write_ok`` (B,) bool
    routes masked-off rows' KV writes to the scratch page (an inactive
    slot's pages may already belong to ANOTHER slot — its write must
    land nowhere real).

    The standalone ``write_ok`` parameter is the DENSE per-row
    counterpart (ignored when ``pages`` is given): masked-off rows'
    KV scatters are dropped outright.  A retired slot's rewrite used to
    be idempotent (same token, same position, same values), but a slot
    mid-CHUNKED-prefill holds a stale position over cache rows its
    slices are actively filling — an unmasked write there would clobber
    real prefilled KV (runtime/engine.py "Overload survival").  Active
    rows scatter exactly as before, bitwise.  The attention itself is
    unchanged: the gathered
    per-row view ``pool[ptab]`` reshapes to the same (B, L, Hk, Dh)
    logical cache the dense path reads, so tokens stay bitwise
    identical — page indirection is traced data flow, never new
    program structure.

    ``paged_kernel`` (static, keyword-only) routes the paged read side
    through the fused Pallas paged-attention kernel
    (ops/pallas_kernels.py ``paged_attention_decode``): the page table
    rides SMEM and pages stream block-by-block inside the kernel, so
    the flat ``pool[ptab]`` (B, L, Hk, Dh) transient is never
    materialized.  The kernel's online softmax changes summation order,
    so this path is BOUNDED-ERROR vs the gather path (tolerance pinned
    in tests), never bitwise — it is opt-in
    (``root.common.serve.paged_kernel``) and composes with, but never
    silently replaces, the bitwise layouts."""
    B, E = x_t.shape
    H, Hk = u.n_heads, u.n_kv_heads
    dt = u.compute_dtype or x_t.dtype
    xq = x_t.astype(dt)
    pos = jnp.asarray(pos)
    per_row = pos.ndim == 1
    if pages is not None and not per_row:
        raise ValueError("paged attention requires per-row positions "
                         "(the continuous-batching engine path)")

    def proj(w, nh):
        return (xq @ w.astype(dt)).reshape(B, 1, nh, -1)

    q = proj(params["wq"], H)                     # (B, 1, H, Dh)
    k = proj(params["wk"], Hk)
    v = proj(params["wv"], Hk)
    if u.rope:
        if per_row:
            q = _rope_rows(q, pos)
            k = _rope_rows(k, pos)
        else:
            q = rotary_embedding(q, offset=pos)
            k = rotary_embedding(k, offset=pos)
    if pages is not None:
        ptab, psz, write_ok = pages
        n_ptab = ptab.shape[1]
        pool_rows = cache["k"].shape[0]           # pages + 1 (scratch)
        # physical write target: the row's current page (clamped — a
        # pad-step position past l_max must not clip into a REAL page),
        # or the scratch row when the write is masked off
        lpage = jnp.minimum(pos // psz, n_ptab - 1)
        pg = jnp.take_along_axis(ptab, lpage[:, None], axis=1)[:, 0]
        if write_ok is not None:
            pg = jnp.where(write_ok, pg, pool_rows - 1)
        off = pos % psz
        ck = cache["k"].at[pg, off].set(k[:, 0].astype(cache["k"].dtype))
        cv = cache["v"].at[pg, off].set(v[:, 0].astype(cache["v"].dtype))
        Dh = q.shape[-1]
        G = H // Hk
        L = n_ptab * psz
        if paged_kernel:
            from ..ops.pallas_kernels import paged_attention_decode

            def attend():          # (B, H, Dh) f32 context via the
                return paged_attention_decode(   # fused page-streaming
                    q[:, 0], ck, cv, ptab, pos,  # kernel
                    page_size=psz, n_kv_heads=Hk, scale=Dh ** -0.5,
                    window=u.window)

            return _attn_scores(u, params, xq, None, None, None, pos,
                                per_row=per_row, B=B, H=H, Hk=Hk, G=G,
                                Dh=Dh, L=L, dt=dt, out_dtype=x_t.dtype,
                                new_cache={"k": ck, "v": cv},
                                attend=attend)
        qg = q[:, 0].reshape(B, Hk, G, Dh).astype(jnp.float32)
        # per-row logical view: gather the row's pages, flatten to the
        # same (B, L, Hk, Dh) the dense path reads
        kf = ck[ptab].reshape(B, L, Hk, Dh).astype(jnp.float32)
        vf = cv[ptab].reshape(B, L, Hk, Dh).astype(jnp.float32)
        return _attn_scores(u, params, xq, qg, kf, vf, pos,
                            per_row=per_row, B=B, H=H, Hk=Hk, G=G,
                            Dh=Dh, L=L, dt=dt, out_dtype=x_t.dtype,
                            new_cache={"k": ck, "v": cv})
    if per_row:
        rows = jnp.arange(B)
        # masked-off rows scatter at L (one past the cache) and are
        # DROPPED — the dense analogue of the paged scratch row; active
        # rows' indices and values are untouched, so their writes stay
        # bitwise the unmasked program's
        wpos = pos if write_ok is None else \
            jnp.where(write_ok, pos, cache["k"].shape[1])
        ck = cache["k"].at[rows, wpos].set(
            k[:, 0].astype(cache["k"].dtype), mode="drop")
        cv = cache["v"].at[rows, wpos].set(
            v[:, 0].astype(cache["v"].dtype), mode="drop")
    else:
        ck = jax.lax.dynamic_update_slice_in_dim(
            cache["k"], k.astype(cache["k"].dtype), pos, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(
            cache["v"], v.astype(cache["v"].dtype), pos, axis=1)

    Dh = q.shape[-1]
    G = H // Hk
    L = ck.shape[1]
    qg = q[:, 0].reshape(B, Hk, G, Dh).astype(jnp.float32)
    kf = ck.astype(jnp.float32)                   # (B, L, Hk, Dh)
    vf = cv.astype(jnp.float32)
    return _attn_scores(u, params, xq, qg, kf, vf, pos,
                        per_row=per_row, B=B, H=H, Hk=Hk, G=G, Dh=Dh,
                        L=L, dt=dt, out_dtype=x_t.dtype,
                        new_cache={"k": ck, "v": cv})


def _attn_scores(u, params, xq, qg, kf, vf, pos, *, per_row, B, H, Hk,
                 G, Dh, L, dt, out_dtype, new_cache, attend=None):
    """Masked score/softmax/output tail shared by the dense and paged
    cache layouts — ONE copy of the attention math, so the two layouts
    cannot drift numerically.  Positional params are traced values;
    everything static (the ``per_row`` layout switch, head geometry,
    dtypes) is keyword-only — the trace-safety convention
    veles_tpu.analysis checks against (docs/analysis.md).

    ``attend`` (static): when the fused Pallas paged-attention kernel
    computes the context itself (masked softmax·V fused over the page
    sweep), it supplies the (B, ...) float32 context here and the
    score/softmax block is skipped (``qg``/``kf``/``vf`` are None) —
    the output projection / residual / dtype tail below stays the ONE
    shared copy, so even the kernel layout cannot drift on anything
    but the documented summation order."""
    if attend is not None:
        o = attend()                              # (B, H|Hk*G, Dh) f32
    else:
        s = jnp.einsum("bkgd,btkd->bkgt", qg, kf) * (Dh ** -0.5)
        t_idx = jnp.arange(L)
        if per_row:
            mask = t_idx[None, :] <= pos[:, None]     # (B, L)
            if u.window is not None:
                mask &= t_idx[None, :] > pos[:, None] - u.window
            s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
        else:
            mask = t_idx <= pos
            if u.window is not None:
                mask &= t_idx > pos - u.window
            s = jnp.where(mask[None, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkgt,btkd->bkgd", p, vf)  # (B, Hk, G, Dh)
    y = o.reshape(B, H * Dh).astype(dt) @ params["wo"].astype(dt)
    if u.residual:
        y = y + xq
    return y.astype(out_dtype), new_cache


class DecodePlan:
    """Static decode program for a sequence workflow: the unit chain
    classified into cached-attention / pointwise / head segments."""

    def __init__(self, wf, output_unit: Optional[str] = None):
        from ..units import nn
        from ..units.parallel_nn import MultiHeadAttention, PipelineStack
        from ..units.recurrent import _RecurrentBase
        self.wf = wf
        order = [u for u in wf.topo_order()
                 if not getattr(u, "is_evaluator", False)]
        if output_unit is not None:
            keep = wf.ancestors(output_unit)
            order = [u for u in order if u.name in keep]
        prev = "@input"
        for u in order:
            if tuple(u.inputs) != (prev,):
                raise WorkflowError(
                    f"generate() needs a linear unit chain; {u.name!r} "
                    f"consumes {list(u.inputs)}, expected [{prev!r}]")
            prev = u.name
        if not order or not isinstance(order[0], nn.Embedding):
            raise WorkflowError(
                "generate() needs an Embedding unit at the front of the "
                "chain (token ids are the decode interface)")
        self.embedding = order[0]
        # Classify the rest. Before seq_last the activation is one
        # position (B, E...) of the sequence; after it the chain operates
        # on flat (B, ...) sample tensors.
        self.seq_handlers: List[Tuple[str, object]] = []
        self.flat_units: List[object] = []
        seen_last = False
        for u in order[1:]:
            if isinstance(u, nn.SeqLast):
                seen_last = True
            elif seen_last:
                self.flat_units.append(u)
            elif isinstance(u, MultiHeadAttention):
                self._check_attn(u)
                self.seq_handlers.append(("attn", u))
            elif isinstance(u, _RecurrentBase):
                self.seq_handlers.append(("recurrent", u))
                if not u.return_sequences:
                    # the current hidden IS the last hidden: the unit
                    # plays seq_last's role and the rest of the chain
                    # operates on flat (B, H) tensors
                    seen_last = True
            elif isinstance(u, PipelineStack):
                if u.stages_cfg is None:
                    self.seq_handlers.append(("pointwise", u))
                    continue
                stage_h = []
                for i, units in enumerate(u._stage_units):
                    for su in units:
                        if isinstance(su, MultiHeadAttention):
                            self._check_attn(su)
                            stage_h.append(("attn", su, i))
                        elif isinstance(su, _RecurrentBase):
                            if not su.return_sequences:
                                raise WorkflowError(
                                    f"recurrent unit {su.name!r} inside "
                                    "a pipeline stage must return "
                                    "sequences (stages preserve the "
                                    "activation spec)")
                            stage_h.append(("recurrent", su, i))
                        else:
                            self._pointwise_ok(su)
                            stage_h.append(("pointwise", su, i))
                self.seq_handlers.append(("stack", (u, stage_h)))
            else:
                self._pointwise_ok(u)
                self.seq_handlers.append(("pointwise", u))
        self._attn_units = list(self._iter_attn())
        self._rec_units = list(self._iter_recurrent())

    @staticmethod
    def _check_attn(u):
        if not u.causal:
            raise WorkflowError(
                f"attention unit {u.name!r} is non-causal; autoregressive "
                "decoding requires causal attention")

    @staticmethod
    def _pointwise_ok(u):
        from ..units import nn
        from ..units.parallel_nn import MoEFFN
        ok = isinstance(u, (nn.LayerNorm, nn.Dropout, nn.FFN,
                            MoEFFN)) or (
            isinstance(u, nn.All2All) and u.per_position)
        if not ok:
            raise WorkflowError(
                f"unit {u.name!r} ({type(u).__name__}) mixes sequence "
                "positions (or is not per-position); generate() supports "
                "attention, rnn/gru/lstm, moe, layer_norm, ffn, "
                "per-position all2all, pipeline_stack and seq_last "
                "before the head")

    def _iter_attn(self):
        """(cache_key, unit, params_path) for every cached attention."""
        for kind, payload in self.seq_handlers:
            if kind == "attn":
                u = payload
                yield (u.name, u, (u.name,))
            elif kind == "stack":
                stack, stage_h = payload
                for h in stage_h:
                    if h[0] == "attn":
                        _, su, i = h
                        yield (f"{stack.name}/s{i}/{su.name}", su,
                               (stack.name, f"s{i}", su.name))

    def _iter_recurrent(self):
        """(cache_key, unit) for every carried-state recurrent unit."""
        for kind, payload in self.seq_handlers:
            if kind == "recurrent":
                yield (payload.name, payload)
            elif kind == "stack":
                stack, stage_h = payload
                for h in stage_h:
                    if h[0] == "recurrent":
                        _, su, i = h
                        yield (f"{stack.name}/s{i}/{su.name}", su)

    # -- runtime -----------------------------------------------------------
    def attn_keys(self):
        """Cache-dict keys backed by paged-able attention KV (the rest —
        recurrent carried state — stays per-slot even under paging)."""
        return {key for key, _, _ in self._attn_units}

    def init_caches(self, params, B: int, L: int, dtype, *,
                    kv_rows: Optional[int] = None,
                    page_size: Optional[int] = None) -> dict:
        """Zeroed cache tree: attention KV as dense per-slot rows
        (B, L, Hk, Dh), or — when ``kv_rows``/``page_size`` are given —
        as the flat page pool (kv_rows, page_size, Hk, Dh) the paged
        engine indexes through per-slot page tables.  Recurrent carried
        state is (B, ...) either way."""
        caches = {}
        for key, u, path in self._attn_units:
            p = params
            for seg in path:
                p = p[seg]
            caches[key] = _attn_cache_init(u, p, B, L, dtype,
                                           kv_rows=kv_rows,
                                           page_size=page_size)
        for key, u in self._rec_units:
            caches[key] = _rec_state_init(u, B)
        return caches

    def step(self, params, caches, tok, pos, ctx: Context, pages=None,
             write_ok=None, *, paged_kernel=False):
        """One decode position: token ids (B,) -> (logits (B, V), caches).
        O(L) attention per layer via the cache.

        ``pos`` may be a scalar (the whole batch at one position — the
        ``generate()`` scan) or a (B,) vector of per-row positions, the
        masked-batched form the continuous-batching engine
        (runtime/engine.py) drives: each slot attends ``t <= pos[row]``
        and writes its KV at its own position.  Recurrent / pointwise
        units are position-free, so only attention branches on it.

        ``pages`` = (ptab, page_size, write_ok) selects the paged KV
        layout for every attention unit (see :func:`_attn_decode_step`);
        it rides the per-row path only.  ``write_ok`` (B,) bool is the
        DENSE layout's write mask — masked-off rows' KV scatters are
        dropped and their recurrent carry is frozen, so an inactive
        slot (retired, or mid-chunked-prefill with its rows being
        filled by slices) provably leaves no trace in the caches.  On
        the paged layout the tuple's own ``write_ok`` serves both
        roles, so pass one or the other, never both.  ``paged_kernel``
        (static, keyword-only) additionally routes the paged read side
        through the fused Pallas paged-attention kernel —
        bounded-error, see :func:`_attn_decode_step`."""
        x = jnp.take(params[self.embedding.name]["table"],
                     tok.astype(jnp.int32), axis=0)      # (B, E)
        # ONE carry/write mask, whichever layout supplied it: the
        # recurrent state is batch-laid-out regardless of how the KV
        # cache is stored, so the paged tuple's mask gates it too
        carry_ok = pages[2] if pages is not None else write_ok

        def run_pointwise(u, p, x):
            from ..parallel.moe import moe_apply
            from ..units.parallel_nn import MoEFFN
            if isinstance(u, MoEFFN):
                # dropless decode: capacity_factor=E gives C = T*K, so
                # no route can exceed capacity (module doc) — the
                # training capacity_factor would drop routes by batch
                # coincidence at T=B tokens per position
                y, _ = moe_apply(p, x, top_k=u.top_k,
                                 capacity_factor=float(u.n_experts),
                                 dispatch_mode=u.dispatch_mode)
                return y
            y, _ = u.apply(p, {}, [x[:, None]], ctx)
            return y[:, 0]

        for kind, payload in self.seq_handlers:
            if kind == "attn":
                u = payload
                x, caches[u.name] = _attn_decode_step(
                    u, params[u.name], caches[u.name], x, pos, pages,
                    write_ok, paged_kernel=paged_kernel)
            elif kind == "recurrent":
                u = payload
                x, caches[u.name] = _rec_decode_step(
                    u, params[u.name], caches[u.name], x, carry_ok)
            elif kind == "pointwise":
                u = payload
                x = run_pointwise(u, params.get(u.name, {}), x)
            else:  # stack (config stages; legacy stacks classify as
                   # pointwise in __init__ — their MLP math is per-token)
                stack, stage_h = payload
                sp = params[stack.name]
                for h in stage_h:
                    if h[0] == "attn":
                        _, su, i = h
                        key = f"{stack.name}/s{i}/{su.name}"
                        x, caches[key] = _attn_decode_step(
                            su, sp[f"s{i}"][su.name], caches[key], x, pos,
                            pages, write_ok, paged_kernel=paged_kernel)
                    elif h[0] == "recurrent":
                        _, su, i = h
                        key = f"{stack.name}/s{i}/{su.name}"
                        x, caches[key] = _rec_decode_step(
                            su, sp[f"s{i}"][su.name], caches[key], x,
                            carry_ok)
                    else:
                        _, su, i = h
                        x = run_pointwise(
                            su, sp[f"s{i}"].get(su.name, {}), x)
        for u in self.flat_units:
            x, _ = u.apply(params.get(u.name, {}), {}, [x], ctx)
        return x, caches


#: Compiled decode runners kept per workflow (LRU): REST clients control
#: shape/sampling knobs, so an unbounded cache would accumulate one XLA
#: program per distinct request (compile-amplification + memory leak).
#: Callers insert only AFTER the first successful execution, so a
#: trace-time validation error can never cache a broken runner (or
#: evict good ones).  The lock keeps the pop/re-insert LRU touch atomic
#: under the REST server's worker threads; duplicate compilation of the
#: same brand-new shape by two concurrent requests is accepted (results
#: identical, last insert wins).
_runner_lock = __import__("threading").Lock()


def _max_runners() -> int:
    """LRU capacity, tuneable via ``root.common.serve.runner_cache`` (a
    public endpoint decides how many distinct shape/sampling programs
    are worth keeping warm; at least one is always retained)."""
    from ..config import root
    return max(1, int(root.common.serve.get("runner_cache", 32)))


def _runner_cache(wf, ck):
    """(cache, hit_or_None) with LRU touch on hit."""
    with _runner_lock:
        cache = getattr(wf, "_decode_runners", None)
        if cache is None:
            cache = wf._decode_runners = {}
        run = cache.pop(ck, None)
        if run is not None:
            cache[ck] = run  # dict order: re-insert = most recent
        return cache, run


def _runner_cache_put(cache, ck, run):
    with _runner_lock:
        cache[ck] = run
        limit = _max_runners()
        while len(cache) > limit:
            cache.pop(next(iter(cache)))


def sample_logits(logits, key, *, temperature: float = 0.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Next-token choice from (B, V) logits.

    temperature=0 -> greedy argmax; otherwise temperature-scaled
    categorical sampling, optionally restricted to the top-k logits
    and/or the smallest set whose probability mass reaches top_p
    (nucleus sampling).  Pure jnp — runs inside the decode scan.
    """
    logits = logits.astype(jnp.float32)
    if top_k is not None and int(top_k) < 1:
        # 0 would silently disable the filter (index -0 is the MINIMUM
        # logit) and negatives keep near-everything — loud error instead
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        # p <= 0 would wrap the cut index to the smallest logit and
        # disable the filter — the opposite of the intent
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None and int(top_k) < logits.shape[-1]:
        # k-th largest via top_k, not a full-vocabulary sort
        kth = jax.lax.top_k(logits, int(top_k))[0][:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and float(top_p) < 1.0:
        # sort descending; keep tokens while cumulative prob (EXCLUSIVE
        # of the current token) is < top_p — always keeps the argmax
        srt = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        csum = jnp.cumsum(probs, axis=-1) - probs
        cut = jnp.maximum(
            jnp.sum(jnp.where(csum < top_p, 1, 0), axis=-1) - 1, 0)
        thresh = jnp.take_along_axis(srt, cut[:, None], axis=-1)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return jax.random.categorical(key, logits)


def generate(wf, wstate, prompt, n_steps: int, *,
             temperature: float = 0.0, key=None,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_id: Optional[int] = None,
             output_unit: Optional[str] = None,
             cache_dtype=jnp.float32):
    """Decode ``n_steps`` tokens after ``prompt`` (B, P) int32.

    Greedy (temperature=0), temperature sampling, optionally truncated
    by ``top_k`` and/or nucleus ``top_p``. Returns (B, P + n_steps)
    int32 — prompt followed by the continuation. The prompt is prefilled
    through the same cached decode step (teacher-forced), so prefill
    costs O(P·L) per layer and each generated token O(L).

    With ``eos_id`` set, a row that emits it is finished: every later
    position of that row is ``eos_id`` (the returned shape stays
    (B, P + n_steps)), and the token loop is a ``while_loop`` that EXITS
    as soon as every row has finished — decode stops paying for tokens
    past end-of-sequence instead of grinding out padding.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    B, P = prompt.shape
    if P < 1:
        raise ValueError("prompt must hold at least one token")
    L = P + int(n_steps)
    if key is None:
        key = jax.random.key(0)
    params = wstate["params"]
    # Compiled-runner cache on the workflow: repeated calls at one shape
    # (a serving endpoint, a sampling sweep) must not re-trace and
    # re-compile the L-step scan every time.  Keyed on everything traced
    # into the closure; params/prompt/key are runtime args.  Top-level
    # validation (plan construction) still runs on the first call per
    # shape.
    ck = (B, P, int(n_steps), float(temperature),
          None if top_k is None else int(top_k),
          None if top_p is None else float(top_p),
          None if eos_id is None else int(eos_id),
          output_unit, jnp.dtype(cache_dtype).name)
    cache, hit = _runner_cache(wf, ck)
    if hit is not None:
        return hit(params, prompt, key)
    plan = DecodePlan(wf, output_unit)
    ctx = Context(train=False, key=None, mesh=None)

    def body_step(params, key, caches, toks, pos, alive):
        """One token position, shared by the scan and while_loop forms.
        ``params``/``key`` MUST be the jitted runner's own arguments —
        closing over generate()'s locals would bake the first call's
        weights and PRNG key into the cached executable as constants
        (every later cache hit would silently replay them).  ``alive``
        is None on the eos-free path (every row runs to L)."""
        tok = jax.lax.dynamic_slice_in_dim(toks, pos, 1, 1)[:, 0]
        logits, caches = plan.step(params, caches, tok, pos, ctx)
        nxt = sample_logits(
            logits, jax.random.fold_in(key, pos),
            temperature=temperature, top_k=top_k, top_p=top_p)
        # teacher-force prompt positions; write generated thereafter
        gen = pos + 1 >= P
        cur = jax.lax.dynamic_slice_in_dim(toks, pos + 1, 1, 1)[:, 0]
        val = nxt.astype(jnp.int32)
        if alive is not None:
            # finished rows pad with eos from the position after their
            # first eos onward; a row dies the step it EMITS eos (the
            # emitted eos itself is still written by the alive branch)
            val = jnp.where(alive, val, jnp.int32(eos_id))
            alive = alive & (~gen | (nxt.astype(jnp.int32) != eos_id))
        val = jnp.where(gen, val, cur)
        toks = jax.lax.dynamic_update_slice_in_dim(
            toks, val[:, None], pos + 1, 1)
        return caches, toks, alive

    if eos_id is None:
        @jax.jit
        def run(params, prompt, key):
            caches = plan.init_caches(params, B, L, cache_dtype)
            toks = jnp.zeros((B, L), jnp.int32)
            toks = jax.lax.dynamic_update_slice_in_dim(toks, prompt, 0, 1)

            def body(carry, pos):
                caches, toks = carry
                caches, toks, _ = body_step(
                    params, key, caches, toks, pos, None)
                return (caches, toks), None

            (caches, toks), _ = jax.lax.scan(
                body, (caches, toks), jnp.arange(L - 1))
            return toks
    else:
        @jax.jit
        def run(params, prompt, key):
            caches = plan.init_caches(params, B, L, cache_dtype)
            toks = jnp.zeros((B, L), jnp.int32)
            toks = jax.lax.dynamic_update_slice_in_dim(toks, prompt, 0, 1)
            alive = jnp.ones((B,), bool)

            def cond(carry):
                _, _, pos, alive = carry
                return (pos < L - 1) & alive.any()

            def body(carry):
                caches, toks, pos, alive = carry
                caches, toks, alive = body_step(
                    params, key, caches, toks, pos, alive)
                return caches, toks, pos + 1, alive

            caches, toks, pos, alive = jax.lax.while_loop(
                cond, body, (caches, toks, jnp.int32(0), alive))
            # rows can only die at generated positions (>= P), so every
            # unwritten position past the early exit is eos padding
            return jnp.where(jnp.arange(L)[None, :] > pos,
                             jnp.int32(eos_id), toks)

    out = run(params, prompt, key)
    _runner_cache_put(cache, ck, run)  # only successful runners cache
    return out


def generate_beam(wf, wstate, prompt, n_steps: int, *, beams: int = 4,
                  eos_id: Optional[int] = None,
                  length_penalty: float = 0.0,
                  output_unit: Optional[str] = None,
                  cache_dtype=jnp.float32):
    """Beam-search decode: (B, P) int32 -> (tokens (B, P + n_steps),
    scores (B,)) — the highest-scoring of ``beams`` hypotheses per row.

    Scores are the GENERATED continuation's summed token
    log-probabilities (the prompt's own log-prob is a per-row constant
    and is deliberately excluded — it would distort length
    normalization), normalized by ``len ** length_penalty`` over the
    generated length (0 = raw sum; >0 favors longer continuations, the
    GNMT convention).  With ``eos_id`` set, a beam that emits it is
    finished: its score freezes and it pads with ``eos_id``.
    ``beams=1`` reduces exactly to greedy :func:`generate`; a width
    covering the whole search space finds the global
    maximum-probability continuation (asserted in tests against
    brute-force enumeration).

    Implementation: the batch axis carries B*W rows through the same
    cached decode step; each expansion takes the top W of the W*V
    candidate scores per row and REORDERS every cache (KV and recurrent
    state alike) by the surviving beams' parents — one gather on the
    batch axis per step.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    B, P = prompt.shape
    W = int(beams)
    if P < 1:
        raise ValueError("prompt must hold at least one token")
    if W < 1:
        raise ValueError(f"beams must be >= 1, got {W}")
    L = P + int(n_steps)
    params = wstate["params"]
    ck = ("beam", B, P, int(n_steps), W, eos_id,
          float(length_penalty), output_unit, jnp.dtype(cache_dtype).name)
    cache, hit = _runner_cache(wf, ck)
    if hit is not None:
        return hit(params, prompt)
    plan = DecodePlan(wf, output_unit)
    ctx = Context(train=False, key=None, mesh=None)
    NEG = jnp.float32(-1e30)

    @jax.jit
    def run(params, prompt):
        # rows are (B, W) flattened; every beam starts as a copy of its
        # batch row, but only beam 0 has score 0 — the first expansion
        # would otherwise select W identical hypotheses
        caches = plan.init_caches(params, B * W, L, cache_dtype)
        toks = jnp.zeros((B * W, L), jnp.int32)
        toks = jax.lax.dynamic_update_slice_in_dim(
            toks, jnp.repeat(prompt, W, axis=0), 0, 1)
        scores = jnp.tile(jnp.where(jnp.arange(W) == 0, 0.0, NEG), B)
        alive = jnp.ones((B * W,), bool)

        def body(carry, pos):
            caches, toks, scores, alive = carry
            tok = jax.lax.dynamic_slice_in_dim(toks, pos, 1, 1)[:, 0]
            logits, caches = plan.step(params, caches, tok, pos, ctx)
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1)      # (B*W, V)
            V = logp.shape[-1]
            if eos_id is not None:
                # finished beams extend ONLY with eos at zero cost
                frozen = jnp.full((V,), NEG).at[eos_id].set(0.0)
                logp = jnp.where(alive[:, None], logp, frozen[None])
            gen = pos + 1 >= P
            cur = jax.lax.dynamic_slice_in_dim(toks, pos + 1, 1, 1)[:, 0]
            # generation: top W of the W*V candidates per batch row
            # (prefill accumulates NOTHING — the prompt's log-prob is a
            # per-row constant that would distort length-normalized
            # ranking; beams only score their generated continuation)
            cand = scores[:, None] + logp                 # (B*W, V)
            flat = cand.reshape(B, W * V)
            top_s, top_i = jax.lax.top_k(flat, W)         # (B, W)
            parent = top_i // V + jnp.arange(B)[:, None] * W
            nxt_tok = (top_i % V).astype(jnp.int32)

            def expand(ops):
                caches, toks, alive = ops
                idx = parent.reshape(-1)
                caches = jax.tree.map(
                    lambda a: jnp.take(a, idx, axis=0), caches)
                return (caches, jnp.take(toks, idx, axis=0),
                        jnp.take(alive.astype(jnp.int32), idx,
                                 axis=0).astype(bool))

            # cond, not a traced-index gather: prefill steps must keep
            # XLA's in-place cache updates (a where-selected index
            # defeats them and copies every KV cache per prompt token)
            caches, toks, alive = jax.lax.cond(
                gen, expand, lambda ops: ops, (caches, toks, alive))
            scores = jnp.where(gen, top_s.reshape(-1), scores)
            if eos_id is not None:
                alive = alive & (~gen | (nxt_tok.reshape(-1) != eos_id))
            val = jnp.where(gen, nxt_tok.reshape(-1), cur)
            toks = jax.lax.dynamic_update_slice_in_dim(
                toks, val[:, None], pos + 1, 1)
            return (caches, toks, scores, alive), None

        (caches, toks, scores, alive), _ = jax.lax.scan(
            body, (caches, toks, scores, alive), jnp.arange(L - 1))
        # length normalization over the generated length (all beams
        # generate n_steps here; with eos the finished length differs,
        # but frozen padding contributed 0 — normalize by first-eos
        # position when eos_id is set)
        toks_bw = toks.reshape(B, W, L)
        scores_bw = scores.reshape(B, W)
        if length_penalty:
            if eos_id is not None:
                gen_part = toks_bw[:, :, P:]
                ended = gen_part == eos_id
                first = jnp.where(
                    ended.any(-1), jnp.argmax(ended, -1) + 1,
                    gen_part.shape[-1])
            else:
                first = jnp.full((B, W), L - P)
            scores_bw = scores_bw / (first.astype(jnp.float32)
                                     ** length_penalty)
        best = jnp.argmax(scores_bw, axis=-1)
        out = jnp.take_along_axis(
            toks_bw, best[:, None, None].repeat(L, -1), 1)[:, 0]
        return out, jnp.take_along_axis(scores_bw, best[:, None], 1)[:, 0]

    out = run(params, prompt)
    _runner_cache_put(cache, ck, run)  # only successful runners cache
    return out
