"""Status reporting: file + tiny HTTP endpoint with live plots.

Reference parity: the web-status stack (reference: veles/web_status.py:113 —
Tornado+MongoDB server; masters POSTed {name, master, time, slaves, plots}
every second from veles/launcher.py:852-885) and the browser-rendered live
plots of the WebAgg graphics backend (veles/graphics_client.py:84,
graphics_server.py:174-220).

TPU redesign: a StatusReporter writes status.json atomically (any dashboard
can poll it; no MongoDB), and an optional StatusServer thread serves it over
stdlib HTTP with a minimal HTML view — zero dependencies, one process.
Nested gauge groups render as dotted rows, so the decode engine's paged
KV-cache pool (``engine.pages.free`` / ``engine.pages.prefix_hit_rate``
/ ``engine.pages.tokens_resident`` / ``engine.pages.evictions`` …)
lands on the page next to the compile counters with no schema here.
When a ``plots_dir`` is set, the page also embeds every PNG in it with a
mtime cache-buster under the existing 2-second meta refresh, so a running
job's metric curves are WATCHABLE live in a browser (round-2 verdict
missing #3) — the MetricsRecorder autosaves the PNGs each epoch."""

from __future__ import annotations

import collections
import html
import http.server
import json
import os
import threading
import time
import urllib.parse
from typing import Optional

from ..config import root
from ..logger import Logger
from .memory import memory_monitor
from .metrics import registry, span_ring
from .profiler import profiler, serve_profile_post
from .slo import slo_tracker


class StatusReporter(Logger):
    """Atomically maintained status.json (reference: the per-master status
    document).

    Event flushes COALESCE: ``record_event`` bursts (a retire storm at
    a deadline sweep, a watcher flapping) rewrite status.json at most
    once per ``flush_interval_s`` (default ``root.common.observe
    .status_flush_s``) instead of fsync-storming the disk — a deferred
    burst is always flushed by a trailing timer, so the final state
    lands within one interval.  Direct ``update()`` calls still write
    through immediately (their callers are already epoch/0.5s-cadence
    throttled)."""

    def __init__(self, path: str = "status.json", name: str = "workflow",
                 plots_dir: Optional[str] = None,
                 graph_svg: Optional[str] = None,
                 events_max: int = 20,
                 flush_interval_s: Optional[float] = None):
        self.path = path
        self.name = name
        self.plots_dir = plots_dir
        # path to the rendered workflow-graph SVG (Workflow.generate_svg)
        # — the status page embeds it, closing the reference's live
        # browser graph view (/root/reference/web/viz.js)
        self.graph_svg = graph_svg
        self.started = time.time()
        self.flush_interval_s = float(
            root.common.observe.get("status_flush_s", 0.25)
            if flush_interval_s is None else flush_interval_s)
        # one reporter, many writers (engine scheduler, deploy control
        # plane, trainer): _lock serializes the read-modify-write on
        # _extra / _events and stays IO-free — the scheduler tick must
        # never stall behind a slow disk (veles-tpu-lint VC205); the
        # actual tmp-file write serializes on _io_lock, a dedicated
        # IO mutex held across the write by design (unannotated: it
        # guards no shared data, only orders the file replaces)
        self._extra = {}  # guarded-by: self._lock
        self._events = collections.deque(maxlen=max(1, int(events_max)))  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._last_flush = 0.0  # guarded-by: self._lock
        self._flush_timer: Optional[threading.Timer] = None  # guarded-by: self._lock
        self._doc_seq = 0  # guarded-by: self._lock
        self._io_lock = threading.Lock()
        self._written_seq = 0   # newest doc seq on disk (under _io_lock)
        reg = registry()
        self._m_flushes = reg.counter(
            "vt_status_flushes_total", "status.json writes")
        self._m_coalesced = reg.counter(
            "vt_status_flushes_coalesced_total",
            "event flushes deferred into the trailing coalescing timer "
            "(root.common.observe.status_flush_s)")

    def plot_files(self):
        """Sorted (name, mtime) of the PNGs currently in plots_dir."""
        if not self.plots_dir or not os.path.isdir(self.plots_dir):
            return []
        out = []
        for fn in sorted(os.listdir(self.plots_dir)):
            if fn.endswith(".png"):
                try:
                    mt = os.path.getmtime(os.path.join(self.plots_dir, fn))
                except OSError:
                    continue
                out.append((fn, mt))
        return out

    def record_event(self, kind: str, **info) -> None:
        """Append to the bounded event log shipped inside status.json
        (``events`` key, newest last): discrete lifecycle moments — a
        weight swap, a drain, a watcher failure — that a sampled gauge
        can't show (the deploy control plane's swap/version history,
        runtime/deploy.py).  Writes coalesce (class docstring); events
        also land as instants on the ``/trace.json`` timeline."""
        at = time.monotonic()
        with self._lock:
            # under the same lock update() iterates the deque with —
            # an un-locked append can blow up that iteration
            self._events.append(
                {"kind": str(kind), "time": round(time.time(), 3), **info})
            stamped = self._flush_locked(coalesce=True)
        self._write_doc(stamped)
        span_ring().add_instant(str(kind), at, cat="status", args=info)

    def update(self, **fields) -> None:
        with self._lock:
            self._extra.update(fields)
            stamped = self._flush_locked(coalesce=False)
        self._write_doc(stamped)

    def _flush_locked(self, *, coalesce: bool):  # requires-lock: self._lock
        """Decide defer-vs-flush and snapshot the document under the
        lock; the caller performs the file write AFTER releasing it.
        Returns ``(doc, seq)`` to write, or None when deferred."""
        now = time.monotonic()
        if coalesce and now - self._last_flush < self.flush_interval_s:
            self._m_coalesced.inc()
            if self._flush_timer is None:
                # trailing flush: the burst's FINAL state always lands
                # within one interval of its last event
                delay = self._last_flush + self.flush_interval_s - now
                t = threading.Timer(max(delay, 0.005), self._timer_flush)
                t.daemon = True
                self._flush_timer = t
                t.start()
            return None
        return self._doc_locked(now)

    def _timer_flush(self) -> None:
        with self._lock:
            self._flush_timer = None
            stamped = self._doc_locked(time.monotonic())
        self._write_doc(stamped)

    def _doc_locked(self, now: float):  # requires-lock: self._lock
        """Snapshot the status document + a monotonic sequence stamp
        (the write-ordering token _write_doc checks)."""
        self._last_flush = now
        if self._flush_timer is not None:
            # a direct write supersedes the pending trailing flush
            self._flush_timer.cancel()
            self._flush_timer = None
        doc = {
            "name": self.name,
            "time": time.time(),
            "uptime_s": round(time.time() - self.started, 1),
            **self._extra,
        }
        if self._events:
            doc["events"] = list(self._events)
        self._doc_seq += 1
        return doc, self._doc_seq

    def _write_doc(self, stamped) -> None:
        """Write a snapshot taken under ``_lock`` — OUTSIDE it, so no
        reader/writer of ``_extra``/``_events`` ever stalls behind the
        disk.  ``_io_lock`` orders concurrent writers; the sequence
        stamp drops a write that lost the race to a newer snapshot
        (the file must only ever move forward)."""
        if stamped is None:
            return
        doc, seq = stamped
        with self._io_lock:
            if seq <= self._written_seq:
                return          # a newer snapshot already landed
            self._written_seq = seq
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=repr)
            os.replace(tmp, self.path)
        self._m_flushes.inc()

    def read(self) -> dict:
        with open(self.path) as f:
            return json.load(f)


_HTML = """<!doctype html><meta http-equiv="refresh" content="2">
<title>veles_tpu status</title>
<style>body{font-family:monospace;margin:2em}td{padding:2px 12px}</style>
<h2>veles_tpu — %s</h2>
<p>%s</p>
<table>%s</table>"""

#: the observability endpoints linked from the status page header
#: (docs/observability.md) — every "why is it slow / will it fit"
#: surface one click from the page an operator already has open.
_LINKS = ("/status.json", "/metrics", "/trace.json", "/slo.json",
          "/memory.json")


def _header_links() -> str:
    links = " · ".join(
        f'<a href="{p}">{p.lstrip("/")}</a>' for p in _LINKS)
    last = profiler().last_path
    if last:
        links += (" · last profile: "
                  f"<code>{html.escape(str(last))}</code>")
    return links


class _Handler(http.server.BaseHTTPRequestHandler):
    reporter: Optional[StatusReporter] = None

    def _reply(self, body: bytes, code: int = 200,
               ctype: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200, default=None):
        self._reply(json.dumps(obj, default=default).encode(), code)

    def do_GET(self):
        if self.path.split("?", 1)[0] == "/metrics":
            # Prometheus text exposition of the process registry —
            # the scrape target every latency histogram lands in
            # (docs/observability.md "Metrics & tracing")
            self._reply(
                registry().render().encode(),
                ctype="text/plain; version=0.0.4; charset=utf-8")
            return
        if self.path.split("?", 1)[0] == "/slo.json":
            # rolling-window latency percentiles + SLO burn rates
            # (runtime/slo.py; the read also rotates the ring)
            self._json(slo_tracker().doc())
            return
        if self.path.split("?", 1)[0] == "/memory.json":
            # HBM truth + the aval-derived component ledger
            # (runtime/memory.py)
            self._json(memory_monitor().doc())
            return
        if self.path.split("?", 1)[0] == "/trace.json":
            # Chrome-trace / Perfetto timeline of the span ring
            self._json(span_ring().chrome_trace(), default=repr)
            return
        if self.path.split("?", 1)[0] == "/graph.svg":
            svg = self.reporter.graph_svg if self.reporter else None
            if not svg or not os.path.isfile(svg):
                self.send_response(404)
                self.end_headers()
                return
            with open(svg, "rb") as f:
                body = f.read()
            self.send_response(200)
            self.send_header("Content-Type", "image/svg+xml")
            self.send_header("Cache-Control", "no-store")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/plots/"):
            # serve a PNG from plots_dir; unquote FIRST, then basename-
            # only lookup, so a crafted (or %2F-encoded) path can never
            # escape the directory
            fn = os.path.basename(
                urllib.parse.unquote(self.path.split("?", 1)[0]))
            root = self.reporter.plots_dir if self.reporter else None
            full = os.path.join(root, fn) if root else None
            if not fn.endswith(".png") or not full \
                    or not os.path.isfile(full):
                self.send_response(404)
                self.end_headers()
                return
            with open(full, "rb") as f:
                body = f.read()
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Cache-Control", "no-store")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        try:
            doc = self.reporter.read() if self.reporter else {}
        except (OSError, json.JSONDecodeError):
            doc = {}
        if self.path.startswith("/status"):
            body = json.dumps(doc).encode()
            ctype = "application/json"
        else:
            def flat(d, prefix=""):
                # nested gauge groups (e.g. the decode engine's) render
                # as dotted rows instead of one opaque repr cell
                for k, v in sorted(d.items()):
                    key = f"{prefix}{k}"
                    if isinstance(v, dict):
                        yield from flat(v, key + ".")
                    else:
                        yield key, v

            # html.escape EVERY interpolated key/value: a metric value
            # whose repr carries < or & (an error string, a path, a
            # label) must render as text, never as markup
            rows = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(str(v))}</td></tr>"
                for k, v in flat(doc))
            plots = self.reporter.plot_files() if self.reporter else []
            # mtime cache-buster: the 2s meta refresh re-requests each
            # image only as it actually changes.  Filenames are URL-
            # quoted for the path and HTML-escaped for the attribute —
            # a quote or angle bracket in a plot name must not break
            # out of the src attribute
            imgs = "".join(
                '<p><img src="/plots/'
                f'{html.escape(urllib.parse.quote(fn))}?t={int(mt)}" '
                'style="max-width:95%"></p>' for fn, mt in plots)
            graph = ""
            if self.reporter and self.reporter.graph_svg \
                    and os.path.isfile(self.reporter.graph_svg):
                graph = ('<h3>workflow graph</h3>'
                         '<p><img src="/graph.svg" '
                         'style="max-width:95%"></p>')
            body = (_HTML % (html.escape(str(doc.get("name", "?"))),
                             _header_links(), rows)
                    + graph + imgs).encode()
            ctype = "text/html"
        self._reply(body, ctype=ctype)

    def do_POST(self):
        if self.path.split("?", 1)[0] != "/debug/profile":
            self.send_error(404)
            return
        # shared handler (runtime/profiler.py): ingress cap, capture,
        # 409/400/500 mapping — one implementation for both servers
        code, obj = serve_profile_post(self.headers, self.rfile)
        self._json(obj, code=code)

    def log_message(self, *args):  # silence request logging
        pass


class StatusServer(Logger):
    """Background HTTP server for the status file."""

    def __init__(self, reporter: StatusReporter, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("BoundHandler", (_Handler,), {"reporter": reporter})
        self.httpd = http.server.ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("status server on http://127.0.0.1:%d", self.port)
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
