"""On-demand device profiler capture: ``POST /debug/profile``.

Until now the only way to get a device-level trace out of a serving
process was to restart it with ``--profile DIR`` — which destroys the
very state (warm caches, live load, the slow request pattern) being
debugged.  This module wraps ``jax.profiler`` start/stop in a
duration-bounded, single-flight capture an operator can trigger over
HTTP against the RUNNING process (reference parity: the L10 per-unit
profiler was likewise a runtime toggle, ``--profile-units`` /
veles/units.py:805-817, not a relaunch).

Contract (docs/observability.md "On-demand profiler capture"):

* one capture at a time — a second ``POST`` while one runs answers
  **409** with the active capture's path (the profiler is process-
  global state; two concurrent ``start_trace`` calls would corrupt
  both traces);
* duration is bounded by ``root.common.observe.profile_max_s`` — a
  typo'd ``{"duration_s": 9999}`` must not profile the service into
  the ground;
* captures land under ``root.common.observe.profile_dir`` (default
  ``<cache_dir>/profiles``) in a per-capture timestamped directory,
  returned in the response and linked from the status page —
  TensorBoard/xprof-loadable.

Host-side only: the capture thread blocks in ``time.sleep``, never in
traced scope (VT103).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional, Tuple

from ..config import root
from ..logger import Logger
from .metrics import ScopedCounter, registry

_CAPTURE_IDS = itertools.count(1)


class ProfilerBusy(RuntimeError):
    """A capture is already running (the HTTP 409 path)."""

    def __init__(self, path: str):
        super().__init__(
            f"a profiler capture is already running (writing {path}); "
            "retry when it finishes")
        self.path = path


class ProfilerCapture(Logger):
    """Single-flight ``jax.profiler`` capture driver (one per process
    behind :func:`profiler`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active_path: Optional[str] = None  # guarded-by: self._lock
        self._last_path: Optional[str] = None    # guarded-by: self._lock
        # per-instance view over the shared registry series (the
        # engine's counter idiom): stats() and /metrics can never drift
        self._captures = ScopedCounter(registry().counter(
            "vt_profile_captures_total",
            "completed on-demand profiler captures "
            "(POST /debug/profile)"))  # guarded-by: self._lock

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active_path is not None

    @property
    def last_path(self) -> Optional[str]:
        """Directory of the most recent finished capture (the status
        page links it)."""
        with self._lock:
            return self._last_path

    def _capture_dir(self, out_dir: Optional[str]) -> str:
        base = out_dir or str(
            root.common.observe.get("profile_dir", "") or "")
        if not base:
            base = os.path.join(str(root.common.cache_dir), "profiles")
        stamp = time.strftime("%Y%m%d-%H%M%S")
        return os.path.join(
            base, f"{stamp}-{os.getpid()}-{next(_CAPTURE_IDS):03d}")

    def capture(self, duration_s: float = 1.0,
                out_dir: Optional[str] = None) -> dict:
        """Run one duration-bounded device trace; blocks for the
        duration and returns ``{path, duration_s, files}``.  Raises
        :class:`ProfilerBusy` when a capture is already in flight."""
        cap = float(root.common.observe.get("profile_max_s", 30.0))
        dur = min(max(float(duration_s), 0.01), max(cap, 0.01))
        path = self._capture_dir(out_dir)
        with self._lock:
            if self._active_path is not None:
                raise ProfilerBusy(self._active_path)
            self._active_path = path
        try:
            os.makedirs(path, exist_ok=True)
            import jax
            self.info("profiler capture -> %s (%.2fs)", path, dur)
            jax.profiler.start_trace(path)
            try:
                time.sleep(dur)
            finally:
                jax.profiler.stop_trace()
            n_files = sum(len(fs) for _b, _d, fs in os.walk(path))
            with self._lock:
                self._last_path = path
                self._captures.inc()
            return {"path": path, "duration_s": dur, "files": n_files}
        finally:
            with self._lock:
                self._active_path = None

    def stats(self) -> dict:
        with self._lock:
            return {"active": self._active_path is not None,
                    "captures": self._captures.n,
                    "last_path": self._last_path}


def serve_profile_post(headers, rfile) -> Tuple[int, dict]:
    """The ONE HTTP half of ``POST /debug/profile`` both servers route
    to (StatusServer and RestfulServer must never drift on the ingress
    cap or the error mapping): body-size 413 before any read, negative
    Content-Length clamped (``rfile.read(-1)`` would block the handler
    thread until the client hangs up), JSON parse, capture, and the
    409/400/500 mapping.  Returns ``(status_code, json_body)``."""
    try:
        n = max(int(headers.get("Content-Length", 0) or 0), 0)
        cap = int(float(root.common.serve.get("max_body_mb", 64))
                  * 2 ** 20)
        if n > cap:
            # refuse BEFORE reading an unbounded body into memory
            return 413, {"error": f"request body {n} bytes exceeds "
                                  f"the {cap} byte cap "
                                  "(root.common.serve.max_body_mb)"}
        req = json.loads(rfile.read(n)) if n else {}
        # no client-chosen output path: captures stay confined to
        # root.common.observe.profile_dir
        return 200, profiler().capture(
            duration_s=float(req.get("duration_s", 1.0)))
    except ProfilerBusy as e:
        return 409, {"error": str(e), "active": e.path}
    except (TypeError, ValueError, json.JSONDecodeError) as e:
        return 400, {"error": str(e)}
    except Exception as e:  # noqa: BLE001 — capture failures answer
        return 500, {"error": f"{type(e).__name__}: {e}"}


_PROFILER_LOCK = threading.Lock()
_PROFILER: Optional[ProfilerCapture] = None  # guarded-by: _PROFILER_LOCK


def profiler() -> ProfilerCapture:
    """THE process capture driver (what ``POST /debug/profile`` runs)."""
    global _PROFILER
    with _PROFILER_LOCK:
        if _PROFILER is None:
            _PROFILER = ProfilerCapture()
        return _PROFILER
