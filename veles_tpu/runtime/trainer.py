"""Trainer: the host-side epoch loop tying loader + workflow + decision +
snapshotter together.

This replaces the reference's gate-driven Repeater loop (reference:
veles/plumbing.py:17 Repeater; Decision closing gates; EndPoint firing
``on_workflow_finished``, veles/workflow.py:351-377). All data-dependent
control flow (epochs, early stop, rollback, checkpoint cadence) lives here
on the host; everything per-step is the compiled train/eval functions.

Metric aggregation matches the reference Decision semantics: per-epoch sums
of n_err / mse over served (non-padded) samples → error % / RMSE.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import prng
from ..config import root
from ..loader.base import TRAIN, VALID, TEST, Loader
from ..logger import Logger, TraceContext
from ..ops.optimizers import (ANOM_CONSEC_KEY, LR_MULT_KEY, Optimizer,
                              reserved_opt_neutral)
from ..units.workflow import Workflow
from .benchmark import epoch_goodput, resolve_peak_tflops
from .decision import Decision
from .memory import memory_monitor, tree_bytes
from .metrics import registry, span_ring
from .snapshotter import (Snapshotter, _to_numpy, restore_with_walkback)
from .step_cache import StepCache, enable_persistent_cache


def aggregate_epoch_metrics(sums: Dict[str, float]) -> Dict[str, float]:
    n = max(sums.get("n_samples", 0.0), 1.0)
    out = dict(sums)
    if "n_err" in sums:
        out["error_pct"] = 100.0 * sums["n_err"] / n
    if "mse_sum" in sums:
        out["rmse"] = float(np.sqrt(sums["mse_sum"] / n))
    # per-batch means exclude sentinel-skipped steps (their metrics were
    # zeroed in-graph): dividing by the raw batch count would bias the
    # epoch loss low on any epoch with anomalies
    trained = max(sums.get("n_batches", 0.0)
                  - sums.get("anomaly_steps", 0.0), 1.0)
    if "loss" in sums and "n_batches" in sums:
        out["loss"] = sums["loss"] / trained
    if "grad_norm" in sums and "n_batches" in sums:
        out["grad_norm"] = sums["grad_norm"] / trained
    return out


class Trainer(Logger):
    """Standalone (or per-host SPMD) training driver."""

    def __init__(self, workflow: Workflow, loader: Loader,
                 optimizer: Optimizer, decision: Optional[Decision] = None,
                 snapshotter: Optional[Snapshotter] = None, *,
                 mesh=None, rule=None, recorder=None, status=None,
                 prefetch: int = 2, pipeline_microbatches=None,
                 pipeline_interleave: int = 1,
                 step_cache: Optional[StepCache] = None):
        self.workflow = workflow
        self.loader = loader
        self.optimizer = optimizer
        self.decision = decision or Decision(max_epochs=10)
        self.snapshotter = snapshotter
        self.mesh = mesh          # jax.sharding.Mesh for SPMD training
        self.rule = rule          # parameter sharding rule (parallel.mesh)
        self.recorder = recorder  # plotting.MetricsRecorder (optional)
        self.status = status      # runtime.status.StatusReporter (optional)
        self.prefetch = prefetch  # batch prefetch depth (0 = synchronous)
        # When set and the mesh has a pipe axis > 1, training runs on the
        # fused 1F1B schedule (Workflow.make_pipeline_train_step) instead
        # of AD-through-GPipe; eval keeps the forward GPipe path.
        self.pipeline_microbatches = pipeline_microbatches
        # v>1: the interleaved (virtual-stage) 1F1B schedule —
        # the stack needs v*pipe uniform stages
        self.pipeline_interleave = int(pipeline_interleave)
        # AOT step-compilation cache: each program compiles once per
        # workflow lifetime; rollbacks/restores are cache hits (the lr
        # drop rides opt_state as a traced scalar, see ops.optimizers).
        self.step_cache = step_cache if step_cache is not None \
            else StepCache()
        self._batch_sh = None
        self._state_sh = None
        self._batch_spec = None
        self.wstate = None
        self._train_cost = {"flops": 0.0, "bytes_accessed": 0.0}
        self._last_mfu = 0.0    # THIS trainer's last epoch (the gauge
        #                         is process-global; two trainers in
        #                         one process must not read each other)
        self._train_step = None
        self._eval_step = None
        self._eval_entry = None
        self._best_wstate = None
        self.results: Dict[str, Any] = {}
        # fault-tolerance gauges (docs/robustness.md): fed to
        # StatusReporter every epoch and into results/bench output
        self.anomaly_steps_skipped = 0
        self.anomaly_rollbacks = 0
        self.snapshot_walkbacks = 0
        # per-step phase breakdown (docs/observability.md "Metrics &
        # tracing"): where a training second actually goes — blocked on
        # the loader, moving the batch H2D, dispatching the step, or
        # writing a snapshot.  Host-side wall times only; the step
        # phase is dispatch + any implicit sync the NEXT phase forces,
        # never a device sync of its own.
        reg = registry()
        self._m_phase = reg.histogram(
            "vt_train_phase_seconds",
            "per-step wall time by phase: data_wait | h2d | step | "
            "snapshot", labels=("phase",))
        self._m_anom = reg.counter(
            "vt_train_anomaly_skips_total",
            "train steps skipped by the in-graph anomaly sentinel")
        self._g_epoch = reg.gauge(
            "vt_train_epoch", "current training epoch")
        # goodput (docs/observability.md "Goodput & MFU"): the train
        # program's cost analysis over the epoch wall, against the
        # measured peak (runtime/benchmark.py GEMM calibration or the
        # root.common.observe.peak_tflops override)
        self._g_flops_sec = reg.gauge(
            "vt_train_flops_per_sec",
            "achieved training flops/s over the last train-epoch wall "
            "(loader data waits included; eval and snapshot phases are "
            "outside it — vt_train_phase_seconds shows where they go)")
        self._g_mfu = reg.gauge(
            "vt_train_mfu",
            "model FLOPs utilization of the last train epoch against "
            "the measured peak (0 = peak unknown)")

    # -- setup -------------------------------------------------------------
    def initialize(self, seed: Optional[int] = None,
                   wstate: Optional[dict] = None) -> None:
        self.loader.initialize()
        batch = next(self.loader.iter_epoch(
            TRAIN if self.loader.class_lengths[TRAIN] else VALID))
        specs = {k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype
                                         if not hasattr(v, "dtype")
                                         else v.dtype)
                 for k, v in batch.items()}
        self.workflow.build(specs)
        if wstate is not None:
            self.wstate = wstate
        else:
            key = prng.get("init").next_key() if seed is None \
                else jax.random.key(seed)
            self.wstate = self.workflow.init_state(key, self.optimizer)
        from ..parallel.distributed import host_count, is_multihost
        if (is_multihost() and self.snapshotter is not None
                and self.snapshotter.time_interval > 0):
            raise ValueError(
                "time_interval snapshot throttling is wall-clock and can "
                "diverge across hosts (the payload gather is a collective "
                "every host must join); use epoch-interval throttling on "
                "multi-host runs")
        if self.mesh is not None and is_multihost():
            # Each host serves a local shard; the compiled step sees the
            # GLOBAL batch (host shards stitched on the data axis by
            # to_global_batch in the epoch loop).
            specs = {k: jax.ShapeDtypeStruct(
                (s.shape[0] * host_count(),) + tuple(s.shape[1:]), s.dtype)
                for k, s in specs.items()}
        self._batch_spec = specs
        # Persistent XLA compilation cache (no-op unless
        # root.common.compile_cache / --compile-cache points somewhere):
        # must be active BEFORE the first compile to be of any use.
        enable_persistent_cache()
        self._compile_steps()
        if self._state_sh is not None:
            self.wstate = self._place_state(self.wstate)
        # aval-derived memory ledger (runtime/memory.py, /memory.json):
        # what this trainer pinned, in exact bytes — the fit check the
        # ZeRO-sharding and quantization ROADMAP items start from
        import weakref

        from .memory import drop_stamped_components
        mem = memory_monitor()
        stamps = {
            name: mem.set_component(name, nbytes) for name, nbytes in (
                ("train.params",
                 tree_bytes(self.wstate.get("params", {}))),
                ("train.opt_state",
                 tree_bytes(self.wstate.get("opt_state", {}))),
                ("train.prefetch_staging",
                 max(self.prefetch, 0) * tree_bytes(self._batch_spec)),
            )}
        # stamped drop on GC: a freed trainer's bytes leave /memory.json
        # unless a newer registrant took the names over
        self._mem_finalizer = weakref.finalize(
            self, drop_stamped_components, stamps)
        mem.ensure_poller()
        self.info("workflow %s: %d params", self.workflow.name,
                  self.workflow.n_params(self.wstate))

    def _compile_steps(self) -> None:
        """Build (or fetch from the StepCache) the AOT-compiled train/eval
        steps, preserving mesh shardings.  Compiled exactly ONCE per
        workflow lifetime: a Decision rollback or ``restore`` with
        ``lr_multiplier != 1`` is a pure cache hit — the lr drop is a
        traced opt_state scalar, not a new Python closure."""
        state_struct = self.workflow.state_struct(self.wstate)
        key = self.step_cache.trainer_key(
            self.workflow, self.optimizer, self.wstate, self._batch_spec,
            mesh=self.mesh, rule=self.rule,
            pipeline=(self.pipeline_microbatches,
                      self.pipeline_interleave))
        pin = (self.workflow, self.rule, self.optimizer)
        args = (state_struct, dict(self._batch_spec))
        if self.mesh is not None:
            fused_pp = (self.pipeline_microbatches is not None
                        and self.mesh.shape.get("pipe", 1) > 1)
            if self.pipeline_interleave > 1 and not fused_pp:
                raise ValueError(
                    "pipeline_interleave needs the fused 1F1B schedule: "
                    "set pipeline_microbatches and give the mesh a "
                    "'pipe' axis > 1 (otherwise the v*S-stage stack "
                    "would silently train sequentially)")
            if fused_pp:
                # Ragged tail batches are fine since round 5: the fused
                # step weights each microbatch's loss by its mask count
                # and normalizes by the batch total, landing exactly on
                # the AD path's global masked mean
                # (pipeline_compile.build_pipeline_step).
                def build_train():
                    return self.workflow.make_pipeline_train_step(
                        self.optimizer, self.mesh, self.wstate,
                        self._batch_spec, rule=self.rule,
                        n_microbatches=self.pipeline_microbatches,
                        interleave=self.pipeline_interleave)
            else:
                def build_train():
                    return self.workflow.make_sharded_train_step(
                        self.optimizer, self.mesh, self.wstate,
                        self._batch_spec, rule=self.rule)

            def build_eval():
                return self.workflow.make_sharded_eval_step(
                    self.mesh, self.wstate, self._batch_spec,
                    rule=self.rule)
        else:
            def build_train():
                return (self.workflow.make_train_step(self.optimizer),
                        None, None)

            def build_eval():
                return self.workflow.make_eval_step(), None, None

        self._train_step, self._state_sh, self._batch_sh = \
            self.step_cache.get_step("train", key, build_train, args,
                                     pin=pin)
        # the cost of THIS trainer's live train program — never the
        # kind-sum, which double-counts superseded entries after an
        # optimizer rebuild (the cache keeps them by design)
        self._train_cost = self.step_cache.entry_cost("train", key)
        # The eval program compiles LAZILY on the first eval epoch — a
        # train-only run (no VALID/TEST data, bench loops) never pays
        # for a program it does not execute.
        self._eval_step = None
        self._eval_entry = (key, build_eval, args, pin)

    def _ensure_eval_step(self):
        if self._eval_step is None:
            key, build_eval, args, pin = self._eval_entry
            self._eval_step, _, _ = \
                self.step_cache.get_step("eval", key, build_eval, args,
                                         pin=pin)
        return self._eval_step

    # -- epoch passes -------------------------------------------------------
    def _batches(self, klass: int, epoch):
        """DEVICE-PLACED batch stream with background prefetch: host-side
        minibatch assembly (gather/decode/normalize) AND the H2D transfer
        (``_place_batch``: ``jax.device_put`` under the batch shardings,
        multihost ``to_global_batch`` included) run in the worker thread,
        overlapping the previous step's compute — the double-buffered
        host→device feed of SURVEY.md §7.7 (the reference got overlap
        accidentally from its thread-pool unit graph).  The queue depth
        (``prefetch``) bounds the number of batches resident in HBM, so
        the default of 2 is a classic device-side double buffer.  The
        ``prefetch=0`` synchronous fallback places batches inline with
        identical semantics."""
        it = self.loader.iter_epoch(klass, epoch)
        if self.prefetch <= 0:
            for item in it:
                yield self._place_batch(item)
            return
        import queue
        import threading
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        _end = object()
        stop = threading.Event()

        def guarded_put(item) -> bool:
            # Bounded put that gives up when the consumer is gone —
            # otherwise an abandoned epoch (step raised, early stop) leaves
            # the worker blocked forever and, for streaming loaders,
            # silently draining samples nobody will see.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in it:
                    # H2D inside the worker: device_put is async and
                    # thread-safe, so the transfer of batch N+1 rides
                    # under step N's compute instead of serializing in
                    # the consumer loop.
                    if not guarded_put(self._place_batch(item)):
                        return
                guarded_put(_end)
            except BaseException as e:  # re-raised on the consumer side
                guarded_put(e)

        threading.Thread(target=worker, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is _end:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def _place_state(self, wstate):
        """Place the (host-identical) state under the mesh shardings; on
        multi-host the shardings span non-addressable devices, which
        device_put refuses."""
        from ..parallel.distributed import is_multihost, place_global_state
        if is_multihost():
            return place_global_state(wstate, self._state_sh)
        return jax.device_put(wstate, self._state_sh)

    def _place_batch(self, batch):
        """H2D placement under the compiled step's batch shardings.
        Called from the prefetch worker thread (see ``_batches``); the
        single/multi-host branching lives in distributed.place_batch."""
        if self._batch_sh is None:
            return batch
        from ..parallel.distributed import place_batch
        t0 = time.monotonic()
        placed = place_batch(batch, self.mesh, self._batch_sh)
        # dispatch wall of the H2D transfer (device_put is async; the
        # actual copy overlaps the previous step by design — this
        # phase going fat means the transfer no longer hides)
        self._m_phase.labels(phase="h2d").observe(time.monotonic() - t0)
        return placed

    def _run_epoch_train(self, epoch: int) -> Dict[str, float]:
        sums: Dict[str, Any] = {}
        phase = self._m_phase
        with TraceContext("train_epoch", epoch=epoch):
            # _batches yields batches already device-placed (H2D runs in
            # the prefetch worker, overlapped with the previous step);
            # data_wait is the time THIS thread blocked on the feed —
            # near zero while prefetch keeps up, the loader's share of
            # the step when it does not
            it = iter(self._batches(TRAIN, epoch))
            while True:
                t0 = time.monotonic()
                batch = next(it, None)
                if batch is None:
                    # exhausted next() is generator teardown, not batch
                    # wait — recording it would skew the distribution
                    # and leave data_wait one count ahead of step
                    break
                phase.labels(phase="data_wait").observe(
                    time.monotonic() - t0)
                t0 = time.monotonic()
                self.wstate, mets = self._train_step(self.wstate, batch)
                # Accumulate ON DEVICE — a float() here would sync the
                # pipeline every step (the reference's --sync-run behavior,
                # veles/accelerated_units.py:186-193, as an accident).
                for k, v in mets.items():
                    sums[k] = sums[k] + v if k in sums else v
                sums["n_batches"] = sums.get("n_batches", 0) + 1
                phase.labels(phase="step").observe(
                    time.monotonic() - t0)
        return aggregate_epoch_metrics(
            {k: float(v) for k, v in sums.items()})

    def _run_epoch_eval(self, klass: int, epoch: int) -> Dict[str, float]:
        if self.loader.class_lengths[klass] == 0:
            return {}
        self._ensure_eval_step()
        sums: Dict[str, float] = {}
        with TraceContext("eval_epoch", epoch=epoch, klass=klass):
            for batch in self._batches(klass, epoch):
                mets = self._eval_step(self.wstate, batch)
                for k, v in mets.items():
                    sums[k] = sums[k] + v if k in sums else v
                sums["n_batches"] = sums.get("n_batches", 0) + 1
        return aggregate_epoch_metrics(
            {k: float(v) for k, v in sums.items()})

    # -- main loop ----------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        if self.wstate is None:
            self.initialize()
        t0 = time.time()
        samples_done = 0
        epoch = self.loader.epoch_number
        while not self.decision.complete:
            t_ep = time.time()
            mono_ep = time.monotonic()
            self._g_epoch.set(epoch)
            train_mets = self._run_epoch_train(epoch)
            t_train = time.time()
            samples_done += int(train_mets.get("n_samples", 0))
            # epoch goodput: the compiled step's cost analysis times the
            # steps run, over the epoch wall — and MFU against the
            # measured peak (runtime/benchmark.py).  Host arithmetic
            # only; the compiled programs are untouched.
            goodput = epoch_goodput(
                self._train_cost["flops"],
                train_mets.get("n_batches", 0.0),
                max(t_train - t_ep, 1e-9))
            self._g_flops_sec.set(goodput["flops_per_sec"])
            self._g_mfu.set(goodput["mfu"])
            self._last_mfu = goodput["mfu"]
            # anomaly accounting + (possibly) rollback escalation BEFORE
            # eval, so a rolled-back epoch validates the restored weights
            self._check_anomalies(epoch, train_mets)
            valid_mets = self._run_epoch_eval(VALID, epoch)
            if root.common.timings:
                # reference: per-unit/root.common.timings wall prints
                # (veles/units.py:144-149); per-unit attribution needs the
                # instrumented Workflow.profile_units mode.
                self.info(
                    "epoch %d timings: train %.3fs (%.0f samples/s), "
                    "eval %.3fs", epoch, t_train - t_ep,
                    train_mets.get("n_samples", 0.0)
                    / max(t_train - t_ep, 1e-9),
                    time.time() - t_train)
            stop = self.decision.on_epoch(epoch, train_mets, valid_mets)
            if self.recorder is not None:
                self.recorder.record(
                    epoch,
                    **{f"train_{k}": v for k, v in train_mets.items()},
                    **{f"valid_{k}": v for k, v in valid_mets.items()})
            if self.status is not None:
                self.status.update(
                    epoch=epoch, best_value=self.decision.best_value,
                    best_epoch=self.decision.best_epoch,
                    train_mfu=round(goodput["mfu"], 4),
                    train_flops_per_sec=round(
                        goodput["flops_per_sec"], 1),
                    anomaly_steps_skipped=self.anomaly_steps_skipped,
                    anomaly_rollbacks=self.anomaly_rollbacks,
                    snapshot_walkbacks=self.snapshot_walkbacks,
                    **{f"valid_{k}": v for k, v in valid_mets.items()})

            if (self.decision.improved
                    and (self.decision.rollback_after is not None
                         or self._anomaly_patience() > 0)):
                # Host-side copy: train_step donates wstate buffers, so an
                # on-device alias would reference deleted arrays by the time
                # a rollback happens. (All hosts reach this branch — the
                # decision is identical everywhere — so the collective
                # gather inside _host_state_copy is safe.)
                self._best_wstate = self._host_state_copy()
            if self.decision.want_rollback and self._best_wstate is not None:
                # Reference: rollback to best snapshot + lr drop
                # (manualrst_veles_algorithms.rst:164). The cumulative
                # multiplier is written into the restored state's traced
                # opt_state scalar — the compiled steps are untouched
                # (ZERO recompiles; the restore re-places onto the mesh).
                self.wstate = Snapshotter.restore_wstate(
                    {"wstate": self._best_wstate}, like=self.wstate,
                    shardings=self._state_sh)
                self.wstate = self._apply_lr_multiplier(self.wstate)

            # Advance the loader first so a restored checkpoint resumes at
            # the *next* epoch instead of repeating the completed one.
            self.loader.next_epoch()
            if (self.snapshotter is not None
                    and self.snapshotter.tick(best=self.decision.improved)):
                # tick() is deterministic across hosts, so throttled
                # epochs skip the payload entirely (no wasted device→host
                # copy). On a snapshot epoch the payload is built on EVERY
                # host — gathering sharded state is a collective — but
                # only host 0 writes (reference: slaves never snapshot,
                # veles/snapshotter.py:160). Multi-host runs must give
                # every host a snapshotter with the same interval;
                # wall-clock time_interval throttling can diverge across
                # hosts and is rejected at initialize().
                t_snap = time.monotonic()
                payload = self._payload()
                if jax.process_index() == 0:
                    self.snapshotter.save(f"ep{epoch}", payload,
                                          best=self.decision.improved)
                self._m_phase.labels(phase="snapshot").observe(
                    time.monotonic() - t_snap)
            # one span per epoch in the shared ring: training epochs
            # land on the same /trace.json timeline serving requests do
            span_ring().add(
                "train_epoch", mono_ep, time.monotonic() - mono_ep,
                cat="train", tid=0,
                args={"epoch": epoch,
                      **{k: round(v, 6) for k, v in train_mets.items()
                         if isinstance(v, float)}})
            epoch = self.loader.epoch_number
            if stop:
                break

        elapsed = time.time() - t0
        test_mets = self._run_epoch_eval(TEST, epoch)
        flops_per_step = self._train_cost["flops"]
        self.results = self.workflow.gather_results({
            "best_value": self.decision.best_value,
            "best_epoch": self.decision.best_epoch,
            "epochs": epoch,
            "elapsed_s": elapsed,
            "train_samples_per_s": samples_done / max(elapsed, 1e-9),
            "train_step_flops": flops_per_step,
            # unrounded: a CPU-tier MFU is ~1e-7 and must not round to
            # a fake zero (display rounding belongs to the status page)
            "train_mfu": self._last_mfu,
            "peak_tflops": resolve_peak_tflops(),
            "anomaly_steps_skipped": self.anomaly_steps_skipped,
            "anomaly_rollbacks": self.anomaly_rollbacks,
            "snapshot_walkbacks": self.snapshot_walkbacks,
            **{f"test_{k}": v for k, v in test_mets.items()},
        })
        return self.results

    # -- anomaly sentinel escalation ----------------------------------------
    def _anomaly_patience(self) -> int:
        return int(root.common.train.get("anomaly_patience", 0) or 0)

    def _check_anomalies(self, epoch: int, train_mets: Dict[str, float]
                         ) -> None:
        """Epoch-granularity half of the sentinel: accumulate the skip
        count the in-graph guard already summed on device, and when the
        traced consecutive-anomaly counter crosses
        ``root.common.train.anomaly_patience``, escalate to the Decision
        rollback ladder — restore the best/last-snapshot weights and
        scale the traced lr multiplier down.  One small device_get per
        epoch; the per-step path never syncs."""
        skipped = int(train_mets.get("anomaly_steps", 0))
        if skipped:
            self.anomaly_steps_skipped += skipped
            self._m_anom.inc(skipped)
            self.warning("epoch %d: %d anomalous step(s) skipped "
                         "(non-finite loss/grad norm)", epoch, skipped)
        patience = self._anomaly_patience()
        if patience <= 0:
            return
        opt_state = (self.wstate or {}).get("opt_state")
        if not isinstance(opt_state, dict) \
                or ANOM_CONSEC_KEY not in opt_state:
            return
        consec = int(jax.device_get(opt_state[ANOM_CONSEC_KEY]))
        if consec >= patience:
            self._escalate_anomaly(epoch, consec)

    def _escalate_anomaly(self, epoch: int, consec: int) -> None:
        """The escalation rung above per-step skipping (reference:
        "rollback to best snapshot on failure + lr change",
        manualrst_veles_algorithms.rst:164 item 11): skipping alone can't
        cure a persistently diverging run, so restore known-good weights
        and train gentler.  Pure state writes — the compiled step
        programs are untouched (ZERO recompiles, tests/test_faults.py)."""
        self.anomaly_rollbacks += 1
        dec = self.decision
        dec.lr_multiplier *= dec.rollback_lr_scale
        source = None
        if self._best_wstate is not None:
            self.wstate = Snapshotter.restore_wstate(
                {"wstate": self._best_wstate}, like=self.wstate,
                shardings=self._state_sh)
            source = "in-memory best state"
        elif self.snapshotter is not None \
                and self.snapshotter.last_path is not None:
            payload, used, skipped = restore_with_walkback(
                self.snapshotter.last_path)
            self._note_walkback(skipped)
            self._adapt_reserved_opt_keys(payload)
            self.wstate = Snapshotter.restore_wstate(
                payload, like=self.wstate, shardings=self._state_sh)
            source = used
        else:
            self.warning("anomaly escalation has no snapshot or best "
                         "state to roll back to; keeping current params")
        self.wstate = self._apply_lr_multiplier(self.wstate)
        self.wstate = self._write_opt_scalars(
            self.wstate, {ANOM_CONSEC_KEY: np.zeros((), np.int32)})
        self.error(
            "anomaly escalation at epoch %d: %d consecutive anomalous "
            "steps >= patience %d — restored %s, lr multiplier now %g",
            epoch, consec, self._anomaly_patience(),
            source or "nothing", dec.lr_multiplier)
        if self.status is not None:
            self.status.record_event(
                "anomaly_rollback", epoch=epoch, consecutive=consec,
                lr_multiplier=dec.lr_multiplier,
                restored=source or "none")

    def _note_walkback(self, skipped) -> None:
        if not skipped:
            return
        self.snapshot_walkbacks += len(skipped)
        for s in skipped:
            self.warning("snapshot walk-back: skipped %s (%s)",
                         s["path"], s["reason"])
        if self.status is not None:
            self.status.record_event(
                "snapshot_walkback", skipped=[s["path"] for s in skipped])

    # -- traced lr multiplier ----------------------------------------------
    def _apply_lr_multiplier(self, wstate):
        """Write ``decision.lr_multiplier`` into the traced opt_state
        scalar the compiled step multiplies onto its base schedule —
        the recompile-free replacement for swapping in a scaled Python
        schedule closure and re-tracing both step programs."""
        mult = float(getattr(self.decision, "lr_multiplier", 1.0))
        opt_state = wstate.get("opt_state")
        if not isinstance(opt_state, dict) or LR_MULT_KEY not in opt_state:
            if mult != 1.0:
                self.warning(
                    "optimizer state carries no %s slot; lr multiplier "
                    "%g NOT applied (optimizer-less workflow?)",
                    LR_MULT_KEY, mult)
            return wstate
        return self._write_opt_scalars(
            wstate, {LR_MULT_KEY: np.asarray(mult, np.float32)})

    def _write_opt_scalars(self, wstate, values: Dict[str, Any]):
        """Host-side write of reserved opt_state scalars (the traced lr
        multiplier and anomaly counters) under the live shardings —
        the recompile-free state-mutation primitive all the rollback
        paths share.  Keys absent from the state are skipped."""
        opt_state = (wstate or {}).get("opt_state")
        if not isinstance(opt_state, dict):
            return wstate
        placed = {}
        for k, v in values.items():
            if k not in opt_state:
                continue
            leaf = jnp.asarray(v)
            if self._state_sh is not None:
                sh = self._state_sh["opt_state"][k]
                from ..parallel.distributed import (is_multihost,
                                                    place_global_state)
                leaf = place_global_state(leaf, sh) if is_multihost() \
                    else jax.device_put(leaf, sh)
            placed[k] = leaf
        if not placed:
            return wstate
        return {**wstate, "opt_state": {**opt_state, **placed}}

    def effective_lr(self, step: int = 0) -> float:
        """The learning rate the compiled step applies at ``step``: the
        base schedule × the traced rollback multiplier riding opt_state
        (``optimizer.schedule`` itself is never mutated anymore)."""
        lr = float(self.optimizer.schedule(step))
        opt_state = (self.wstate or {}).get("opt_state")
        if isinstance(opt_state, dict) and LR_MULT_KEY in opt_state:
            lr *= float(jax.device_get(opt_state[LR_MULT_KEY]))
        return lr

    def _host_state_copy(self):
        """Numpy copy of wstate; all-gathers non-addressable (multi-host
        rule-sharded) leaves — collective, call on every host."""
        from ..parallel.distributed import gather_to_host, is_multihost
        if is_multihost():
            return gather_to_host(self.wstate)
        return _to_numpy(self.wstate)

    def _payload(self) -> Dict[str, Any]:
        return {
            "wstate": self._host_state_copy(),
            "loader": self.loader.state(),
            "decision": self.decision.state(),
            "prng": prng.streams.state(),
            "config": root.to_dict(),
            "workflow_checksum": self.workflow.checksum(),
        }

    def _adapt_reserved_opt_keys(self, payload: Dict[str, Any]) -> None:
        """Bridge snapshot ↔ live reserved opt_state scalars: snapshots
        predating the traced multiplier / anomaly counters get neutral
        slots injected so the structural tree-map succeeds, and slots
        the live state doesn't carry (sentinel disabled, optimizer-less
        workflow) are dropped from the restored tree."""
        saved = payload.get("wstate")
        live_os = (self.wstate or {}).get("opt_state")
        if not (isinstance(saved, dict) and isinstance(live_os, dict)
                and isinstance(saved.get("opt_state"), dict)):
            return
        saved_os = saved["opt_state"]
        for k, neutral in reserved_opt_neutral().items():
            if k in live_os and k not in saved_os:
                saved_os[k] = neutral
            elif k in saved_os and k not in live_os:
                del saved_os[k]

    def restore(self, path: str, *, force: bool = False) -> None:
        """Resume from a snapshot manifest (reference CLI restore path,
        veles/__main__.py:539-589). Checksum mismatch is fatal unless
        ``force`` (the reference validated the workflow checksum in its
        distributed handshake, veles/server.py:478-492).

        Filesystem snapshots verify the manifest's tensors sha256 and,
        when the named snapshot is corrupt (truncated write, bit rot),
        WALK BACK through the retained snapshots to the newest valid one
        — logging every snapshot skipped and counting it in the
        ``snapshot_walkbacks`` gauge (docs/robustness.md)."""
        payload, used, skipped = restore_with_walkback(path)
        self._note_walkback(skipped)
        if skipped:
            self.warning("restoring %s instead of corrupt %s", used, path)
        if self.wstate is None:
            self.initialize()
        if payload.get("workflow_checksum") != self.workflow.checksum():
            msg = ("snapshot was taken from a different workflow "
                   f"(checksum {payload.get('workflow_checksum')!r} != "
                   f"{self.workflow.checksum()!r})")
            if not force:
                raise ValueError(msg + "; pass force=True to override")
            self.warning("%s — forcing restore", msg)
        self._adapt_reserved_opt_keys(payload)
        self.wstate = Snapshotter.restore_wstate(payload, like=self.wstate,
                                                 shardings=self._state_sh)
        self.loader.set_state(payload["loader"])
        self.decision.set_state(payload["decision"])
        prng.streams.set_state(payload["prng"])
        # Re-apply accumulated rollback lr drops as the traced opt_state
        # scalar (else a resumed run trains at the original, too-high lr).
        # The compiled steps are untouched: restore is recompile-free.
        self.wstate = self._apply_lr_multiplier(self.wstate)
