"""``--frontend``: a browser form that composes a command line.

Reference parity: veles/__main__.py:258-332 — ``veles --frontend`` served a
web form (Tornado + the ``web/`` frontend bundle), waited for the user to
submit, and then ran with the composed command line.

TPU rebuild: the form is generated straight from the argparse parser
(every option becomes a field, choices become selects, store_true become
checkboxes) and served by stdlib http.server on localhost; the POST handler
converts fields back into an argv list and hands it to ``main`` — no
Tornado, no static bundle, same workflow.

Cross-origin hardening (advisor r1): a ``.py`` config path in the form is
*executed*, so a drive-by cross-origin POST from any web page must not be
able to start a run.  The served form embeds a per-process random token;
POSTs without it are rejected (a foreign origin cannot read the form to
learn the token), and the Host header must be local."""

from __future__ import annotations

import html
import secrets
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import List, Optional

from .logger import Logger

_STYLE = """
body { font-family: sans-serif; margin: 2em auto; max-width: 44em; }
label { display: block; margin-top: .8em; font-weight: bold; }
input[type=text] { width: 100%; } .help { color: #666; font-size: .85em; }
button { margin-top: 1.2em; padding: .5em 2em; }
"""


def render_form(parser, token: str = "") -> str:
    """HTML form generated from the argparse parser's actions."""
    rows = []
    if token:
        rows.append(f'<input type="hidden" name="_token" '
                    f'value="{html.escape(token)}">')
    for action in parser._actions:
        if action.dest in ("help", "frontend"):
            continue
        name = html.escape(action.dest)
        helptext = html.escape(action.help or "")
        if not action.option_strings:  # positional
            field = (f'<input type="text" name="{name}" '
                     f'placeholder="{name}">')
        elif action.const is True:  # store_true
            field = f'<input type="checkbox" name="{name}" value="1">'
        elif action.choices:
            opts = "".join(
                f'<option value="{html.escape(str(c))}">'
                f'{html.escape(str(c))}</option>' for c in action.choices)
            field = (f'<select name="{name}">'
                     f'<option value=""></option>{opts}</select>')
        else:
            field = f'<input type="text" name="{name}">'
        rows.append(f'<label>{name}</label>{field}'
                    f'<div class="help">{helptext}</div>')
    return (f"<html><head><title>veles_tpu frontend</title>"
            f"<style>{_STYLE}</style></head><body>"
            f"<h2>veles_tpu — compose a run</h2>"
            f'<form method="POST">{"".join(rows)}'
            f'<button type="submit">Run</button></form></body></html>')


def form_to_argv(parser, fields: dict) -> List[str]:
    """Inverse of render_form: POSTed fields -> argv list."""
    argv: List[str] = []
    positionals: List[str] = []
    for action in parser._actions:
        if action.dest in ("help", "frontend"):
            continue  # submitting the form must not relaunch the frontend
        raw = fields.get(action.dest, [""])[0].strip()
        if not raw:
            continue
        if not action.option_strings:
            if action.nargs in ("*", "+"):
                # list positionals (overrides) arrive space-separated
                positionals.extend(raw.split())
            else:
                positionals.append(raw)  # paths may contain spaces
        elif action.const is True:
            argv.append(action.option_strings[-1])
        else:
            argv.extend([action.option_strings[-1], raw])
    return positionals + argv


class Frontend(Logger):
    """Serve the form once; ``wait()`` returns the composed argv."""

    def __init__(self, parser, port: int = 8080, host: str = "127.0.0.1"):
        self.parser = parser
        self.argv: Optional[List[str]] = None
        self.token = secrets.token_urlsafe(24)
        self._done = threading.Event()
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def _host_ok(self):
                # The Host check only defends loopback binds against DNS
                # rebinding; an explicit non-loopback bind is reachable
                # under names we cannot enumerate — there the token is
                # the sole (and sufficient) launch guard.
                if host not in ("127.0.0.1", "localhost", "::1"):
                    return True
                raw = (self.headers.get("Host") or "").strip()
                if raw.startswith("["):  # bracketed IPv6, maybe with port
                    req_host = raw[1:].split("]", 1)[0]
                else:
                    req_host = raw.split(":")[0]
                return req_host in ("127.0.0.1", "localhost", "::1", host)

            def _reject(self, code, msg):
                body = msg.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if not self._host_ok():
                    return self._reject(403, "bad Host header")
                body = render_form(frontend.parser,
                                   frontend.token).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    fields = urllib.parse.parse_qs(
                        self.rfile.read(length).decode())
                except (ValueError, UnicodeDecodeError):
                    return self._reject(400, "malformed body")
                if not self._host_ok():
                    return self._reject(403, "bad Host header")
                sent = fields.pop("_token", [""])[0]
                # compare bytes: compare_digest raises TypeError on
                # non-ASCII str input (a malformed POST must get the same
                # clean 403 as every other rejection)
                if not secrets.compare_digest(
                        sent.encode("utf-8", "surrogatepass"),
                        frontend.token.encode("utf-8")):
                    return self._reject(403, "missing/invalid form token")
                frontend.argv = form_to_argv(frontend.parser, fields)
                body = (b"<html><body><h3>Launched.</h3><pre>" +
                        html.escape(" ".join(frontend.argv)).encode() +
                        b"</pre></body></html>")
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                frontend._done.set()

            def log_message(self, *args):
                pass

        self._server = HTTPServer((host, port), Handler)
        self._server.timeout = 0.2  # lets _serve poll _done; close() can
        self.port = self._server.server_address[1]  # then join cleanly
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self.info("frontend at http://%s:%d/ — submit the form to run",
                  host, self.port)

    def _serve(self):
        while not self._done.is_set():
            self._server.handle_request()

    def wait(self, timeout: Optional[float] = None) -> Optional[List[str]]:
        """Block until the form is submitted; returns the argv."""
        if not self._done.wait(timeout):
            return None
        return self.argv

    def close(self):
        self._done.set()
        self._thread.join(2.0)  # serve loop exits on its 0.2s poll tick
        self._server.server_close()
