"""Expert parallelism: mixture-of-experts layer sharded over a mesh axis.

NOT in the reference (SURVEY.md §2.5 item 4) — new TPU-native design. The
expert FFN bank is a batched gemm with a leading expert axis sharded over
``expert``.

Two dispatch formulations, same routing semantics (GShard slot priority:
every token's slot-0 route queues before any slot-1 route; capacity
overflow drops the weakest routes):

* ``"sort"`` (default, round 3): route queue positions come from a
  stable argsort by expert id; tokens scatter into their (expert, slot)
  rows and combine gathers them back.  Peak memory is
  O(T·K + E·C·D + T·K·D) — no tensor couples T with C, so it scales to
  real token counts (the round-2 one-hot formulation's (T, K, E, C)
  slot tensor is O(T²·K/E) at fixed capacity_factor and dominated HBM).
* ``"dense"`` (round 2): one-hot einsum dispatch — kept because its
  dispatch/combine einsums are what GSPMD lowers to all_to_all over ICI
  when the expert axis is sharded, and as the cross-check reference.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int,
                    dtype=jnp.float32) -> dict:
    kw1, kw2, kr = jax.random.split(key, 3)
    scale1 = 1.0 / jnp.sqrt(d_model)
    scale2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.uniform(kr, (d_model, n_experts), dtype,
                                     -scale1, scale1),
        "w1": jax.random.uniform(kw1, (n_experts, d_model, d_hidden),
                                 dtype, -scale1, scale1),
        "w2": jax.random.uniform(kw2, (n_experts, d_hidden, d_model),
                                 dtype, -scale2, scale2),
    }


def _route_positions(topi: jnp.ndarray, E: int) -> jnp.ndarray:
    """Queue position of each (token, slot) route within its expert.

    Routes are ordered slot-major (all slot-0 routes before any slot-1
    route — GShard priority); the position equals the count of earlier
    same-expert routes, exactly what the dense formulation's masked
    cumsum computed, at O(T·K·log) sort cost and O(T·K) memory instead
    of an O(T·K·E) cumsum tensor."""
    T, K = topi.shape
    flat_e = topi.T.reshape(-1)                     # slot-major (K*T,)
    perm = jnp.argsort(flat_e, stable=True)         # groups by expert,
    seg = flat_e[perm]                              # priority-stable
    starts = jnp.searchsorted(seg, jnp.arange(E), side="left")
    pos_sorted = jnp.arange(T * K, dtype=jnp.int32) \
        - starts[seg].astype(jnp.int32)
    pos_flat = jnp.zeros(T * K, jnp.int32).at[perm].set(pos_sorted)
    return pos_flat.reshape(K, T).T                 # (T, K)


def moe_apply(params: dict, x: jnp.ndarray, *,
              capacity_factor: float = 1.25, top_k: int = 1,
              dispatch_mode: str = "sort"
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k MoE FFN (round 2: k >= 1 with renormalized combine weights;
    round 1 was top-1 only).

    x: (tokens, d_model) -> (tokens, d_model), plus the load-balancing
    auxiliary loss (Switch-style: E * sum_e f_e * p_e over the primary
    assignment).  Slot priority is GShard-style: all tokens' first choices
    queue before any second choice, so capacity overflow drops the weakest
    routes first.  Tokens over capacity are dropped (0 contribution for
    that route).  ``dispatch_mode``: "sort" (scalable scatter/gather,
    default) or "dense" (one-hot einsums) — identical outputs (tests
    assert it); see the module docstring for the trade.
    """
    T, D = x.shape
    E = params["router"].shape[1]
    K = int(top_k)
    C = max(1, int(capacity_factor * T * K / E))

    logits = x @ params["router"]                    # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, K)             # (T, K)
    if K == 1:
        # Switch semantics: scale by the raw top-1 probability — the path
        # that carries router gradients (renormalizing would make it 1.0
        # and cut the router out of the backward graph)
        gates = topv
    else:
        gates = topv / jnp.maximum(
            jnp.sum(topv, axis=-1, keepdims=True), 1e-9)

    if dispatch_mode == "sort":
        pos = _route_positions(topi, E)              # (T, K)
        keep = pos < C
        # dropped routes target the out-of-bounds row E*C; scatter mode
        # 'drop' discards them. Slot rows are unique (positions are a
        # per-expert enumeration), so 'add' never accumulates two tokens.
        slot_idx = jnp.where(keep, topi * C + pos, E * C)
        xk = jnp.broadcast_to(x[:, None, :], (T, K, D)).reshape(T * K, D)
        xe = jnp.zeros((E * C, D), x.dtype) \
            .at[slot_idx.reshape(-1)].add(xk, mode="drop") \
            .reshape(E, C, D)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, params["w1"],
                                   preferred_element_type=jnp.float32))
        ye = jnp.einsum("ech,ehd->ecd", h.astype(x.dtype), params["w2"])
        yk = ye.reshape(E * C, D)[
            jnp.clip(slot_idx, 0, E * C - 1).reshape(-1)] \
            .reshape(T, K, D)
        w = (gates * keep.astype(gates.dtype)).astype(x.dtype)
        y = jnp.einsum("tk,tkd->td", w, yk)
    elif dispatch_mode == "dense":
        onehots = jax.nn.one_hot(topi, E, dtype=x.dtype)  # (T, K, E)
        # queue positions, slot-major (GShard priority).  The cumsum runs
        # in f32 regardless of activation dtype — a bf16 cumsum loses
        # integer exactness past 256 and collides capacity slots.
        oh_flat = onehots.transpose(1, 0, 2).reshape(K * T, E) \
            .astype(jnp.float32)
        pos_flat = jnp.cumsum(oh_flat, axis=0) * oh_flat - 1.0
        pos = pos_flat.reshape(K, T, E).transpose(1, 0, 2)    # (T, K, E)
        keep = (pos >= 0) & (pos < C)
        slot = jax.nn.one_hot(
            jnp.clip(pos, 0, C - 1).astype(jnp.int32), C,
            dtype=x.dtype) * keep.astype(x.dtype)[..., None]  # (T,K,E,C)
        # combine carries the gate weights; dispatch is its 0/1 support
        combine = jnp.einsum("tk,tkec->tec", gates.astype(x.dtype), slot)
        dispatch = (combine > 0).astype(x.dtype)

        # dispatch -> (E, C, D): with expert axis sharded, GSPMD lowers
        # this to an all_to_all over ICI
        xe = jnp.einsum("tec,td->ecd", dispatch, x)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, params["w1"],
                                   preferred_element_type=jnp.float32))
        ye = jnp.einsum("ech,ehd->ecd", h.astype(x.dtype), params["w2"])
        y = jnp.einsum("tec,ecd->td", combine, ye)
    else:
        raise ValueError(f"unknown dispatch_mode {dispatch_mode!r}")

    # Switch load-balance loss on the primary assignment (bincount form:
    # no (T, E) one-hot materialization)
    frac_tokens = jnp.zeros(E, jnp.float32) \
        .at[topi[:, 0]].add(1.0) / T
    frac_probs = jnp.mean(probs.astype(jnp.float32), axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return y, aux


def moe_shardings(params: dict, mesh: Mesh, axis: str = "expert") -> dict:
    """Shard the expert banks on the expert axis; router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(axis)),
        "w2": NamedSharding(mesh, P(axis)),
    }
