"""Expert parallelism: mixture-of-experts layer sharded over a mesh axis.

NOT in the reference (SURVEY.md §2.5 item 4) — new TPU-native design. The
expert FFN bank is a batched gemm with a leading expert axis sharded over
``expert``.

Two dispatch formulations, same routing semantics (GShard slot priority:
every token's slot-0 route queues before any slot-1 route; capacity
overflow drops the weakest routes):

* ``"sort"`` (default, round 3): route queue positions come from a
  stable argsort by expert id; tokens scatter into their (expert, slot)
  rows and combine gathers them back.  Peak memory is
  O(T·K + E·C·D + T·K·D) — no tensor couples T with C, so it scales to
  real token counts (the round-2 one-hot formulation's (T, K, E, C)
  slot tensor is O(T²·K/E) at fixed capacity_factor and dominated HBM).
* ``"dense"`` (round 2): one-hot einsum dispatch — kept because its
  dispatch/combine einsums are what GSPMD lowers to all_to_all over ICI
  when the expert axis is sharded, and as the cross-check reference.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int,
                    dtype=jnp.float32) -> dict:
    kw1, kw2, kr = jax.random.split(key, 3)
    scale1 = 1.0 / jnp.sqrt(d_model)
    scale2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.uniform(kr, (d_model, n_experts), dtype,
                                     -scale1, scale1),
        "w1": jax.random.uniform(kw1, (n_experts, d_model, d_hidden),
                                 dtype, -scale1, scale1),
        "w2": jax.random.uniform(kw2, (n_experts, d_hidden, d_model),
                                 dtype, -scale2, scale2),
    }


def _route_positions(topi: jnp.ndarray, E: int) -> jnp.ndarray:
    """Queue position of each (token, slot) route within its expert.

    Routes are ordered slot-major (all slot-0 routes before any slot-1
    route — GShard priority); the position equals the count of earlier
    same-expert routes, exactly what the dense formulation's masked
    cumsum computed, at O(T·K·log) sort cost and O(T·K) memory instead
    of an O(T·K·E) cumsum tensor."""
    T, K = topi.shape
    flat_e = topi.T.reshape(-1)                     # slot-major (K*T,)
    perm = jnp.argsort(flat_e, stable=True)         # groups by expert,
    seg = flat_e[perm]                              # priority-stable
    starts = jnp.searchsorted(seg, jnp.arange(E), side="left")
    pos_sorted = jnp.arange(T * K, dtype=jnp.int32) \
        - starts[seg].astype(jnp.int32)
    pos_flat = jnp.zeros(T * K, jnp.int32).at[perm].set(pos_sorted)
    return pos_flat.reshape(K, T).T                 # (T, K)


# -- shared building blocks of the "sort" formulation -----------------------
# moe_apply's local path and moe_apply_manual's expert-parallel path are
# contractually identical in routing, combine weights, and aux statistics
# (the fused-1F1B exactness tests depend on it) — so the steps live ONCE.

def _route(params: dict, x: jnp.ndarray, top_k: int):
    """Router logits -> (gates, topi, probs); Switch keeps the raw top-1
    probability (renormalizing would cut the router out of backward)."""
    logits = x @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, top_k)
    if top_k == 1:
        gates = topv
    else:
        gates = topv / jnp.maximum(
            jnp.sum(topv, axis=-1, keepdims=True), 1e-9)
    return gates, topi, probs


def _pack_slots(x: jnp.ndarray, topi: jnp.ndarray, E: int, C: int):
    """Scatter tokens into their (expert, slot) rows -> (slot_idx, keep,
    xe (E, C, D)); dropped routes target the out-of-bounds row E*C."""
    T, D = x.shape
    K = topi.shape[1]
    pos = _route_positions(topi, E)
    keep = pos < C
    slot_idx = jnp.where(keep, topi * C + pos, E * C)
    xk = jnp.broadcast_to(x[:, None, :], (T, K, D)).reshape(T * K, D)
    xe = jnp.zeros((E * C, D), x.dtype) \
        .at[slot_idx.reshape(-1)].add(xk, mode="drop") \
        .reshape(E, C, D)
    return slot_idx, keep, xe


def _expert_ffn(xe: jnp.ndarray, w1: jnp.ndarray, w2: jnp.ndarray,
                out_dtype) -> jnp.ndarray:
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, w1,
                               preferred_element_type=jnp.float32))
    return jnp.einsum("ech,ehd->ecd", h.astype(out_dtype), w2)


def _combine_slots(ye: jnp.ndarray, slot_idx: jnp.ndarray,
                   keep: jnp.ndarray, gates: jnp.ndarray,
                   x_dtype) -> jnp.ndarray:
    E_C, D = ye.shape[0] * ye.shape[1], ye.shape[2]
    T, K = slot_idx.shape
    yk = ye.reshape(E_C, D)[
        jnp.clip(slot_idx, 0, E_C - 1).reshape(-1)].reshape(T, K, D)
    w = (gates * keep.astype(gates.dtype)).astype(x_dtype)
    return jnp.einsum("tk,tkd->td", w, yk)


def _switch_aux(topi: jnp.ndarray, probs: jnp.ndarray,
                axis_name: Optional[str] = None) -> jnp.ndarray:
    """Switch load-balance loss on the primary assignment (bincount form:
    no (T, E) one-hot materialization).

    With ``axis_name`` (the expert-parallel shard_map path) the token
    statistics are psum'd over that axis first, so ``frac_tokens`` /
    ``frac_probs`` are fractions of the dispatch group's FULL token batch
    and the aux matches ``moe_apply``'s global-batch formulation exactly
    — rank-local fractions averaged after the fact are NOT the same
    number (E·Σ mean_r(f_r)·mean_r(p_r) ≠ mean_r(E·Σ f_r·p_r))."""
    T, E = probs.shape
    counts = jnp.zeros(E, jnp.float32).at[topi[:, 0]].add(1.0)
    prob_sums = jnp.sum(probs.astype(jnp.float32), axis=0)
    n_tokens = jnp.asarray(T, jnp.float32)
    if axis_name is not None:
        counts = jax.lax.psum(counts, axis_name)
        prob_sums = jax.lax.psum(prob_sums, axis_name)
        n_tokens = n_tokens * jax.lax.psum(1, axis_name)
    frac_tokens = counts / n_tokens
    frac_probs = prob_sums / n_tokens
    return E * jnp.sum(frac_tokens * frac_probs)


def moe_apply(params: dict, x: jnp.ndarray, *,
              capacity_factor: float = 1.25, top_k: int = 1,
              dispatch_mode: str = "sort"
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k MoE FFN (round 2: k >= 1 with renormalized combine weights;
    round 1 was top-1 only).

    x: (tokens, d_model) -> (tokens, d_model), plus the load-balancing
    auxiliary loss (Switch-style: E * sum_e f_e * p_e over the primary
    assignment).  Slot priority is GShard-style: all tokens' first choices
    queue before any second choice, so capacity overflow drops the weakest
    routes first.  Tokens over capacity are dropped (0 contribution for
    that route).  ``dispatch_mode``: "sort" (scalable scatter/gather,
    default) or "dense" (one-hot einsums) — identical outputs (tests
    assert it); see the module docstring for the trade.
    """
    T, D = x.shape
    E = params["router"].shape[1]
    K = int(top_k)
    C = max(1, int(capacity_factor * T * K / E))

    gates, topi, probs = _route(params, x, K)

    if dispatch_mode == "sort":
        slot_idx, keep, xe = _pack_slots(x, topi, E, C)
        ye = _expert_ffn(xe, params["w1"], params["w2"], x.dtype)
        y = _combine_slots(ye, slot_idx, keep, gates, x.dtype)
    elif dispatch_mode == "dense":
        onehots = jax.nn.one_hot(topi, E, dtype=x.dtype)  # (T, K, E)
        # queue positions, slot-major (GShard priority).  The cumsum runs
        # in f32 regardless of activation dtype — a bf16 cumsum loses
        # integer exactness past 256 and collides capacity slots.
        oh_flat = onehots.transpose(1, 0, 2).reshape(K * T, E) \
            .astype(jnp.float32)
        pos_flat = jnp.cumsum(oh_flat, axis=0) * oh_flat - 1.0
        pos = pos_flat.reshape(K, T, E).transpose(1, 0, 2)    # (T, K, E)
        keep = (pos >= 0) & (pos < C)
        slot = jax.nn.one_hot(
            jnp.clip(pos, 0, C - 1).astype(jnp.int32), C,
            dtype=x.dtype) * keep.astype(x.dtype)[..., None]  # (T,K,E,C)
        # combine carries the gate weights; dispatch is its 0/1 support
        combine = jnp.einsum("tk,tkec->tec", gates.astype(x.dtype), slot)
        dispatch = (combine > 0).astype(x.dtype)

        # dispatch -> (E, C, D): with expert axis sharded, GSPMD lowers
        # this to an all_to_all over ICI
        xe = jnp.einsum("tec,td->ecd", dispatch, x)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, params["w1"],
                                   preferred_element_type=jnp.float32))
        ye = jnp.einsum("ech,ehd->ecd", h.astype(x.dtype), params["w2"])
        y = jnp.einsum("tec,ecd->td", combine, ye)
    else:
        raise ValueError(f"unknown dispatch_mode {dispatch_mode!r}")

    aux = _switch_aux(topi, probs)
    return y, aux


def moe_apply_manual(params: dict, x: jnp.ndarray, *, axis_name: str,
                     capacity_factor: float = 1.25, top_k: int = 1
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE for code ALREADY inside a ``shard_map`` (a
    pipeline-schedule body, Context.manual_axes): ``x`` is this rank's
    token shard, the expert partition lives on mesh axis ``axis_name``,
    and dispatch/combine are explicit ``all_to_all`` over that axis —
    the hand-written form of what GSPMD lowers the sharded einsums to
    (round-4 verdict #3: expert-parallel MoE inside fused-1F1B stages).

    Every rank routes its own tokens with the (replicated) router, packs
    them into per-expert capacity slots exactly like ``moe_apply``'s
    "sort" mode, then exchanges slots so each rank computes ONLY its
    E/n experts — on slots from all ranks — with its slice of the
    (replicated) expert bank, and a second all_to_all carries results
    home.  Parameter gradients compose with a psum over ``axis_name``:
    each rank's grad is nonzero only in its expert slice (the slice is
    a dynamic_slice of the replicated bank), so the sum reassembles the
    full bank gradient exactly once per expert.

    Semantics vs the non-distributed ``moe_apply``: identical routing
    and combine weights; capacity is enforced PER SOURCE RANK (C =
    cf·T_local·K/E slots per expert per rank) rather than globally —
    the standard expert-parallel behavior.  With capacity ample enough
    that nothing drops the outputs are exact to the global formulation;
    the load-balance aux loss psums ``frac_tokens``/``frac_probs`` over
    ``axis_name`` so it equals ``moe_apply``'s global-batch formulation
    on the dispatch group's full token set (every rank returns the same
    value — reductions that average it across ranks keep it exact).

    Registered in ``analysis/registry.py`` ``SHARD_MAP_ROOTS`` with
    axis environment ``("expert",)``: the raw ``all_to_all``/``psum``/
    ``axis_index`` here (and in :func:`_switch_aux`, which joins the
    scope through the module-local closure) are legal exactly because
    callers are already inside a schedule shard_map — veles-tpu-lint
    VS502 enforces it.
    """
    T = x.shape[0]
    E = params["router"].shape[1]
    n = jax.lax.psum(1, axis_name)           # static inside shard_map
    if E % n:
        raise ValueError(
            f"n_experts={E} must divide over the {axis_name!r} axis ({n})")
    El = E // n
    rank = jax.lax.axis_index(axis_name)
    K = int(top_k)
    C = max(1, int(capacity_factor * T * K / E))

    gates, topi, probs = _route(params, x, K)
    slot_idx, keep, xe = _pack_slots(x, topi, E, C)
    # exchange: expert-major split — rank r receives every rank's slots
    # for ITS El experts, concatenated source-major on the slot axis
    xr = jax.lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=1,
                            tiled=True)       # (El, n*C, D)
    w1 = jax.lax.dynamic_slice_in_dim(params["w1"], rank * El, El, 0)
    w2 = jax.lax.dynamic_slice_in_dim(params["w2"], rank * El, El, 0)
    yr = _expert_ffn(xr, w1, w2, x.dtype)
    # inverse exchange: slot chunks go back to their source ranks
    ye = jax.lax.all_to_all(yr, axis_name, split_axis=1, concat_axis=0,
                            tiled=True)       # (E, C, D)
    y = _combine_slots(ye, slot_idx, keep, gates, x.dtype)
    aux = _switch_aux(topi, probs, axis_name=axis_name)
    return y, aux


def moe_shardings(params: dict, mesh: Mesh, axis: str = "expert") -> dict:
    """Shard the expert banks on the expert axis; router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(axis)),
        "w2": NamedSharding(mesh, P(axis)),
    }
