"""Expert parallelism: mixture-of-experts layer sharded over a mesh axis.

NOT in the reference (SURVEY.md §2.5 item 4) — new TPU-native design. The
expert FFN bank is a batched gemm with a leading expert axis sharded over
``expert``; top-1 routing with capacity dispatches tokens via one-hot
einsums (dense dispatch — the XLA-friendly formulation; GSPMD turns the
dispatch/combine einsums into all_to_all when the expert axis is sharded).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int,
                    dtype=jnp.float32) -> dict:
    kw1, kw2, kr = jax.random.split(key, 3)
    scale1 = 1.0 / jnp.sqrt(d_model)
    scale2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.uniform(kr, (d_model, n_experts), dtype,
                                     -scale1, scale1),
        "w1": jax.random.uniform(kw1, (n_experts, d_model, d_hidden),
                                 dtype, -scale1, scale1),
        "w2": jax.random.uniform(kw2, (n_experts, d_hidden, d_model),
                                 dtype, -scale2, scale2),
    }


def moe_apply(params: dict, x: jnp.ndarray, *,
              capacity_factor: float = 1.25
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 MoE FFN.

    x: (tokens, d_model) -> (tokens, d_model), plus the load-balancing
    auxiliary loss (Switch-style: E * sum_e f_e * p_e).
    Tokens over capacity are dropped (output 0 for the FFN path) — standard
    Switch semantics.
    """
    T, D = x.shape
    E = params["router"].shape[1]
    C = max(1, int(capacity_factor * T / E))

    logits = x @ params["router"]                    # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)              # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)           # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0             # (T, E)
    keep = (pos >= 0) & (pos < C)
    dispatch = onehot[..., None] * jax.nn.one_hot(
        jnp.clip(pos, 0, C - 1).astype(jnp.int32), C,
        dtype=x.dtype)                                          # (T, E, C)
    dispatch = dispatch * keep.astype(x.dtype)[..., None]

    # dispatch -> (E, C, D): with expert axis sharded, GSPMD lowers this
    # to an all_to_all over ICI
    xe = jnp.einsum("tec,td->ecd", dispatch, x)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, params["w1"],
                               preferred_element_type=jnp.float32))
    ye = jnp.einsum("ech,ehd->ecd", h.astype(x.dtype), params["w2"])
    y = jnp.einsum("tec,ecd->td", dispatch, ye)
    y = y * gate[:, None]

    # Switch load-balance loss
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return y, aux


def moe_shardings(params: dict, mesh: Mesh, axis: str = "expert") -> dict:
    """Shard the expert banks on the expert axis; router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(axis)),
        "w2": NamedSharding(mesh, P(axis)),
    }
