"""Expert parallelism: mixture-of-experts layer sharded over a mesh axis.

NOT in the reference (SURVEY.md §2.5 item 4) — new TPU-native design. The
expert FFN bank is a batched gemm with a leading expert axis sharded over
``expert``; top-1 routing with capacity dispatches tokens via one-hot
einsums (dense dispatch — the XLA-friendly formulation; GSPMD turns the
dispatch/combine einsums into all_to_all when the expert axis is sharded).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int,
                    dtype=jnp.float32) -> dict:
    kw1, kw2, kr = jax.random.split(key, 3)
    scale1 = 1.0 / jnp.sqrt(d_model)
    scale2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.uniform(kr, (d_model, n_experts), dtype,
                                     -scale1, scale1),
        "w1": jax.random.uniform(kw1, (n_experts, d_model, d_hidden),
                                 dtype, -scale1, scale1),
        "w2": jax.random.uniform(kw2, (n_experts, d_hidden, d_model),
                                 dtype, -scale2, scale2),
    }


def moe_apply(params: dict, x: jnp.ndarray, *,
              capacity_factor: float = 1.25, top_k: int = 1
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k MoE FFN (round 2: k >= 1 with renormalized combine weights;
    round 1 was top-1 only).

    x: (tokens, d_model) -> (tokens, d_model), plus the load-balancing
    auxiliary loss (Switch-style: E * sum_e f_e * p_e over the primary
    assignment).  Slot priority is GShard-style: all tokens' first choices
    queue before any second choice, so capacity overflow drops the weakest
    routes first.  Tokens over capacity are dropped (0 contribution for
    that route).
    """
    T, D = x.shape
    E = params["router"].shape[1]
    K = int(top_k)
    C = max(1, int(capacity_factor * T * K / E))

    logits = x @ params["router"]                    # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, K)             # (T, K)
    if K == 1:
        # Switch semantics: scale by the raw top-1 probability — the path
        # that carries router gradients (renormalizing would make it 1.0
        # and cut the router out of the backward graph)
        gates = topv
    else:
        gates = topv / jnp.maximum(
            jnp.sum(topv, axis=-1, keepdims=True), 1e-9)

    onehots = jax.nn.one_hot(topi, E, dtype=x.dtype)  # (T, K, E)
    # queue positions, slot-major: every token's slot-0 route is queued
    # before any slot-1 route (GShard priority).  The cumsum runs in f32
    # regardless of activation dtype — a bf16 cumsum loses integer
    # exactness past 256 and collides capacity slots.
    oh_flat = onehots.transpose(1, 0, 2).reshape(K * T, E) \
        .astype(jnp.float32)
    pos_flat = jnp.cumsum(oh_flat, axis=0) * oh_flat - 1.0
    pos = pos_flat.reshape(K, T, E).transpose(1, 0, 2)          # (T, K, E)
    keep = (pos >= 0) & (pos < C)
    slot = jax.nn.one_hot(
        jnp.clip(pos, 0, C - 1).astype(jnp.int32), C,
        dtype=x.dtype) * keep.astype(x.dtype)[..., None]        # (T,K,E,C)
    # combine carries the gate weights; dispatch is its 0/1 support
    combine = jnp.einsum("tk,tkec->tec", gates.astype(x.dtype), slot)
    dispatch = (combine > 0).astype(x.dtype)

    # dispatch -> (E, C, D): with expert axis sharded, GSPMD lowers this
    # to an all_to_all over ICI
    xe = jnp.einsum("tec,td->ecd", dispatch, x)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, params["w1"],
                               preferred_element_type=jnp.float32))
    ye = jnp.einsum("ech,ehd->ecd", h.astype(x.dtype), params["w2"])
    y = jnp.einsum("tec,ecd->td", combine, ye)

    # Switch load-balance loss on the primary assignment
    frac_tokens = jnp.mean(onehots[:, 0, :], axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return y, aux


def moe_shardings(params: dict, mesh: Mesh, axis: str = "expert") -> dict:
    """Shard the expert banks on the expert axis; router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(axis)),
        "w2": NamedSharding(mesh, P(axis)),
    }
