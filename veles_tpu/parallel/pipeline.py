"""Pipeline parallelism: GPipe-style microbatched execution over a mesh
axis.

NOT in the reference (SURVEY.md §2.5: the reference's only parallel axis was
the batch); required for TPU-scale models. Design: S stages sharded
one-stage-per-device over the ``pipe`` mesh axis, microbatches streamed
with ``jax.lax.ppermute`` rotating activations around the ring under
``shard_map`` — the scan-over-microbatches schedule, compute/transfer
overlap left to XLA.

Round-2 redesign (the round-1 restrictions removed):

* **Sharded input/output.** Round 1 replicated the full microbatch stack to
  every device with only rank 0 reading it.  Now the input is sharded
  ``P(pipe)`` on the microbatch axis (device d owns block d) and delivered
  to stage 0 just-in-time on a one-microbatch "conveyor" that rotates one
  hop per step — per-device input memory drops S×, per-step transfer stays
  one microbatch.  Outputs travel home the same way and come back sharded
  ``P(pipe)``: memory S×, no final psum broadcast.
* **Heterogeneous stages.** ``stage_fn`` may be a list of S different
  callables with per-stage parameter pytrees of different structures; each
  stage's params are raveled (jax.flatten_util), zero-padded to the widest
  stage and stacked (S, P_max) sharded on ``pipe`` — every device holds
  max-stage params, not the sum — and applied under ``lax.switch`` on the
  device's stage index.  Activation shapes must still agree between stages
  (the thing that physically rides the ring).
* **Bubble accounting.** ``bubble_fraction(S, n_mb)`` is the idle share of
  the schedule; ``pipeline_apply`` logs it per call.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..logger import Logger as _Logger


_log = _Logger()


def stack_stage_params(per_stage_params) -> dict:
    """Stack a list of identical-structure stage params along axis 0 (the
    stage axis that shards over 'pipe')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Idle fraction of the fwd schedule: each device does n_mb useful
    stage applications out of n_mb + 2(S-1) steps (fill + drain, plus the
    S-1 output-return tail)."""
    steps = n_microbatches + 2 * (n_stages - 1)
    return 1.0 - n_microbatches / steps


def _pipeline_local(stage_params, x_blk, *, apply_local, axis_name: str,
                    n_microbatches: int, n_stages: int):
    """Per-device body under shard_map.

    stage_params: this device's stage params — every leaf has leading
    stage-axis extent 1 (homogeneous: the P(pipe)-sharded stacked tree;
    heterogeneous: a (1, P_max) raveled vector).
    x_blk: (1, Q, mb...) this device's contiguous block of Q = n_mb/S
    microbatches.  Stage-0 inputs and finished outputs each travel on a
    one-microbatch conveyor rotating one hop per step (see module doc).
    """
    S, Q = n_stages, n_microbatches // n_stages
    idx = jax.lax.axis_index(axis_name)
    p_local = jax.tree.map(lambda a: a[0], stage_params)
    x_local = x_blk[0]                       # (Q, mb...)
    mb_shape = x_local.shape[1:]

    # conveyors rotate DOWN (i -> i-1): inputs converge on device 0;
    # activations rotate UP (i -> i+1): stage d feeds stage d+1; finished
    # outputs also rotate UP, S-1 -> 0 -> ... -> home device.
    down = [(i, (i - 1) % S) for i in range(S)]
    up = [(i, (i + 1) % S) for i in range(S)]

    n_steps = n_microbatches + 2 * (S - 1)

    def body(carry, s):
        held, in_conv, out_conv, out_local = carry

        # -- input conveyor: device c loads mb t = s + c when it owns it
        t_here = s + idx
        own = (t_here >= idx * Q) & (t_here < (idx + 1) * Q) \
            & (t_here < n_microbatches)
        local_i = jnp.clip(t_here - idx * Q, 0, Q - 1)
        in_conv = jnp.where(own, x_local[local_i], in_conv)

        # -- stage compute: device 0 consumes the conveyor head (mb s).
        # checkpoint: the backward (reverse schedule via jax.grad of this
        # scan) rematerializes stage internals instead of stashing them
        # per step — per-device backward memory stays O(steps) carries,
        # the GPipe-with-remat memory profile (1F1B's further O(S) stash
        # reduction would need a manual interleaved bwd schedule; not
        # worth the complexity at this depth).
        cur = jnp.where(idx == 0, in_conv, held)
        out = jax.checkpoint(
            lambda p, c: apply_local(idx, p, c))(p_local, cur)

        # -- output conveyor: last stage writes mb m = s - (S-1)
        m_written = s - (S - 1)
        write = (idx == S - 1) & (m_written >= 0) \
            & (m_written < n_microbatches)
        out_conv = jnp.where(write, out, out_conv)

        # -- harvest: mb m arrives home h = m // Q after (h+1) mod S hops
        m_arr = s - (S - 1) - ((idx + 1) % S)
        harvest = (m_arr >= 0) & (m_arr < n_microbatches) \
            & (m_arr // Q == idx)
        local_o = jnp.clip(m_arr - idx * Q, 0, Q - 1)
        out_local = jnp.where(
            harvest,
            out_local.at[local_o].set(out_conv),
            out_local)

        held = jax.lax.ppermute(out, axis_name, up)
        in_conv = jax.lax.ppermute(in_conv, axis_name, down)
        out_conv = jax.lax.ppermute(out_conv, axis_name, up)
        return (held, in_conv, out_conv, out_local), None

    zeros = jnp.zeros(mb_shape, x_local.dtype)
    out_local0 = jnp.zeros((Q,) + mb_shape, x_local.dtype)
    (_, _, _, out_local), _ = jax.lax.scan(
        body, (zeros, zeros, zeros, out_local0), jnp.arange(n_steps))
    return out_local[None]                   # (1, Q, mb...)


def _ravel_stages(stage_fns: Sequence[Callable], params_list):
    """Heterogeneous-stage path: ravel per-stage params, zero-pad to the
    widest stage, stack (S, P_max), apply via lax.switch on stage index."""
    vecs, unravels, lens = [], [], []
    for p in params_list:
        v, un = ravel_pytree(p)
        vecs.append(v)
        unravels.append(un)
        lens.append(v.shape[0])
    pmax = max(lens)
    stacked = jnp.stack([jnp.pad(v, (0, pmax - v.shape[0])) for v in vecs])
    branches = [
        (lambda vec, x, _fn=fn, _un=un, _l=l:
         _fn(_un(vec[:_l]), x))
        for fn, un, l in zip(stage_fns, unravels, lens)]

    def apply_vec(idx, vec, x):
        return jax.lax.switch(idx, branches, vec, x)

    return stacked, apply_vec


def pipeline_apply(stage_fn: Union[Callable, Sequence[Callable]],
                   params, x, mesh: Mesh, *,
                   axis_name: str = "pipe",
                   n_microbatches: Optional[int] = None,
                   batch_axes: Sequence[str] = ()):
    """Run x through S pipelined stages.

    ``stage_fn(params, x) -> y``: one stage's computation (same activation
    shape in/out).  Homogeneous form: one callable + stage-stacked params
    (leading axis S, see :func:`stack_stage_params`).  Heterogeneous form:
    a list of S callables + a list of S per-stage param pytrees (arbitrary,
    possibly different structures).

    x: (n_microbatches, mb, ...) microbatch stack; ``n_microbatches`` must
    be a multiple of S (it is sharded ``P(axis_name)`` across stages).
    ``batch_axes``: mesh axes the per-microbatch batch dim (axis 1) is
    sharded over (e.g. ("data",)) — without it a dp×pp mesh would
    all-gather the batch and run the FULL batch through every data shard.
    Returns (n_microbatches, mb, ...) outputs, sharded the same way.
    """
    S = mesh.shape[axis_name]
    if callable(stage_fn):
        # homogeneous fast path: use the stacked tree directly — each
        # leaf shards P(pipe) on its stage axis, no ravel round-trip
        n_stages = {a.shape[0] for a in jax.tree.leaves(params)}
        if n_stages != {S}:
            raise ValueError(
                f"stacked params leading axis {sorted(n_stages)} must equal "
                f"the {axis_name!r} mesh axis size {S}")
        stacked = params
        p_specs = jax.tree.map(lambda a: _stage_spec(a, axis_name), params)

        def apply_local(idx, p, x):
            return stage_fn(p, x)
    else:
        stage_fns = list(stage_fn)
        per_stage = list(params)
        if len(stage_fns) != S or len(per_stage) != S:
            raise ValueError(
                f"need {S} stage fns + param sets for the {axis_name!r} "
                f"axis, got {len(stage_fns)}/{len(per_stage)}")
        stacked, apply_local = _ravel_stages(stage_fns, per_stage)
        p_specs = P(axis_name)
    n_mb = x.shape[0]
    if n_microbatches is not None and n_microbatches != n_mb:
        raise ValueError(
            f"n_microbatches={n_microbatches} != x.shape[0]={n_mb}")
    if n_mb % S:
        raise ValueError(
            f"n_microbatches={n_mb} must be a multiple of the pipeline "
            f"depth {S} (inputs/outputs are sharded over {axis_name!r})")

    _log.debug("pipeline: S=%d n_mb=%d bubble=%.1f%%", S, n_mb,
               100 * bubble_fraction(S, n_mb))

    batch_axes = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    bsz = math.prod(mesh.shape[a] for a in batch_axes) if batch_axes else 1
    if batch_axes and x.shape[1] % bsz:
        raise ValueError(
            f"microbatch size {x.shape[1]} not divisible over batch axes "
            f"{batch_axes} (total {bsz})")
    mb_ax = batch_axes or None
    # grouped layout (S, Q, mb, ...): stage blocks on 'pipe', the batch
    # dim on the data axes
    x_spec = P(axis_name, None, mb_ax)
    fn = jax.shard_map(
        functools.partial(_pipeline_local, apply_local=apply_local,
                          axis_name=axis_name, n_microbatches=n_mb,
                          n_stages=S),
        mesh=mesh,
        in_specs=(p_specs, x_spec),
        out_specs=x_spec,
        check_vma=False)
    # group the microbatch axis into (S, Q) so P(axis) places block d on
    # stage d, then flatten back
    grouped = x.reshape((S, n_mb // S) + x.shape[1:])
    out = fn(stacked, grouped)
    return out.reshape((n_mb,) + x.shape[1:])


def _stage_spec(a, axis_name: str) -> P:
    """PartitionSpec splitting the leading stage axis over the pipe axis."""
    return P(axis_name, *([None] * (a.ndim - 1)))


def pipeline_stage_shardings(stacked_params, mesh: Mesh,
                             axis_name: str = "pipe"):
    """NamedShardings placing one stage per device along the pipe axis."""
    return jax.tree.map(
        lambda a: NamedSharding(mesh, _stage_spec(a, axis_name)),
        stacked_params)
