"""Pipeline parallelism: GPipe-style microbatched execution over a mesh
axis.

NOT in the reference (SURVEY.md §2.5: the reference's only parallel axis was
the batch); required for TPU-scale models. Design: S identical stages (a
stack of repeated blocks, params stacked on a leading stage axis and sharded
one-stage-per-device over the ``pipe`` mesh axis), microbatches streamed
with ``jax.lax.ppermute`` rotating activations around the ring under
``shard_map`` — the scan-over-microbatches schedule with (S-1) bubble steps,
compute/transfer overlap left to XLA.

Restriction (round 1): stages must share one params structure (true for the
transformer-block / repeated-MLP models pipeline parallelism exists for);
heterogeneous stages belong to a later round.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage_params) -> dict:
    """Stack a list of identical-structure stage params along axis 0 (the
    stage axis that shards over 'pipe')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _pipeline_local(params, x, *, stage_fn, axis_name: str,
                    n_microbatches: int):
    """Per-device body under shard_map.

    params: this device's stage params (leading stage axis of size 1).
    x: the full (n_microbatches, mb, ...) microbatch stack, replicated on
    every device (in_specs P()). Activations ppermute through the ring with
    device d applying stage d; microbatch m enters at device 0 on step m,
    so only device 0 ever reads x."""
    axis_size = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], params)  # drop stage axis

    n_steps = n_microbatches + axis_size - 1
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    mb_shape = x.shape[1:]

    def body(carry, step):
        held, outputs = carry
        # device 0 injects microbatch `step` (if any remain); others keep
        # what arrived from the previous stage.
        inject = jnp.where(step < n_microbatches,
                           x[jnp.minimum(step, n_microbatches - 1)],
                           jnp.zeros(mb_shape, x.dtype))
        cur = jnp.where(idx == 0, inject, held)
        out = stage_fn(params, cur)
        # the last stage finishes microbatch (step - (S-1)) on this step
        mb_done = step - (axis_size - 1)
        valid = (mb_done >= 0) & (mb_done < n_microbatches)
        outputs = jnp.where(
            valid & (idx == axis_size - 1),
            outputs.at[jnp.clip(mb_done, 0, n_microbatches - 1)].set(out),
            outputs)
        held_next = jax.lax.ppermute(out, axis_name, perm)
        return (held_next, outputs), None

    outputs0 = jnp.zeros((n_microbatches,) + mb_shape, x.dtype)
    held0 = jnp.zeros(mb_shape, x.dtype)
    (_, outputs), _ = jax.lax.scan(body, (held0, outputs0),
                                   jnp.arange(n_steps))
    # outputs live on the last device; broadcast to all so out_specs can be
    # replicated (cheap for activations-sized data; callers that keep going
    # sharded can skip this).
    outputs = jax.lax.psum(
        jnp.where(idx == axis_size - 1, outputs, 0.0), axis_name)
    return outputs


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh, *,
                   axis_name: str = "pipe", n_microbatches: int = None):
    """Run x through S pipelined stages.

    stage_fn(params, x) -> y: one stage's computation (same shape in/out).
    stacked_params: stage-stacked params (leading axis S), sharded on
    ``axis_name``. x: (n_microbatches, mb, ...) microbatch stack.
    Returns (n_microbatches, mb, ...) outputs.
    """
    S = mesh.shape[axis_name]
    n_stages = {a.shape[0] for a in jax.tree.leaves(stacked_params)}
    if n_stages != {S}:
        raise ValueError(
            f"stacked_params leading axis {sorted(n_stages)} must equal the "
            f"{axis_name!r} mesh axis size {S}")
    if n_microbatches is None:
        n_microbatches = x.shape[0]
    elif n_microbatches != x.shape[0]:
        raise ValueError(
            f"n_microbatches={n_microbatches} != x.shape[0]={x.shape[0]}")
    pspec = jax.tree.map(lambda a: _stage_spec(a, axis_name), stacked_params)
    fn = jax.shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name,
                          n_microbatches=n_microbatches),
        mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
        check_vma=False)
    return fn(stacked_params, x)


def _stage_spec(a, axis_name: str) -> P:
    """PartitionSpec splitting the leading stage axis over the pipe axis."""
    return P(axis_name, *([None] * (a.ndim - 1)))


def pipeline_stage_shardings(stacked_params, mesh: Mesh,
                             axis_name: str = "pipe"):
    """NamedShardings placing one stage per device along the pipe axis."""
    return jax.tree.map(
        lambda a: NamedSharding(mesh, _stage_spec(a, axis_name)),
        stacked_params)
