"""Pipeline parallelism: GPipe-style microbatched execution over a mesh
axis.

NOT in the reference (SURVEY.md §2.5: the reference's only parallel axis was
the batch); required for TPU-scale models. Design: S stages sharded
one-stage-per-device over the ``pipe`` mesh axis, microbatches streamed
with ``jax.lax.ppermute`` rotating activations around the ring under
``shard_map`` — the scan-over-microbatches schedule, compute/transfer
overlap left to XLA.

Round-2 redesign (the round-1 restrictions removed):

* **Sharded input/output.** Round 1 replicated the full microbatch stack to
  every device with only rank 0 reading it.  Now the input is sharded
  ``P(pipe)`` on the microbatch axis (device d owns block d) and delivered
  to stage 0 just-in-time on a one-microbatch "conveyor" that rotates one
  hop per step — per-device input memory drops S×, per-step transfer stays
  one microbatch.  Outputs travel home the same way and come back sharded
  ``P(pipe)``: memory S×, no final psum broadcast.
* **Heterogeneous stages.** ``stage_fn`` may be a list of S different
  callables with per-stage parameter pytrees of different structures; each
  stage's params are raveled (jax.flatten_util), zero-padded to the widest
  stage and stacked (S, P_max) sharded on ``pipe`` — every device holds
  max-stage params, not the sum — and applied under ``lax.switch`` on the
  device's stage index.  Activation shapes must still agree between stages
  (the thing that physically rides the ring).
* **Bubble accounting.** ``bubble_fraction(S, n_mb)`` is the idle share of
  the schedule; ``pipeline_apply`` logs it per call.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..logger import Logger as _Logger
from .mesh import shard_map


_log = _Logger()


def stack_stage_params(per_stage_params) -> dict:
    """Stack a list of identical-structure stage params along axis 0 (the
    stage axis that shards over 'pipe')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Idle fraction of the fwd schedule: each device does n_mb useful
    stage applications out of n_mb + 2(S-1) steps (fill + drain, plus the
    S-1 output-return tail)."""
    steps = n_microbatches + 2 * (n_stages - 1)
    return 1.0 - n_microbatches / steps


def _pipeline_local(stage_params, x_blk, *args, apply_local,
                    axis_name: str, n_microbatches: int, n_stages: int,
                    keyed: bool = False, batch_axes=()):
    """Per-device body under shard_map.

    (Like ``_1f1b_local`` and ``_interleaved_local``, registered in
    ``analysis/registry.py`` ``SHARD_MAP_ROOTS`` — the schedule bodies
    are where the analyzer permits raw ``ppermute``/``psum``, with the
    pipe/batch/width axes as the declared environment.)

    stage_params: this device's stage params — every leaf has leading
    stage-axis extent 1 (homogeneous: the P(pipe)-sharded stacked tree;
    heterogeneous: a (1, P_max) raveled vector).
    x_blk: (1, Q, mb...) this device's contiguous block of Q = n_mb/S
    microbatches.  Stage-0 inputs and finished outputs each travel on a
    one-microbatch conveyor rotating one hop per step (see module doc).

    ``keyed``: stage fns take ``(p, x, key)`` and return ``(y, aux)``
    where ``key`` is ``fold_in(rng, microbatch_index)`` — the SAME
    derivation the fused 1F1B schedule uses, so a stochastic unit draws
    identical randomness under both schedules, and per-microbatch aux
    losses (MoE load balance) accumulate into the second output (mean
    over microbatches, replicated).
    """
    rng = args[0] if keyed else None
    S, Q = n_stages, n_microbatches // n_stages
    idx = jax.lax.axis_index(axis_name)
    p_local = jax.tree.map(lambda a: a[0], stage_params)
    x_local = x_blk[0]                       # (Q, mb...)
    mb_shape = x_local.shape[1:]

    # conveyors rotate DOWN (i -> i-1): inputs converge on device 0;
    # activations rotate UP (i -> i+1): stage d feeds stage d+1; finished
    # outputs also rotate UP, S-1 -> 0 -> ... -> home device.
    down = [(i, (i - 1) % S) for i in range(S)]
    up = [(i, (i + 1) % S) for i in range(S)]

    n_steps = n_microbatches + 2 * (S - 1)

    def body(carry, s):
        held, in_conv, out_conv, out_local, aux_acc = carry

        # -- input conveyor: device c loads mb t = s + c when it owns it
        t_here = s + idx
        own = (t_here >= idx * Q) & (t_here < (idx + 1) * Q) \
            & (t_here < n_microbatches)
        local_i = jnp.clip(t_here - idx * Q, 0, Q - 1)
        in_conv = jnp.where(own, x_local[local_i], in_conv)

        # -- stage compute: device 0 consumes the conveyor head (mb s).
        # checkpoint: the backward (reverse schedule via jax.grad of this
        # scan) rematerializes stage internals instead of stashing them
        # per step — per-device backward memory stays O(steps) carries,
        # the GPipe-with-remat memory profile (1F1B's further O(S) stash
        # reduction would need a manual interleaved bwd schedule; not
        # worth the complexity at this depth).
        cur = jnp.where(idx == 0, in_conv, held)
        m_f = s - idx                        # this device's forward mb
        f_valid = (m_f >= 0) & (m_f < n_microbatches)
        if keyed:
            key_m = jax.random.fold_in(
                rng, jnp.clip(m_f, 0, n_microbatches - 1))
            out, aux = jax.checkpoint(
                lambda p, c, k: apply_local(idx, p, c, k))(
                    p_local, cur, key_m)
            aux_acc = aux_acc + jnp.where(
                f_valid, aux.astype(jnp.float32), 0.0)
        else:
            out = jax.checkpoint(
                lambda p, c: apply_local(idx, p, c))(p_local, cur)

        # -- output conveyor: last stage writes mb m = s - (S-1)
        m_written = s - (S - 1)
        write = (idx == S - 1) & (m_written >= 0) \
            & (m_written < n_microbatches)
        out_conv = jnp.where(write, out, out_conv)

        # -- harvest: mb m arrives home h = m // Q after (h+1) mod S hops
        m_arr = s - (S - 1) - ((idx + 1) % S)
        harvest = (m_arr >= 0) & (m_arr < n_microbatches) \
            & (m_arr // Q == idx)
        local_o = jnp.clip(m_arr - idx * Q, 0, Q - 1)
        out_local = jnp.where(
            harvest,
            out_local.at[local_o].set(out_conv),
            out_local)

        held = jax.lax.ppermute(out, axis_name, up)
        in_conv = jax.lax.ppermute(in_conv, axis_name, down)
        out_conv = jax.lax.ppermute(out_conv, axis_name, up)
        return (held, in_conv, out_conv, out_local, aux_acc), None

    zeros = jnp.zeros(mb_shape, x_local.dtype)
    out_local0 = jnp.zeros((Q,) + mb_shape, x_local.dtype)
    (_, _, _, out_local, aux_acc), _ = jax.lax.scan(
        body,
        (zeros, zeros, zeros, out_local0, jnp.zeros((), jnp.float32)),
        jnp.arange(n_steps))
    if not keyed:
        return out_local[None]               # (1, Q, mb...)
    # per-stage aux sums -> replicated mean over microbatches (each aux
    # is already a mean over its microbatch slice; data shards average
    # via psum/bsz, psum over the pipe ring collects stages, /n_mb
    # averages microbatches)
    for ax in batch_axes:
        aux_acc = jax.lax.psum(aux_acc, ax) / jax.lax.psum(1, ax)
    aux_acc = jax.lax.psum(aux_acc, axis_name) / n_microbatches
    return out_local[None], aux_acc


def _ravel_stages(stage_fns: Sequence[Callable], params_list):
    """Heterogeneous-stage path: ravel per-stage params, zero-pad to the
    widest stage, stack (S, P_max), apply via lax.switch on stage index.
    Returns (stacked, apply_vec, unravels) where ``unravels`` maps a
    padded row back to that stage's param pytree."""
    vecs, unravels, lens = [], [], []
    for p in params_list:
        v, un = ravel_pytree(p)
        vecs.append(v)
        unravels.append(un)
        lens.append(v.shape[0])
    pmax = max(lens)
    stacked = jnp.stack([jnp.pad(v, (0, pmax - v.shape[0])) for v in vecs])
    branches = [
        (lambda vec, *xs, _fn=fn, _un=un, _l=l:
         _fn(_un(vec[:_l]), *xs))
        for fn, un, l in zip(stage_fns, unravels, lens)]

    def apply_vec(idx, vec, *xs):
        return jax.lax.switch(idx, branches, vec, *xs)

    return stacked, apply_vec, [
        (lambda row, _un=un, _l=l: _un(row[:_l]))
        for un, l in zip(unravels, lens)]


def _prep_stages(stage_fn, params, S: int, axis_name: str,
                 shared: bool = False):
    """Shared homogeneous/heterogeneous dispatch for pipeline_apply and
    pipeline_train_step: validates stage counts and returns
    (stacked, apply_local(idx, p, x), p_specs, unravels) where
    ``unravels`` is None on the homogeneous path.

    ``shared``: ONE callable ``stage_fn(idx, p, *xs)`` applied to every
    stage with per-stage params of IDENTICAL pytree structure (a list of
    S pytrees).  Unlike the heterogeneous ``lax.switch`` dispatch, every
    device traces the SAME stage body — required when stage bodies
    contain collectives over other mesh axes (ring attention, MoE
    all_to_all): a switch would diverge the collective sequence across
    pipe ranks, which a single SPMD program cannot express (the XLA CPU
    rendezvous deadlocks on it, and relying on CSE to merge identical
    branches is fragile)."""
    if shared:
        if not callable(stage_fn) or callable(params):
            raise ValueError(
                "shared mode takes one stage_fn(idx, p, *xs) plus a "
                "list of per-stage param pytrees")
        per_stage = list(params)
        if len(per_stage) != S:
            raise ValueError(
                f"need {S} per-stage param sets, got {len(per_stage)}")
        vecs, unravels, lens = [], [], []
        for p in per_stage:
            v, un = ravel_pytree(p)
            vecs.append(v)
            unravels.append(un)
            lens.append(v.shape[0])
        if len(set(lens)) != 1 or len({
                jax.tree_util.tree_structure(p) for p in per_stage}) != 1:
            raise ValueError(
                "shared stage dispatch needs structurally identical "
                f"per-stage params (raveled lengths {lens})")
        stacked = jnp.stack(vecs)
        un0, l0 = unravels[0], lens[0]

        def apply_shared(idx, vec, *xs):
            return stage_fn(idx, un0(vec[:l0]), *xs)

        return stacked, apply_shared, P(axis_name), unravels
    if callable(stage_fn):
        # homogeneous fast path: use the stacked tree directly — each
        # leaf shards P(pipe) on its stage axis, no ravel round-trip
        n_stages = {a.shape[0] for a in jax.tree.leaves(params)}
        if n_stages != {S}:
            raise ValueError(
                f"stacked params leading axis {sorted(n_stages)} must equal "
                f"the {axis_name!r} mesh axis size {S}")
        p_specs = jax.tree.map(lambda a: _stage_spec(a, axis_name), params)

        def apply_local(idx, p, *xs):
            return stage_fn(p, *xs)

        return params, apply_local, p_specs, None
    stage_fns, per_stage = list(stage_fn), list(params)
    if len(stage_fns) != S or len(per_stage) != S:
        raise ValueError(
            f"need {S} stage fns + param sets for the {axis_name!r} "
            f"axis, got {len(stage_fns)}/{len(per_stage)}")
    stacked, apply_local, unravels = _ravel_stages(stage_fns, per_stage)
    return stacked, apply_local, P(axis_name), unravels


def _prep_batch(x, n_mb: int, S: int, mesh: Mesh, axis_name: str,
                batch_axes):
    """Shared microbatch validation/spec construction: returns
    (batch_axes, x_spec) with the (S, Q) grouped layout spec."""
    if n_mb % S:
        raise ValueError(
            f"n_microbatches={n_mb} must be a multiple of the pipeline "
            f"depth {S} (inputs/outputs are sharded over {axis_name!r})")
    batch_axes = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    bsz = math.prod(mesh.shape[a] for a in batch_axes) if batch_axes else 1
    if batch_axes and x.shape[1] % bsz:
        raise ValueError(
            f"microbatch size {x.shape[1]} not divisible over batch axes "
            f"{batch_axes} (total {bsz})")
    # grouped layout (S, Q, mb, ...): stage blocks on 'pipe', the batch
    # dim on the data axes
    return batch_axes, P(axis_name, None, batch_axes or None)


def pipeline_apply(stage_fn: Union[Callable, Sequence[Callable]],
                   params, x, mesh: Mesh, *,
                   axis_name: str = "pipe",
                   n_microbatches: Optional[int] = None,
                   batch_axes: Sequence[str] = (),
                   rng: Optional[jax.Array] = None):
    """Run x through S pipelined stages.

    ``stage_fn(params, x) -> y``: one stage's computation (same activation
    shape in/out).  Homogeneous form: one callable + stage-stacked params
    (leading axis S, see :func:`stack_stage_params`).  Heterogeneous form:
    a list of S callables + a list of S per-stage param pytrees (arbitrary,
    possibly different structures).

    x: (n_microbatches, mb, ...) microbatch stack; ``n_microbatches`` must
    be a multiple of S (it is sharded ``P(axis_name)`` across stages).
    ``batch_axes``: mesh axes the per-microbatch batch dim (axis 1) is
    sharded over (e.g. ("data",)) — without it a dp×pp mesh would
    all-gather the batch and run the FULL batch through every data shard.
    Returns (n_microbatches, mb, ...) outputs, sharded the same way.

    ``rng``: keyed mode — stage fns take ``(params, x, key)`` with
    ``key = fold_in(rng, microbatch_index)`` (identical derivation to the
    fused 1F1B schedule, so stochastic stages draw the same randomness
    under either schedule) and return ``(y, aux)``; the call then returns
    ``(outputs, aux_mean)`` where ``aux_mean`` is the replicated mean
    over microbatches of the summed per-stage aux losses.
    """
    S = mesh.shape[axis_name]
    stacked, apply_local, p_specs, _ = _prep_stages(
        stage_fn, params, S, axis_name)
    n_mb = x.shape[0]
    if n_microbatches is not None and n_microbatches != n_mb:
        raise ValueError(
            f"n_microbatches={n_microbatches} != x.shape[0]={n_mb}")
    batch_axes, x_spec = _prep_batch(x, n_mb, S, mesh, axis_name,
                                     batch_axes)
    _log.debug("pipeline: S=%d n_mb=%d bubble=%.1f%%", S, n_mb,
               100 * bubble_fraction(S, n_mb))
    keyed = rng is not None
    fn = shard_map(
        functools.partial(_pipeline_local, apply_local=apply_local,
                          axis_name=axis_name, n_microbatches=n_mb,
                          n_stages=S, keyed=keyed,
                          batch_axes=batch_axes),
        mesh=mesh,
        in_specs=(p_specs, x_spec) + ((P(),) if keyed else ()),
        out_specs=(x_spec, P()) if keyed else x_spec,
        check_vma=False)
    # group the microbatch axis into (S, Q) so P(axis) places block d on
    # stage d, then flatten back
    grouped = x.reshape((S, n_mb // S) + x.shape[1:])
    if keyed:
        out, aux = fn(stacked, grouped, rng)
        return out.reshape((n_mb,) + x.shape[1:]), aux
    out = fn(stacked, grouped)
    return out.reshape((n_mb,) + x.shape[1:])


def _stage_spec(a, axis_name: str) -> P:
    """PartitionSpec splitting the leading stage axis over the pipe axis."""
    return P(axis_name, *([None] * (a.ndim - 1)))


def pick_batch_axes(axis_sizes: dict, mb: int,
                    candidates: Sequence[str] = ("data", "fsdp")
                    ) -> Tuple[str, ...]:
    """The candidate-axis SUBSET with the largest product dividing ``mb``
    (per-axis checks would accept data=2 AND fsdp=2 for mb=2 — an
    impossible 4-way shard of 2 samples; a fixed greedy order could pick
    data=2 over fsdp=4).  Shared by PipelineStack.apply and the fused
    1F1B compiler so both schedules shard a model identically."""
    cands = [a for a in candidates if axis_sizes.get(a, 1) > 1]
    best, picked = 1, ()
    for pick in range(1 << len(cands)):
        sub = tuple(a for i, a in enumerate(cands) if pick >> i & 1)
        prod = math.prod(axis_sizes[a] for a in sub) if sub else 1
        if mb % prod == 0 and prod > best:
            best, picked = prod, sub
    return picked


# ---------------------------------------------------------------------------
# 1F1B fused train step
# ---------------------------------------------------------------------------

def _1f1b_local(stage_params, x_blk, y_blk, *args, apply_local, loss_local,
                axis_name: str, batch_axes, n_microbatches: int,
                n_stages: int, het: bool = False, keyed: bool = False,
                ring_feat=(), ring_dtype=None):
    """Per-device 1F1B body under shard_map.

    Lockstep schedule over s = 0..n_mb+2(S-1)-1 where EVERY step carries
    one forward slot and one backward slot per device:

    * fwd: device d runs stage d on microbatch ``m_f = s - d``;
    * bwd: device d runs the stage VJP on ``m_b = s - 2(S-1) + d``.

    At the last stage ``m_f == m_b`` — the loss gradient of a microbatch
    is computed in the same step its forward completes, so backward waves
    start draining immediately (the "one forward, one backward" steady
    state).  Device d holds at most ``2(S-1-d)+1`` stashed stage inputs —
    bounded by the pipeline depth, NOT by n_microbatches, which is the
    1F1B memory property GPipe-with-tape lacks.  Stage internals are
    rematerialized inside the VJP (activation-stash-only recompute
    backward, the standard 1F1B memory/compute trade).

    Internal stage contract (both modes lower to it):
    ``apply_full(idx, p, x_in, x_ring, key) -> (ring_msg, out, aux)``.

    * ``het=False`` (uniform buffers, the generic API): x_in/x_ring/ring/
      out all share the input microbatch shape; the lift selects
      ``where(idx==0, x_in, x_ring)`` and emits its output as both the
      ring message and the loss input, aux 0.
    * ``het=True``: the input conveyor, activation ring, and loss input
      have their OWN static shapes/dtypes (``ring_feat``/``out_feat``) —
      the ring never carries logits (stage S-1's output is consumed by
      the loss locally, its ring slot is zeros nobody reads) and dtypes
      are preserved end to end (a bf16 ring stays bf16).  Backward keeps
      two stash buffers (stage-0 input + ring activations).
    * ``keyed``: the per-slot key is ``fold_in(rng, mb_index)`` — forward
      and its matching VJP recompute use the SAME key, so stochastic
      stages (dropout) are consistent, and the derivation equals the
      GPipe keyed path's.  Aux losses accumulate from valid forward
      slots; their parameter/input gradients enter through the VJP's
      aux cotangent of 1.
    """
    rng = args[0] if keyed else None
    S, Q = n_stages, n_microbatches // n_stages
    K = 2 * (S - 1) + 1 if S > 1 else 1      # stash depth (max in-flight)
    idx = jax.lax.axis_index(axis_name)
    p_local = jax.tree.map(lambda a: a[0], stage_params)
    x_local = x_blk[0]                       # (Q, mb...)
    y_local = y_blk[0]                       # (Q, lbl...)
    mb_shape = x_local.shape[1:]
    lbl_shape = y_local.shape[1:]
    mb = mb_shape[0]
    if het:
        ring_shape, ring_dt = (mb,) + tuple(ring_feat), ring_dtype
    else:
        ring_shape, ring_dt = mb_shape, x_local.dtype

    down = [(i, (i - 1) % S) for i in range(S)]
    up = [(i, (i + 1) % S) for i in range(S)]
    n_steps = n_microbatches + 2 * (S - 1)

    if het:
        def apply_full(p, xi, xr, key):
            return apply_local(idx, p, xi, xr, key)
    else:
        def apply_full(p, xi, xr, key):
            cur = jnp.where(idx == 0, xi, xr)
            out = (apply_local(idx, p, cur, key) if keyed
                   else apply_local(idx, p, cur))
            if keyed:
                out, aux = out
            else:
                aux = jnp.zeros((), jnp.float32)
            return out, out, aux

    def mb_key(m):
        if rng is None:
            return jax.random.key(0)  # het & deterministic: unused
        return jax.random.fold_in(
            rng, jnp.clip(m, 0, n_microbatches - 1))

    def body(carry, s):
        (held, g_held, in_conv, lbl_conv, stash_in, stash_ring, gp_acc,
         loss_acc, aux_acc) = carry

        # -- input conveyor (converges down to stage 0): load mb s+idx
        t_in = s + idx
        own_in = (t_in >= idx * Q) & (t_in < (idx + 1) * Q) \
            & (t_in < n_microbatches)
        in_conv = jnp.where(own_in, x_local[jnp.clip(t_in - idx * Q,
                                                     0, Q - 1)], in_conv)

        # -- label conveyor (converges up to stage S-1): device c loads
        # label mb t = s - c; after S-1-c up-hops it reaches the last
        # stage at step t + S - 1, exactly when that microbatch's forward
        # completes there.
        t_lb = s - idx
        own_lb = (t_lb >= idx * Q) & (t_lb < (idx + 1) * Q) \
            & (t_lb < n_microbatches)
        lbl_conv = jnp.where(own_lb, y_local[jnp.clip(t_lb - idx * Q,
                                                      0, Q - 1)], lbl_conv)

        # -- forward slot: mb m_f = s - idx
        m_f = s - idx
        f_valid = (m_f >= 0) & (m_f < n_microbatches)
        ring_out, out_f, aux_f = apply_full(
            p_local, in_conv, held, mb_key(m_f))
        # stash this step's stage inputs for the matching backward
        slot = jnp.mod(m_f, K)
        if het:
            stash_in = jnp.where(f_valid,
                                 stash_in.at[slot].set(in_conv), stash_in)
            stash_ring = jnp.where(
                f_valid, stash_ring.at[slot].set(held), stash_ring)
        else:
            cur = jnp.where(idx == 0, in_conv, held)
            stash_in = jnp.where(f_valid,
                                 stash_in.at[slot].set(cur), stash_in)

        # -- backward slot: mb m_b = s - 2(S-1) + idx
        m_b = s - 2 * (S - 1) + idx
        b_valid = (m_b >= 0) & (m_b < n_microbatches)
        bslot = jnp.mod(m_b, K)
        xi_saved = stash_in[bslot]
        xr_saved = stash_ring[bslot] if het else xi_saved
        # last stage: m_b == m_f, loss grad comes straight off this
        # step's forward output; other stages consume the rotated
        # cotangent from the stage above (naturally zero at S-1: stage
        # 0 reads the input conveyor, so its ring cotangent is zero).
        loss_m, gy_last = jax.value_and_grad(loss_local)(out_f, lbl_conv)
        key_b = mb_key(m_b)
        _, vjp = jax.vjp(
            lambda p, xi, xr: apply_full(p, xi, xr, key_b),
            p_local, xi_saved, xr_saved)
        gy = jnp.where(idx == S - 1, gy_last,
                       jnp.zeros_like(gy_last))
        # one VJP for all three outputs: the ring cotangent from above,
        # the loss cotangent (last stage only), and aux cotangent 1 —
        # aux-loss grads get the same /n_mb rescale as the main loss
        gp, _, gx = vjp((g_held, gy, jnp.ones((), jnp.float32)))
        gp_acc = jax.tree.map(
            lambda a, g: a + jnp.where(b_valid, g, 0), gp_acc, gp)
        loss_acc = loss_acc + jnp.where(
            (idx == S - 1) & f_valid, loss_m, 0.0)
        # aux tracked separately so the step can report it as its own
        # metric (the AD path's loss metric excludes aux too); its
        # gradient already entered through the vjp cotangent above
        aux_acc = aux_acc + jnp.where(
            f_valid, aux_f.astype(jnp.float32), 0.0)

        held = jax.lax.ppermute(ring_out, axis_name, up)
        g_held = jax.lax.ppermute(jnp.where(b_valid, gx, 0),
                                  axis_name, down)
        in_conv = jax.lax.ppermute(in_conv, axis_name, down)
        lbl_conv = jax.lax.ppermute(lbl_conv, axis_name, up)
        return (held, g_held, in_conv, lbl_conv, stash_in, stash_ring,
                gp_acc, loss_acc, aux_acc), None

    carry0 = (jnp.zeros(ring_shape, ring_dt),
              jnp.zeros(ring_shape, ring_dt),
              jnp.zeros(mb_shape, x_local.dtype),
              jnp.zeros(lbl_shape, y_local.dtype),
              jnp.zeros((K,) + mb_shape, x_local.dtype),
              (jnp.zeros((K,) + ring_shape, ring_dt) if het
               else jnp.zeros((), jnp.float32)),
              jax.tree.map(jnp.zeros_like, p_local),
              jnp.zeros((), jnp.float32),
              jnp.zeros((), jnp.float32))
    (_, _, _, _, _, _, gp_acc, loss_acc, aux_acc), _ = jax.lax.scan(
        body, carry0, jnp.arange(n_steps))
    # batch dims may be sharded over data axes: reduce across those shards
    # (params are replicated there), then rescale so per-microbatch
    # semantics stay "loss_fn over the FULL microbatch" — each shard saw
    # loss_fn over a 1/bsz slice, so the psum of per-shard means is bsz
    # times the global mean.
    bsz = 1
    for ax in batch_axes:
        bsz *= jax.lax.psum(1, ax)
        gp_acc = jax.tree.map(
            lambda g: jax.lax.psum(g, ax), gp_acc)
        loss_acc = jax.lax.psum(loss_acc, ax)
        aux_acc = jax.lax.psum(aux_acc, ax)
    gp_acc = jax.tree.map(lambda g: g / bsz, gp_acc)
    loss_acc = loss_acc / bsz
    aux_acc = aux_acc / bsz
    # the loss lives on the last stage only (aux on every stage); share
    # them along the pipe ring
    loss_acc = jax.lax.psum(loss_acc, axis_name) / n_microbatches
    aux_acc = jax.lax.psum(aux_acc, axis_name) / n_microbatches
    # grads are accumulated as SUMS over microbatches; rescale to the mean
    # so (loss, grads) form a consistent pair with the pipeline_apply +
    # jax.grad path — swapping schedules must not change the effective
    # learning rate by a factor of n_microbatches.
    gp_acc = jax.tree.map(lambda g: g / n_microbatches, gp_acc)
    return (jax.tree.map(lambda g: g[None], gp_acc), loss_acc, aux_acc)


def pipeline_train_step(stage_fn: Union[Callable, Sequence[Callable]],
                        loss_fn: Callable, params, x, labels, mesh: Mesh, *,
                        axis_name: str = "pipe",
                        batch_axes: Sequence[str] = (),
                        width_axes: Sequence[str] = (),
                        rng: Optional[jax.Array] = None,
                        ring_spec=None, with_aux: bool = False,
                        shared: bool = False, interleave: int = 1):
    """Fused 1F1B pipeline training step: returns ``(loss, param_grads)``.

    Unlike :func:`pipeline_apply` + ``jax.grad`` (GPipe schedule: AD tapes
    O(n_microbatches) carries per device), this hand-scheduled step
    interleaves one forward and one backward per device per step and
    stashes at most ``2(S-1)+1`` stage inputs — backward memory bounded by
    pipeline depth.  The trade: it IS the training step (fwd+bwd fused),
    so it composes with an optimizer, not with arbitrary surrounding AD —
    use it when the model is the pipeline (the Megatron-style scheduling
    contract).

    ``loss_fn(y_mb, label_mb) -> scalar`` is evaluated on the last stage's
    output per microbatch and MUST be a mean (not a sum) over its
    microbatch slice when ``batch_axes`` shards the batch dim — the
    cross-shard reduction rescales by the shard count on that assumption.
    The returned loss is the mean over microbatches and the grads are
    d(that mean)/dparams — the same (loss, grads) contract as
    ``jax.value_and_grad`` over ``pipeline_apply``, so the two schedules
    are drop-in interchangeable under one optimizer.  Heterogeneous form
    returns grads as a list of per-stage pytrees matching ``params``.

    Two stage contracts:

    * **uniform** (``ring_spec=None``): ``stage_fn(p, x) -> y`` with the
      same microbatch shape in/out; with ``rng`` given, ``stage_fn(p, x,
      key) -> (y, aux)`` where ``key = fold_in(rng, mb_index)`` (the same
      derivation :func:`pipeline_apply`'s keyed mode uses, so stochastic
      stages match across schedules) and ``aux`` joins the loss with
      cotangent 1.
    * **heterogeneous buffers** (``ring_spec`` a per-sample
      ``ShapeDtypeStruct``): ``stage_fn(p, x_in, x_ring, key) ->
      (ring_msg, out, aux)``.  The input conveyor keeps x's shape/dtype,
      the activation ring carries exactly ``ring_spec`` per sample
      (dtype preserved — never upcast), and the last stage's ``out``
      feeds ``loss_fn`` locally without ever riding the ring, so ring
      bytes are independent of the output/vocab width.  Used by the
      fused workflow compiler (``pipeline_compile.py``).

    ``width_axes`` (heterogeneous mode only): mesh axes sharding the
    trailing FEATURE dim of the input conveyor and the activation ring —
    sequence parallelism (round-4 verdict #3: ring attention inside
    fused-1F1B stages).  The per-sample ring payload each device carries
    becomes ``ring_spec/∏width_axes``; labels stay width-replicated (the
    loss slices them by rank); stage closures see LOCAL shards and may
    run raw collectives over these axes (they are part of this
    shard_map's mesh).  The per-device loss must then be the mean over
    the LOCAL slice — the cross-shard reduction treats width axes
    exactly like batch axes (psum of per-shard means / shard count).
    """
    S = mesh.shape[axis_name]
    v = int(interleave)
    if v > 1 and not shared:
        raise ValueError(
            "interleave > 1 needs the shared stage dispatch (uniform "
            "virtual chunks; heterogeneous lax.switch stages cannot "
            "interleave)")
    if v > 1 and ring_spec is None:
        raise ValueError(
            "pipeline_train_step's interleave mode uses the "
            "heterogeneous-buffer contract (ring_spec); for the plain "
            "uniform contract use interleaved_train_step")
    stacked, apply_local, p_specs, unravels = _prep_stages(
        stage_fn, params, S * v if shared else S, axis_name,
        shared=shared)
    if v > 1:
        # (L, P) raveled rows -> (S, v, P): row [d, j] is logical stage
        # j*S + d, so P(pipe) shards the DEVICE axis
        L = v * S
        stacked = jnp.stack(
            [jnp.stack([stacked[j * S + d] for j in range(v)])
             for d in range(S)])
    n_mb = x.shape[0]
    if labels.shape[0] != n_mb:
        raise ValueError("labels must have the same microbatch count as x")
    batch_axes, x_spec = _prep_batch(x, n_mb, S, mesh, axis_name,
                                     batch_axes)
    lbl_spec = x_spec
    het = ring_spec is not None
    width_axes = tuple(a for a in width_axes if mesh.shape[a] > 1)
    ring_feat = tuple(ring_spec.shape) if het else ()
    if width_axes:
        if not het:
            raise ValueError(
                "width_axes needs the heterogeneous-buffer mode "
                "(ring_spec): uniform stages carry the input shape")
        wsz = math.prod(mesh.shape[a] for a in width_axes)
        if x.shape[-1] % wsz or (ring_feat and ring_feat[-1] % wsz):
            raise ValueError(
                f"conveyor width {x.shape[-1]} / ring width {ring_feat} "
                f"not divisible over width axes {width_axes} ({wsz})")
        # (S, Q, mb, width): width sharded, labels stay replicated there
        x_spec = P(axis_name, None,
                   x_spec[2] if len(x_spec) > 2 else None, width_axes)
        ring_feat = ring_feat[:-1] + (ring_feat[-1] // wsz,)
    keyed = rng is not None or het
    if het and rng is None:
        rng = jax.random.key(0)  # deterministic het stages: key unused
    if v > 1:
        body = functools.partial(
            _interleaved_local, apply_local=apply_local,
            loss_local=loss_fn, axis_name=axis_name,
            batch_axes=batch_axes + width_axes, n_microbatches=n_mb,
            n_stages=S, v=v, het=het, keyed=keyed,
            ring_feat=ring_feat,
            ring_dtype=ring_spec.dtype if het else None)
    else:
        body = functools.partial(
            _1f1b_local, apply_local=apply_local,
            loss_local=loss_fn, axis_name=axis_name,
            batch_axes=batch_axes + width_axes, n_microbatches=n_mb,
            n_stages=S, het=het, keyed=keyed, ring_feat=ring_feat,
            ring_dtype=ring_spec.dtype if het else None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(p_specs, x_spec, lbl_spec) + ((P(),) if keyed else ()),
        out_specs=(p_specs, P(), P()),
        check_vma=False)
    grouped_x = x.reshape((S, n_mb // S) + x.shape[1:])
    grouped_y = labels.reshape((S, n_mb // S) + labels.shape[1:])
    args = (rng,) if keyed else ()
    grads, loss, aux = fn(stacked, grouped_x, grouped_y, *args)
    if v > 1:
        # (S, v, P) device/lane grouping -> the caller's logical order
        grads = [unravels[l](grads[l % S, l // S])
                 for l in range(S * v)]
    elif unravels is not None:
        # hand grads back in the caller's per-stage structures, not the
        # internal zero-padded raveled stack
        grads = [un(grads[s]) for s, un in enumerate(unravels)]
    # `loss` excludes aux (the AD path's reporting contract: aux is its
    # own metric); grads ARE d(loss + aux)/dparams
    if with_aux:
        return loss, aux, grads
    return loss, grads


# ---------------------------------------------------------------------------
# Interleaved 1F1B (virtual pipeline stages)
# ---------------------------------------------------------------------------

def _interleaved_local(stage_params, x_blk, y_blk, *args, apply_local,
                       loss_local, axis_name: str, batch_axes,
                       n_microbatches: int, n_stages: int, v: int,
                       keyed: bool = False, het: bool = False,
                       ring_feat=(), ring_dtype=None):
    """Per-device interleaved-1F1B body under shard_map — the Megatron
    "virtual pipeline" schedule, one chunk-slot pair per device-step.

    L = v·S logical stages; stage l lives on device d = l % S as lane
    j = l // S.  Device d runs ONE chunk forward and ONE chunk backward
    per step in the LOOPING order (groups of S microbatches sweep each
    lane before the next lane starts):

    * fwd slot of stage l on microbatch m at step
      ``F(l, m) = vS·(m÷S) + S·j + (m mod S) + d``;
    * bwd slot at
      ``B(l, m) = vS·(m÷S) + S·(v−1−j) + (m mod S) + (S−1−d) + (L−1)``.

    Both assignments give every device exactly one fwd and one bwd slot
    per step (contiguous once filled), satisfy the one-hop dependency
    chains (``F(l,m) = F(l−1,m)+1``, ``B(l,m) = B(l+1,m)+1``), and put
    the last stage's bwd in the SAME step as its fwd (loss grad straight
    off the fresh output, like the plain schedule).  Total span is
    ``v·n_mb + L + S − 2`` chunk-pair steps — dependency-chain optimal
    for this lockstep form, and v=1 reduces exactly to plain 1F1B.

    Honest bubble accounting: the same L-chunk model folded v-per-stage
    into plain 1F1B spans ``v·n_mb + 2v(S−1)`` chunk-pairs, so the
    interleave saves ``v(S−2) − S + 2`` bubble steps — up to ~2× less
    bubble at deep pipes (ratio → 2(S−1)/S), NOT the (S−1)/v of the
    MPMD Megatron schedule: a single SPMD scan executes masked slots at
    full cost and cannot skip a phase (a per-device fwd/bwd cond would
    diverge the in-stage collective sequence), so the fill fills v×
    faster but the paired fwd+bwd lockstep bounds the total gain.  The
    trade costs v× the activation stash; parameter bytes per device are
    unchanged.

    Ring traffic is ONE chunk message per hop: consecutive devices'
    current slots are lane-aligned by the timetable (the lane index
    advances automatically across the S−1 → 0 wrap), so no stacked
    lanes ride the ring.  The input conveyor loads mb m on its owner so
    it reaches device 0 at ``F(0, m)``; the label conveyor reaches
    device S−1 at ``F(L−1, m)``.

    ``het``: the fused-compiler contract — ``apply_local(l, p, x_in,
    x_ring, key) -> (ring_msg, out, aux)`` with l the (traced) logical
    stage; uniform mode wraps ``stage_fn(p, x[, key])`` the same way
    ``_1f1b_local`` does."""
    rng = args[0] if keyed else None
    S, L = n_stages, v * n_stages
    n_mb = n_microbatches
    Q = n_mb // S
    vS = v * S
    K = 2 * (L - 1) + 1
    idx = jax.lax.axis_index(axis_name)
    p_lanes = jax.tree.map(lambda a: a[0], stage_params)   # (v, ...)
    x_local = x_blk[0]
    y_local = y_blk[0]
    mb_shape = x_local.shape[1:]
    mb = mb_shape[0]
    lbl_shape = y_local.shape[1:]
    if het:
        ring_shape, ring_dt = (mb,) + tuple(ring_feat), ring_dtype
    else:
        ring_shape, ring_dt = mb_shape, x_local.dtype

    up = [(i, (i + 1) % S) for i in range(S)]
    down = [(i, (i - 1) % S) for i in range(S)]
    n_steps = v * n_mb + L + S - 2

    def mb_key(m):
        if rng is None:
            return None
        return jax.random.fold_in(rng, jnp.clip(m, 0, n_mb - 1))

    if het:
        def apply_full(l, p, xi, xr, key):
            return apply_local(l, p, xi, xr, key)
    else:
        def apply_full(l, p, xi, xr, key):
            cur = jnp.where(l == 0, xi, xr)
            if keyed:
                out, aux = apply_local(p, cur, key)
            else:
                out, aux = apply_local(p, cur), \
                    jnp.zeros((), jnp.float32)
            return out, out, aux

    def lane_p(j):
        return jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, j, 0, keepdims=False), p_lanes)

    def body(carry, s):
        (held, g_held, in_conv, lbl_conv, stash_in, stash_ring, gp_acc,
         loss_acc, aux_acc) = carry

        # -- input conveyor: mb m must reach device 0 at F(0, m) =
        # vS·g + r; loading on owner c happens c down-hops earlier
        w_in = s + idx
        r_in = jnp.mod(w_in, vS)
        m_in = (w_in // vS) * S + r_in
        own_in = (r_in < S) & (m_in >= idx * Q) \
            & (m_in < (idx + 1) * Q) & (m_in < n_mb)
        in_conv = jnp.where(
            own_in, x_local[jnp.clip(m_in - idx * Q, 0, Q - 1)], in_conv)
        # -- label conveyor: arrival at device S-1 at F(L-1, m) =
        # vS·g + S(v-1) + r + (S-1); loading is S-1-c up-hops earlier
        w_lb = s - idx - S * (v - 1)
        r_lb = jnp.mod(w_lb, vS)
        m_lb = (w_lb // vS) * S + r_lb
        own_lb = (w_lb >= 0) & (r_lb < S) & (m_lb >= idx * Q) \
            & (m_lb < (idx + 1) * Q) & (m_lb < n_mb)
        lbl_conv = jnp.where(
            own_lb, y_local[jnp.clip(m_lb - idx * Q, 0, Q - 1)], lbl_conv)

        # -- forward slot: u = s - d encodes (group, lane, rank)
        u_f = s - idx
        j_f = jnp.mod(u_f, vS) // S
        m_f = (u_f // vS) * S + jnp.mod(u_f, S)
        l_f = j_f * S + idx
        f_valid = (u_f >= 0) & (m_f < n_mb)
        ring_msg, out, aux_f = apply_full(
            l_f, lane_p(j_f), in_conv, held, mb_key(m_f))
        slot_f = jnp.mod(jnp.clip(m_f, 0, n_mb - 1), K)
        if het:
            stash_in = jnp.where(
                f_valid, stash_in.at[j_f, slot_f].set(in_conv), stash_in)
            stash_ring = jnp.where(
                f_valid, stash_ring.at[j_f, slot_f].set(held), stash_ring)
        else:
            cur = jnp.where(l_f == 0, in_conv, held)
            stash_in = jnp.where(
                f_valid, stash_in.at[j_f, slot_f].set(cur), stash_in)
        aux_acc = aux_acc + jnp.where(
            f_valid, aux_f.astype(jnp.float32), 0.0)

        # -- backward slot: u = s - (S-1-d) - (L-1) encodes the
        # mirrored (group, lane, rank)
        u_b = s - (S - 1 - idx) - (L - 1)
        j_b = v - 1 - jnp.mod(u_b, vS) // S
        m_b = (u_b // vS) * S + jnp.mod(u_b, S)
        l_b = j_b * S + idx
        b_valid = (u_b >= 0) & (m_b >= 0) & (m_b < n_mb)
        slot_b = jnp.mod(jnp.clip(m_b, 0, n_mb - 1), K)
        xi_saved = stash_in[j_b, slot_b]
        xr_saved = stash_ring[j_b, slot_b] if het else xi_saved
        is_last = (idx == S - 1) & (j_b == v - 1)
        # B(L-1, m) == F(L-1, m): the last stage's loss grad comes off
        # THIS step's forward output, exactly like the plain schedule
        loss_m, gy_last = jax.value_and_grad(loss_local)(out, lbl_conv)
        gy = jnp.where(is_last, gy_last, jnp.zeros_like(gy_last))
        key_b = mb_key(m_b)
        j_b_ = j_b

        def bwd_fn(p, xi, xr):
            return apply_full(j_b_ * S + idx, p, xi, xr, key_b)

        _, vjp = jax.vjp(bwd_fn, lane_p(j_b), xi_saved, xr_saved)
        g_ring = g_held if het else jnp.where(
            is_last, jnp.zeros_like(g_held), g_held)
        gp, _, gxr = vjp((g_ring, gy, jnp.ones((), jnp.float32)))
        gp_acc = jax.tree.map(
            lambda acc, g: jax.lax.dynamic_update_index_in_dim(
                acc, jnp.where(b_valid, g, 0)
                + jax.lax.dynamic_index_in_dim(acc, j_b, 0,
                                               keepdims=False),
                j_b, 0),
            gp_acc, gp)
        gx = jnp.where(b_valid, gxr, jnp.zeros_like(gxr))
        loss_acc = loss_acc + jnp.where(
            is_last & (m_b >= 0) & (m_b < n_mb), loss_m, 0.0)

        # -- hops: one chunk message each way; consecutive devices'
        # slots are lane-aligned by the timetable (incl. at the wrap)
        held = jax.lax.ppermute(ring_msg, axis_name, up)
        g_held = jax.lax.ppermute(gx, axis_name, down)
        in_conv = jax.lax.ppermute(in_conv, axis_name, down)
        lbl_conv = jax.lax.ppermute(lbl_conv, axis_name, up)
        return (held, g_held, in_conv, lbl_conv, stash_in, stash_ring,
                gp_acc, loss_acc, aux_acc), None

    carry0 = (jnp.zeros(ring_shape, ring_dt),
              jnp.zeros(ring_shape, ring_dt),
              jnp.zeros(mb_shape, x_local.dtype),
              jnp.zeros(lbl_shape, y_local.dtype),
              jnp.zeros((v, K) + mb_shape, x_local.dtype),
              (jnp.zeros((v, K) + ring_shape, ring_dt) if het
               else jnp.zeros((), jnp.float32)),
              jax.tree.map(jnp.zeros_like, p_lanes),
              jnp.zeros((), jnp.float32),
              jnp.zeros((), jnp.float32))
    (_, _, _, _, _, _, gp_acc, loss_acc, aux_acc), _ = jax.lax.scan(
        body, carry0, jnp.arange(n_steps))
    bsz = 1
    for ax in batch_axes:
        bsz *= jax.lax.psum(1, ax)
        gp_acc = jax.tree.map(lambda g: jax.lax.psum(g, ax), gp_acc)
        loss_acc = jax.lax.psum(loss_acc, ax)
        aux_acc = jax.lax.psum(aux_acc, ax)
    gp_acc = jax.tree.map(lambda g: g / bsz, gp_acc)
    loss_acc = jax.lax.psum(loss_acc, axis_name) / bsz / n_mb
    aux_acc = jax.lax.psum(aux_acc, axis_name) / bsz / n_mb
    gp_acc = jax.tree.map(lambda g: g[None] / n_mb, gp_acc)
    return gp_acc, loss_acc, aux_acc


def interleaved_train_step(stage_fn: Callable, loss_fn: Callable,
                           params, x, labels, mesh: Mesh, *,
                           interleave: int,
                           axis_name: str = "pipe",
                           batch_axes: Sequence[str] = (),
                           rng: Optional[jax.Array] = None,
                           with_aux: bool = False):
    """Interleaved (virtual-stage) 1F1B training step.

    ``params``: stage-stacked pytree with leading axis L = interleave·S
    (logical stage l lives on device l % S — the caller keeps the plain
    (L, ...) layout; this function regroups to (S, v, ...) so P(pipe)
    shards the device axis).  Uniform-buffer contract only (every chunk
    preserves the microbatch shape); ``stage_fn(p, x)`` or — with
    ``rng`` — ``stage_fn(p, x, key) -> (y, aux)`` exactly like
    :func:`pipeline_train_step`'s uniform keyed mode, and the returned
    (loss, grads) pair matches it: mean over microbatches, so the two
    schedules are drop-in interchangeable under one optimizer.  Grads
    come back in the caller's (L, ...) stacking.

    Why: splitting the model into v chunks per device fills the
    pipeline v× faster; total span drops from ``v·n_mb + 2v(S−1)``
    chunk-pair steps (the same model folded into plain 1F1B) to
    ``v·n_mb + L + S − 2`` — up to ~2× less bubble at deep pipes (see
    ``_interleaved_local`` for why the SPMD lockstep bounds the gain
    below MPMD Megatron's (S−1)/v) — at v× the activation stash.
    """
    v = int(interleave)
    S = mesh.shape[axis_name]
    L = v * S
    leaves = jax.tree.leaves(params)
    if not leaves or any(a.shape[0] != L for a in leaves):
        raise ValueError(
            f"interleaved params need leading stage axis {L} "
            f"(= interleave {v} × {axis_name} {S}); got "
            f"{sorted({a.shape[0] for a in leaves})}")
    n_mb = x.shape[0]
    if labels.shape[0] != n_mb:
        raise ValueError("labels must have the same microbatch count as x")
    batch_axes, x_spec = _prep_batch(x, n_mb, S, mesh, axis_name,
                                     batch_axes)
    # (L, ...) -> (S, v, ...): row [d, j] is logical stage j*S + d
    regrouped = jax.tree.map(
        lambda a: jnp.stack(
            [jnp.stack([a[j * S + d] for j in range(v)])
             for d in range(S)]), params)
    p_specs = jax.tree.map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), regrouped)
    keyed = rng is not None
    fn = shard_map(
        functools.partial(_interleaved_local, apply_local=stage_fn,
                          loss_local=loss_fn, axis_name=axis_name,
                          batch_axes=batch_axes, n_microbatches=n_mb,
                          n_stages=S, v=v, keyed=keyed),
        mesh=mesh,
        in_specs=(p_specs, x_spec, x_spec) + ((P(),) if keyed else ()),
        out_specs=(p_specs, P(), P()),
        check_vma=False)
    gx = x.reshape((S, n_mb // S) + x.shape[1:])
    gy = labels.reshape((S, n_mb // S) + labels.shape[1:])
    args = (rng,) if keyed else ()
    grads, loss, aux = fn(regrouped, gx, gy, *args)
    # (S, v, ...) -> caller's (L, ...)
    grads = jax.tree.map(
        lambda a: jnp.stack([a[l % S, l // S] for l in range(L)]), grads)
    if with_aux:
        return loss, aux, grads
    return loss, grads


def pipeline_stage_shardings(stacked_params, mesh: Mesh,
                             axis_name: str = "pipe"):
    """NamedShardings placing one stage per device along the pipe axis."""
    return jax.tree.map(
        lambda a: NamedSharding(mesh, _stage_spec(a, axis_name)),
        stacked_params)
