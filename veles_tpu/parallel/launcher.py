"""Host launcher: spawn one training process per host.

Reference parity: the Launcher SSH-spawned slaves from ``-n
host/device:0-3x2`` specs and owned their lifecycle
(veles/launcher.py:617,808-842, respawn veles/server.py:637-655).

TPU redesign: there is no master — the launcher starts N identical SPMD
processes (local ``subprocess`` for localhost entries, ``ssh`` otherwise),
handing each its rank via the VELES_* environment that
``initialize_distributed`` reads. Host 0's machine doubles as the JAX
coordinator. Failure semantics follow SURVEY.md §5.3: if any process dies,
the launcher terminates the rest (gang scheduling) and reports — recovery
is checkpoint-restart, not slave respawn."""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

from ..logger import Logger

_LOCAL = {"localhost", "127.0.0.1", ""}


class HostLauncher(Logger):
    """Launch ``command`` on every host with rank env vars set."""

    def __init__(self, hosts: Sequence[str], *, coordinator_port: int = 9428,
                 ssh_args: Optional[Sequence[str]] = None):
        self.hosts = [h.strip() for h in hosts if h.strip()]
        if not self.hosts:
            raise ValueError("no hosts")
        self.coordinator_port = coordinator_port
        # -tt forces a pty so terminating the ssh client HUPs the remote
        # process — without it "terminate the gang" would only kill the
        # local ssh while the remote rank keeps holding its chips.
        self.ssh_args = list(ssh_args or ("-o", "BatchMode=yes", "-tt"))
        self.procs: List[subprocess.Popen] = []

    def _env_for(self, rank: int) -> Dict[str, str]:
        if self.hosts[0] in _LOCAL:
            # With remote ranks in the gang, "127.0.0.1" would point each
            # one at ITS OWN loopback; give them this machine's name.
            any_remote = any(h not in _LOCAL for h in self.hosts)
            import socket
            coord_host = socket.gethostname() if any_remote else "127.0.0.1"
        else:
            coord_host = self.hosts[0]
        return {
            "VELES_COORDINATOR": f"{coord_host}:{self.coordinator_port}",
            "VELES_NUM_PROCESSES": str(len(self.hosts)),
            "VELES_PROCESS_ID": str(rank),
        }

    def launch(self, command: Sequence[str]) -> List[subprocess.Popen]:
        """Start the command on every host; returns the process handles
        (remote hosts run under ssh)."""
        for rank, host in enumerate(self.hosts):
            env_vars = self._env_for(rank)
            if host in _LOCAL:
                env = dict(os.environ)
                env.update(env_vars)
                proc = subprocess.Popen(list(command), env=env)
            else:
                import shlex
                exports = " ".join(f"{k}={v}" for k, v in env_vars.items())
                remote = (f"cd {shlex.quote(os.getcwd())} && {exports} "
                          + " ".join(shlex.quote(c) for c in command))
                proc = subprocess.Popen(
                    ["ssh", *self.ssh_args, host, remote])
            self.info("rank %d on %s: pid %d", rank, host or "localhost",
                      proc.pid)
            self.procs.append(proc)
        return self.procs

    def wait(self, timeout: Optional[float] = None) -> int:
        """Wait for all ranks, polling EVERY process — a failure in any
        rank must be seen even while another rank hangs in a collective
        waiting for it (SPMD is gang-scheduled; a lone survivor never
        exits on its own). On the first non-zero exit the rest are
        terminated. Returns the first non-zero exit code, else 0."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        failed = 0
        pending = list(self.procs)
        while pending:
            progressed = False
            for proc in list(pending):
                code = proc.poll()
                if code is None:
                    continue
                progressed = True
                pending.remove(proc)
                if code != 0 and failed == 0:
                    failed = code
                    self.warning("rank %d exited %d; terminating the gang",
                                 self.procs.index(proc), code)
                    for other in pending:
                        other.terminate()
            if pending and not progressed:
                if deadline is not None and _time.monotonic() > deadline:
                    self.terminate()
                    raise subprocess.TimeoutExpired(
                        "gang", timeout if timeout is not None else 0)
                _time.sleep(0.05)
        return failed

    def terminate(self):
        for proc in self.procs:
            if proc.poll() is None:
                proc.terminate()


def launch_hosts(hosts: Sequence[str], argv: Sequence[str], *,
                 coordinator_port: int = 9428) -> int:
    """One-shot: spawn ``python -m veles_tpu <argv>`` per host and wait."""
    launcher = HostLauncher(hosts, coordinator_port=coordinator_port)
    launcher.launch([sys.executable, "-m", "veles_tpu", *argv])
    return launcher.wait()
