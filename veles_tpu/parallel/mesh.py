"""Device mesh + sharding rules.

This module replaces the ENTIRE distributed stack of the reference — the
Twisted TCP control plane, ZeroMQ data plane, master-slave job protocol and
serialized Python gradient merging (reference: veles/server.py:659,
veles/client.py:405, veles/txzmq/connection.py:97, SURVEY.md §2.5) — with
the TPU-native SPMD model: a ``jax.sharding.Mesh`` over ICI/DCN, sharding
annotations on the workflow state pytree, and XLA-inserted collectives
(psum for gradients riding ICI instead of pickles riding TCP).

Axes (any may be size 1):
  * ``data``  — batch-dimension data parallelism (the reference's only
                scaling axis: minibatch jobs to slaves),
  * ``fsdp``  — parameter sharding across data-parallel workers
                (ZeRO-style; absent in the reference, required at TPU scale),
  * ``model`` — tensor parallelism for wide layers,
  * ``seq``   — sequence/context parallelism for ring attention.

Rules are functions ``(path, spec) -> PartitionSpec`` applied over the
workflow state; GSPMD propagates everything else.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class MeshSpec:
    """Declarative mesh description; -1 = absorb remaining devices.

    Axes: data (DP), fsdp (ZeRO), model (TP), seq (ring attention),
    pipe (pipeline stages), expert (MoE banks)."""
    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    def axis_sizes(self, n_devices: int) -> Dict[str, int]:
        sizes = {"data": self.data, "fsdp": self.fsdp,
                 "model": self.model, "seq": self.seq,
                 "pipe": self.pipe, "expert": self.expert}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        wild = [k for k, v in sizes.items() if v == -1]
        if wild:
            rem = n_devices // fixed
            for k in wild[:-1]:
                sizes[k] = 1
            sizes[wild[-1]] = rem
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not tile {n_devices} devices")
        return sizes


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh; defaults to pure data parallelism over all devices.

    Axis order is (data, fsdp, model, seq): the innermost axes get
    ICI-neighbor device ranges, which is where tensor/sequence parallel
    traffic belongs (scaling-book recipe)."""
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        # root.common.mesh (default ``{"data": -1}``) is the config-tree
        # form of MeshSpec: axis name -> size, -1 absorbing the rest
        # (docs/configuration.md)
        from ..config import root
        axes = {k: int(v) for k, v in root.common.mesh.items()}
        spec = MeshSpec(**axes) if axes else MeshSpec()
    sizes = spec.axis_sizes(len(devices))
    names = ("data", "fsdp", "model", "seq", "pipe", "expert")
    arr = np.asarray(devices).reshape(*(sizes[n] for n in names))
    return Mesh(arr, names)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions: the public API landed
    after 0.4.x, where it lives at ``jax.experimental.shard_map`` with
    the replication check named ``check_rep`` instead of
    ``check_vma``.  All veles_tpu shard_map call sites route through
    here so schedule code is written against the current API only."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


# -- sharding rules ----------------------------------------------------------

Rule = Callable[[Tuple[str, ...], jax.ShapeDtypeStruct], P]


def data_parallel_rules(path, spec) -> P:
    """Replicate everything (grads psum'd by GSPMD): classic DP, the direct
    analog of the reference's master-applied weight deltas."""
    return P()


def fsdp_rules(min_size: int = 2 ** 16, axis: str = "fsdp",
               axis_size: Optional[int] = None) -> Rule:
    """Shard large parameters over the fsdp axis on their largest
    divisible dimension (ZeRO-3-ish; weights all_gather on use,
    grads reduce_scatter — all XLA-inserted).

    Pass ``axis_size`` (the mesh's fsdp extent) to skip dims that don't
    tile; without it the largest dim is chosen and state_shardings'
    divisibility guard may drop the annotation entirely."""

    def rule(path, spec) -> P:
        if math.prod(spec.shape) < min_size:
            return P()
        dims = sorted(range(len(spec.shape)),
                      key=lambda d: -spec.shape[d])
        for d in dims:
            if axis_size is not None and spec.shape[d] % axis_size != 0:
                continue
            parts: list = [None] * len(spec.shape)
            parts[d] = axis
            return P(*parts)
        return P()

    return rule


def tensor_parallel_rules(table: Dict[str, P], default: Rule = None) -> Rule:
    """Explicit per-unit PartitionSpecs, e.g. megatron-style
    ``{"fc1/w": P(None, "model"), "fc2/w": P("model", None)}``."""
    default = default or data_parallel_rules

    def rule(path, spec) -> P:
        key = "/".join(path)
        for pat, pspec in table.items():
            if key == pat or key.endswith("/" + pat):
                return pspec
        return default(path, spec)

    return rule


def compose_rules(*rules: Rule) -> Rule:
    """First rule returning a non-trivial spec wins."""

    def rule(path, spec) -> P:
        for r in rules:
            p = r(path, spec)
            if p != P():
                return p
        return P()

    return rule


def _tree_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _tree_paths(v, prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _tree_paths(v, prefix + (str(i),))
    else:
        yield prefix, tree


def state_shardings(wstate_spec, mesh: Mesh, rule: Rule = None):
    """Map a rule over the workflow-state pytree -> NamedSharding pytree.
    Scalars (step) and keys are always replicated."""
    rule = rule or data_parallel_rules

    def assign(path, spec):
        shape = getattr(spec, "shape", ())
        if len(shape) == 0:
            return NamedSharding(mesh, P())
        pspec = rule(path, spec)
        # divisibility guard: drop axes that don't tile
        parts = []
        for d, ax in enumerate(tuple(pspec) + (None,) * len(shape)):
            if d >= len(shape):
                break
            if ax is None:
                parts.append(None)
                continue
            ax_size = mesh.shape[ax] if isinstance(ax, str) else math.prod(
                mesh.shape[a] for a in ax)
            parts.append(ax if shape[d] % ax_size == 0 else None)
        while parts and parts[-1] is None:
            parts.pop()
        return NamedSharding(mesh, P(*parts))

    def walk(tree, prefix=()):
        if isinstance(tree, dict):
            return {k: walk(v, prefix + (str(k),)) for k, v in tree.items()}
        if isinstance(tree, tuple):
            return tuple(walk(v, prefix + (str(i),))
                         for i, v in enumerate(tree))
        if isinstance(tree, list):
            return [walk(v, prefix + (str(i),)) for i, v in enumerate(tree)]
        return assign(prefix, tree)

    return walk(wstate_spec)


def batch_shardings(batch_spec, mesh: Mesh, *, seq_axis: Optional[int] = None):
    """Shard every batch array on its leading (batch) axis over
    data×fsdp (fsdp workers are data-parallel too), optionally the sequence
    axis over 'seq'."""
    def assign(spec):
        shape = getattr(spec, "shape", ())
        if len(shape) == 0:
            return NamedSharding(mesh, P())
        parts: list = [None] * len(shape)
        dp = tuple(a for a in ("data", "fsdp") if mesh.shape[a] > 1)
        if dp and shape[0] % math.prod(mesh.shape[a] for a in dp) == 0:
            parts[0] = dp if len(dp) > 1 else dp[0]
        if (seq_axis is not None and len(shape) > seq_axis
                and mesh.shape["seq"] > 1
                and shape[seq_axis] % mesh.shape["seq"] == 0):
            parts[seq_axis] = "seq"
        return NamedSharding(mesh, P(*parts))

    return jax.tree.map(assign, batch_spec)
