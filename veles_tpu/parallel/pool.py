"""Host-process worker pools for meta-workflow evaluation fan-out.

Reference parity: the reference farmed GA chromosomes and ensemble members
out as standalone ``veles`` runs on slaves (reference:
veles/genetics/optimization_workflow.py:70-339,
veles/ensemble/base_workflow.py:135-143 — each evaluation exec'd a full
subprocess). The rebuild keeps exactly that semantic — one independent
training process per evaluation — but replaces the ZMQ master/slave
plumbing with a bounded local pool of CLI subprocesses (a gang spawned
through ssh can do the same across hosts via parallel/launcher.py).

Device discipline: concurrent subprocesses must not fight over one TPU
chip. ``CliRunner`` therefore pins workers to CPU by default
(``JAX_PLATFORMS=cpu``) unless the caller passes ``env`` overrides
mapping each worker to its own accelerator (e.g. one entry per host in a
gang, or TPU visible-device masks on a pod slice).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from ..logger import Logger


class CliRunner(Logger):
    """Run ``python -m veles_tpu <argv>`` jobs on up to ``n_workers``
    concurrent subprocesses; returns each job's ``--result-file`` JSON."""

    def __init__(self, n_workers: int = 1,
                 env: Optional[Dict[str, str]] = None,
                 timeout: Optional[float] = None,
                 pin_cpu: bool = True):
        self.n_workers = max(int(n_workers), 1)
        self.env = env
        self.timeout = timeout
        # pin_cpu=False: serial callers (curriculum) whose single job may
        # legitimately use the accelerator inherit the parent platform.
        self.pin_cpu = pin_cpu

    def _run_one(self, argv: Sequence[str], tag: str) -> dict:
        fd, result_path = tempfile.mkstemp(
            prefix=f"veles_job_{tag}_", suffix=".json")
        os.close(fd)
        env = dict(os.environ)
        if self.pin_cpu:
            # Pin workers to CPU even when the parent selected a
            # platform — concurrent subprocesses must never fight over
            # one TPU chip; the caller-level override channel is
            # self.env.
            env["JAX_PLATFORMS"] = "cpu"
        if self.env:
            env.update(self.env)
        cmd = [sys.executable, "-m", "veles_tpu", *argv,
               "--result-file", result_path]
        try:
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      env=env, timeout=self.timeout)
            except subprocess.TimeoutExpired:
                self.warning("job %s timed out after %.0fs", tag,
                             self.timeout)
                return {"error": f"timeout after {self.timeout}s",
                        "returncode": -1}
            if proc.returncode != 0:
                self.warning("job %s failed (rc=%d): %s", tag,
                             proc.returncode, proc.stderr[-2000:])
                return {"error": proc.stderr[-2000:],
                        "returncode": proc.returncode}
            with open(result_path) as f:
                data = f.read()
            return json.loads(data) if data.strip() else {}
        finally:
            try:
                os.unlink(result_path)
            except OSError:
                pass

    def run_jobs(self, jobs: Sequence[Sequence[str]]) -> List[dict]:
        """Execute all jobs; order of results matches order of jobs."""
        if self.n_workers == 1:
            return [self._run_one(j, str(i)) for i, j in enumerate(jobs)]
        with ThreadPoolExecutor(self.n_workers) as ex:
            futs = [ex.submit(self._run_one, j, str(i))
                    for i, j in enumerate(jobs)]
            return [f.result() for f in futs]


class ParallelMap:
    """Thread-pool map for in-process fitness callables whose heavy work
    releases the GIL or blocks on IO/subprocesses (the degenerate
    n_workers=1 case is a plain loop, keeping determinism)."""

    def __init__(self, fn, n_workers: int = 1):
        self.fn = fn
        self.n_workers = max(int(n_workers), 1)

    def __call__(self, items: Sequence) -> List:
        if self.n_workers == 1:
            return [self.fn(x) for x in items]
        with ThreadPoolExecutor(self.n_workers) as ex:
            return list(ex.map(self.fn, items))
