"""Compile a Workflow into the fused 1F1B pipeline training step.

Round-2 verdict #3 ("1F1B is not reachable from the product"): the
hand-scheduled :func:`~veles_tpu.parallel.pipeline.pipeline_train_step` was
grad-exact and memory-bounded but nothing product-facing could drive it —
``PipelineStack.apply`` always ran the GPipe schedule under workflow AD.
This module closes that gap: it maps a *whole workflow* onto the 1F1B
schedule, the Megatron-style contract where the model IS the pipeline.

Mapping (all shapes validated at compile time):

* forward units BEFORE the ``PipelineStack`` (embedding, normalizers…)
  fold into stage 0;
* the stack's S stages map one-per-device over the ``pipe`` mesh axis;
* forward units AFTER the stack (seq_last, heads…) plus the evaluator
  loss fold into stage S-1.

The folded segments change shapes (token ids -> activations -> logits),
so the schedule's three transports each carry their OWN static flat
shape/dtype (``pipeline_train_step``'s heterogeneous-buffer mode):

* the **input conveyor** carries ``(mb, in_width)`` in the input dtype
  (token ids stay int32 — no float round-trip);
* the **activation ring** carries ``(mb, act_width)`` in the activation
  dtype (bf16 stays bf16 — round 3 silently upcast to f32);
* the last stage's **logits never ride the ring**: the loss consumes
  them locally in the same step, so ring bytes are independent of the
  vocab width (round 3 padded every hop to ``max(in, act, T·V)`` —
  4·T·V bytes per hop at a real vocab regardless of the model width).

Each stage closure unflattens its true input shape, applies its units,
and re-pads; pad lanes are written as zeros each step, so no garbage
propagates, and the per-sample layout keeps the microbatch dim shardable
over data axes (dp×pp composition).  Labels/masks ride the label
conveyor the same way.  Parameters reuse the heterogeneous ravel+switch
machinery of ``pipeline.py`` unchanged.

Stochastic units (dropout) draw from ``fold_in(step_key, mb_index)`` —
the schedule threads the per-microbatch key into every stage closure and
its backward recompute, and the GPipe keyed path uses the identical
derivation, so the two schedules are grad-exact against each other.
Aux-loss units (MoE load balance) accumulate through the stage
closures' aux output across stages AND microbatches; aux gradients
enter through the schedule's aux cotangent.

No reference counterpart (the reference's only parallel axis was the
batch, SURVEY.md §2.5); the scheduling contract follows the 1F1B /
Megatron pipeline literature (PAPERS.md).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..units.base import Context, Spec


def _sample_size(shape: Sequence[int]) -> int:
    return int(math.prod(shape)) if shape else 1


def _flatten_pad(x: jax.Array, width: int) -> jax.Array:
    """(mb, *s) -> (mb, width), zero-padded per sample, dtype preserved."""
    mb = x.shape[0]
    flat = x.reshape(mb, -1)
    pad = width - flat.shape[1]
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat

def _unflatten(xf: jax.Array, shape: Sequence[int], dtype) -> jax.Array:
    """(mb, width) -> (mb, *shape) cast back to the true dtype."""
    n = _sample_size(shape)
    return xf[:, :n].reshape((xf.shape[0],) + tuple(shape)).astype(dtype)


class PipelinePlan:
    """Static compilation plan: unit partition, shapes, pack/unpack."""

    def __init__(self, wf, mesh, n_microbatches: int, *,
                 axis_name: str = "pipe"):
        from ..units.parallel_nn import PipelineStack
        from ..units.workflow import WorkflowError
        if wf.evaluator is None:
            raise WorkflowError("pipeline training needs an evaluator")
        order = [u for u in wf.topo_order()
                 if not getattr(u, "is_evaluator", False)]
        # The fused schedule streams ONE activation through the ring, so
        # the forward graph must be a linear chain @input -> ... -> loss.
        prev = "@input"
        for u in order:
            if tuple(u.inputs) != (prev,):
                raise WorkflowError(
                    f"1F1B pipeline training requires a linear unit chain; "
                    f"{u.name!r} consumes {list(u.inputs)}, expected "
                    f"[{prev!r}]")
            prev = u.name
        ev = wf.evaluator
        if ev.inputs[0] != prev:
            raise WorkflowError(
                f"evaluator must consume the last forward unit {prev!r}, "
                f"got {ev.inputs[0]!r}")
        for src in ev.inputs[1:]:
            if not src.startswith("@"):
                raise WorkflowError(
                    f"evaluator side input {src!r} must be a batch key "
                    "(it rides the label conveyor)")
        for u in order:
            # stochastic units draw per-microbatch keys and aux-loss
            # units accumulate through the stage closures' aux channel
            # (round-4 lift); only self-updating units stay out — their
            # state writes do not ride the pipeline ring
            if getattr(u, "self_updating", False):
                raise WorkflowError(
                    f"self-updating unit {u.name!r} is not supported in "
                    "the fused 1F1B step (its state updates do not ride "
                    "the pipeline ring); use the GPipe/AD path")
        stacks = [u for u in order if isinstance(u, PipelineStack)]
        if len(stacks) != 1:
            raise WorkflowError(
                f"1F1B pipeline training requires exactly one "
                f"PipelineStack unit, found {len(stacks)}")
        self.stack = stacks[0]
        S = mesh.shape[axis_name]
        if self.stack.n_stages != S:
            raise WorkflowError(
                f"PipelineStack has {self.stack.n_stages} stages but the "
                f"{axis_name!r} mesh axis is {S}")
        si = order.index(self.stack)
        self.pre: List = order[:si]
        self.post: List = order[si + 1:]
        self.evaluator = ev
        self.axis_name = axis_name
        self.S = S

        specs: Dict[str, Spec] = wf._specs
        in_spec = wf._input_specs["@input"]
        self.batch_size = int(in_spec.shape[0])
        self.n_mb = int(n_microbatches)
        if self.batch_size % self.n_mb:
            raise WorkflowError(
                f"batch {self.batch_size} not divisible into "
                f"{self.n_mb} microbatches")
        if self.n_mb % S:
            raise WorkflowError(
                f"n_microbatches={self.n_mb} must be a multiple of the "
                f"pipeline depth {S}")
        self.mb = self.batch_size // self.n_mb
        self.in_shape = tuple(in_spec.shape[1:])
        self.in_dtype = in_spec.dtype
        act_spec = specs[self.stack.inputs[0]] if self.pre else in_spec
        self.act_shape = tuple(act_spec.shape[1:])
        self.act_dtype = act_spec.dtype
        y_spec = specs[order[-1].name]
        self.y_shape = tuple(y_spec.shape[1:])
        self.y_dtype = y_spec.dtype
        # three independent transports (module doc): ring width must not
        # depend on the output/vocab width
        self.in_width = _sample_size(self.in_shape)
        self.act_width = _sample_size(self.act_shape)
        self.y_width = _sample_size(self.y_shape)
        # label conveyor layout: evaluator side inputs packed in order
        self.label_keys = tuple(ev.inputs[1:])
        self.label_shapes = []
        self.label_dtypes = []
        for k in self.label_keys:
            s = wf._input_specs[k]
            self.label_shapes.append(tuple(s.shape[1:]))
            self.label_dtypes.append(s.dtype)
        self.label_width = max(
            1, sum(_sample_size(s) for s in self.label_shapes))

    # -- packing -----------------------------------------------------------
    def pack_input(self, x: jax.Array) -> jax.Array:
        """(B, *in) -> (n_mb, mb, in_width), input dtype preserved."""
        xm = x.reshape((self.n_mb, self.mb) + self.in_shape)
        return jax.vmap(lambda b: _flatten_pad(b, self.in_width))(xm)

    def pack_labels(self, batch: Dict[str, jax.Array]) -> jax.Array:
        """Evaluator side inputs -> (n_mb, mb, label_width)."""
        parts = []
        for k in self.label_keys:
            a = batch[k].reshape(self.n_mb, self.mb, -1)
            parts.append(a.astype(jnp.float32))
        if not parts:
            return jnp.zeros((self.n_mb, self.mb, 1), jnp.float32)
        flat = jnp.concatenate(parts, axis=-1)
        pad = self.label_width - flat.shape[-1]
        if pad:
            flat = jnp.pad(flat, ((0, 0), (0, 0), (0, pad)))
        return flat

    def unpack_labels(self, lf: jax.Array) -> List[jax.Array]:
        out, off = [], 0
        for shape, dtype in zip(self.label_shapes, self.label_dtypes):
            n = _sample_size(shape)
            out.append(lf[:, off:off + n]
                       .reshape((lf.shape[0],) + tuple(shape))
                       .astype(dtype))
            off += n
        return out

    # -- stage closures ----------------------------------------------------
    @staticmethod
    def _apply_acc(u, p, x, ictx, aux):
        """One unit with aux-loss accumulation (the workflow AD path's
        aux channel, folded into the stage closure)."""
        y, st = u.apply(p.get(u.name, {}), {}, [x], ictx)
        if getattr(u, "has_aux_loss", False):
            aux = aux + u.aux_weight * st["aux_loss"]
        return y, aux

    def stage_fns(self, ctx: Context) -> List:
        """Per-stage closures in ``pipeline_train_step``'s heterogeneous-
        buffer contract: ``(p, x_in, x_ring, key) -> (ring, out, aux)``
        where ``key`` is the schedule's per-microbatch key (stochastic
        units read it through their unit ctx) and ``aux`` the stage's
        summed weighted aux losses.  ``ctx`` must carry mesh=None: the
        closures execute inside the schedule's shard_map, where a unit
        starting its own collective (ring attention) would illegally
        nest."""
        fns = []
        for i in range(self.S):
            def fn(p, x_in, x_ring, key, _i=i):
                ictx = Context(train=ctx.train, key=key, mesh=None)
                mb = x_in.shape[0]
                aux = jnp.zeros((), jnp.float32)
                if _i == 0:
                    x = _unflatten(x_in, self.in_shape, self.in_dtype)
                    for u in self.pre:
                        x, aux = self._apply_acc(u, p, x, ictx, aux)
                else:
                    x = _unflatten(x_ring, self.act_shape, self.act_dtype)
                x, a = self.stack.stage_apply_aux(
                    _i, p["__stack__"], x, ictx)
                aux = aux + a
                # transports carry the DECLARED spec dtypes: a unit that
                # internally promotes (f32 math on a bf16 stream) is cast
                # back at the stage boundary, exactly like the spec
                # contract between workflow units
                if _i == self.S - 1:
                    for u in self.post:
                        x, aux = self._apply_acc(u, p, x, ictx, aux)
                    # logits are consumed by the loss locally — the ring
                    # slot is a zeros placeholder nobody reads
                    return (jnp.zeros((mb, self.act_width),
                                      self.act_dtype),
                            _flatten_pad(x.astype(self.y_dtype),
                                         self.y_width), aux)
                return (_flatten_pad(x.astype(self.act_dtype),
                                     self.act_width),
                        jnp.zeros((mb, self.y_width), self.y_dtype), aux)
            fns.append(fn)
        return fns

    def loss_fn(self, ctx: Context):
        ev = self.evaluator

        def loss(yf, lf):
            y = _unflatten(yf, self.y_shape, self.y_dtype)
            xs = [y] + self.unpack_labels(lf)
            out, _ = ev.apply({}, {}, xs, ctx)
            return out
        return loss

    # -- parameter plumbing ------------------------------------------------
    def split_params(self, params: dict) -> List[dict]:
        out = []
        for i in range(self.S):
            d = {}
            if i == 0:
                for u in self.pre:
                    if u.name in params:
                        d[u.name] = params[u.name]
            if i == self.S - 1:
                for u in self.post:
                    if u.name in params:
                        d[u.name] = params[u.name]
            d["__stack__"] = self.stack.stage_param_slice(
                params[self.stack.name], i)
            out.append(d)
        return out

    def merge_grads(self, sgrads: List[dict], params: dict) -> dict:
        g = {self.stack.name: self.stack.restack_stage_grads(
            [sg["__stack__"] for sg in sgrads])}
        for u in self.pre:
            if u.name in params:
                g[u.name] = sgrads[0][u.name]
        for u in self.post:
            if u.name in params:
                g[u.name] = sgrads[-1][u.name]
        missing = set(params) - set(g)
        if missing:  # paramless evaluators never get here; safety net
            raise ValueError(f"grads missing for units {sorted(missing)}")
        return g


def build_pipeline_step(wf, optimizer, mesh, wstate, batch_spec, *,
                        n_microbatches: int, rule=None,
                        axis_name: str = "pipe",
                        batch_axes: Sequence[str] = ("data", "fsdp"),
                        donate: bool = True):
    """The product entry point (used by ``Workflow.make_pipeline_train_
    step``): returns ``(step_fn, state_shardings, batch_shardings)`` with
    the same call contract as ``make_sharded_train_step`` — so the Trainer
    can swap schedules with a config switch.

    Loss/grad semantics match the AD path: loss is the mean of the
    evaluator's per-microbatch losses; grads differentiate that mean
    (``pipeline.py`` rescales the 1F1B sums).  With a non-uniform @mask
    the mean-of-means differs from the global masked mean — every train
    batch must be FULL (uniform mask); the Trainer rejects loaders whose
    train count does not divide by the batch size before routing here.
    """
    from .mesh import batch_shardings, state_shardings
    from .pipeline import pipeline_train_step
    from ..units.workflow import new_state

    plan = PipelinePlan(wf, mesh, n_microbatches, axis_name=axis_name)
    # Stage closures run units with empty state; a unit that actually
    # CARRIES state (MeanDispNormalizer stats, BN...) would read missing
    # keys at trace time — reject it up front with a real error.  An
    # aux-loss channel is a per-step output, not persistent state: it
    # accumulates through the stage closures instead.
    from ..units.workflow import WorkflowError
    stateful = [u.name for u in plan.pre + [plan.stack] + plan.post
                if set(wstate["state"].get(u.name, {})) - {"aux_loss"}]
    if stateful:
        raise WorkflowError(
            f"stateful units {stateful} are not supported in the fused "
            "1F1B step (unit state does not ride the pipeline ring); "
            "use the GPipe/AD path")
    # mesh=None: see PipelinePlan.stage_fns — units must not open nested
    # collectives inside the schedule's shard_map body.
    ctx = Context(train=True, key=None, mesh=None)
    stage_fns = plan.stage_fns(ctx)
    loss_fn = plan.loss_fn(ctx)
    from .pipeline import pick_batch_axes
    baxes = pick_batch_axes(dict(mesh.shape), plan.mb,
                            candidates=batch_axes)
    state_sh = state_shardings(wstate, mesh, rule)
    batch_sh = batch_shardings(batch_spec, mesh)
    wf.mesh = mesh
    wf.state_sharding = state_sh
    n_samples = jnp.asarray(plan.batch_size, jnp.float32)
    ring_spec = jax.ShapeDtypeStruct((plan.act_width,), plan.act_dtype)

    def step(wstate, batch):
        params = wstate["params"]
        xf = plan.pack_input(batch["@input"])
        lf = plan.pack_labels(batch)
        # the SAME key split as Workflow._build_step: both schedules
        # derive per-microbatch unit keys from `sub`, so a stochastic
        # stage draws identical masks under either — the grad-exactness
        # contract (tests/test_pipeline_product.py)
        key, sub = jax.random.split(wstate["key"])
        loss, aux, sgrads = pipeline_train_step(
            stage_fns, loss_fn, plan.split_params(params), xf, lf, mesh,
            axis_name=axis_name, batch_axes=baxes, rng=sub,
            ring_spec=ring_spec, with_aux=True)
        grads = plan.merge_grads(sgrads, params)
        nparams, opt_state = optimizer.update(
            grads, wstate["opt_state"], params, wstate["step"])
        nws = new_state(nparams, wstate["state"], opt_state,
                        wstate["step"] + 1, key)
        # `loss` excludes aux (the AD path's metric contract); the
        # gradient step above includes it
        return nws, {"loss": loss, "aux": aux, "n_samples": n_samples}

    fn = jax.jit(step,
                 in_shardings=(state_sh, batch_sh),
                 out_shardings=(state_sh, None),
                 donate_argnums=(0,) if donate else ())
    return fn, state_sh, batch_sh
