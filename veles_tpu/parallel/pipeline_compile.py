"""Compile a Workflow into the fused 1F1B pipeline training step.

Round-2 verdict #3 ("1F1B is not reachable from the product"): the
hand-scheduled :func:`~veles_tpu.parallel.pipeline.pipeline_train_step` was
grad-exact and memory-bounded but nothing product-facing could drive it —
``PipelineStack.apply`` always ran the GPipe schedule under workflow AD.
This module closes that gap: it maps a *whole workflow* onto the 1F1B
schedule, the Megatron-style contract where the model IS the pipeline.

Mapping (all shapes validated at compile time):

* forward units BEFORE the ``PipelineStack`` (embedding, normalizers…)
  fold into stage 0;
* the stack's S stages map one-per-device over the ``pipe`` mesh axis;
* forward units AFTER the stack (seq_last, heads…) plus the evaluator
  loss fold into stage S-1.

The folded segments change shapes (token ids -> activations -> logits),
so the schedule's three transports each carry their OWN static flat
shape/dtype (``pipeline_train_step``'s heterogeneous-buffer mode):

* the **input conveyor** carries ``(mb, in_width)`` in the input dtype
  (token ids stay int32 — no float round-trip);
* the **activation ring** carries ``(mb, act_width)`` in the activation
  dtype (bf16 stays bf16 — round 3 silently upcast to f32);
* the last stage's **logits never ride the ring**: the loss consumes
  them locally in the same step, so ring bytes are independent of the
  vocab width (round 3 padded every hop to ``max(in, act, T·V)`` —
  4·T·V bytes per hop at a real vocab regardless of the model width).

Each stage closure unflattens its true input shape, applies its units,
and re-pads; pad lanes are written as zeros each step, so no garbage
propagates, and the per-sample layout keeps the microbatch dim shardable
over data axes (dp×pp composition).  Labels/masks ride the label
conveyor the same way.  Parameters reuse the heterogeneous ravel+switch
machinery of ``pipeline.py`` unchanged.

Stochastic units (dropout) draw from ``fold_in(step_key, mb_index)`` —
the schedule threads the per-microbatch key into every stage closure and
its backward recompute, and the GPipe keyed path uses the identical
derivation, so the two schedules are grad-exact against each other.
Aux-loss units (MoE load balance) accumulate through the stage
closures' aux output across stages AND microbatches; aux gradients
enter through the schedule's aux cotangent.

No reference counterpart (the reference's only parallel axis was the
batch, SURVEY.md §2.5); the scheduling contract follows the 1F1B /
Megatron pipeline literature (PAPERS.md).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..units.base import Context, Spec


def _sample_size(shape: Sequence[int]) -> int:
    return int(math.prod(shape)) if shape else 1


def _flatten_pad(x: jax.Array, width: int) -> jax.Array:
    """(mb, *s) -> (mb, width), zero-padded per sample, dtype preserved."""
    mb = x.shape[0]
    flat = x.reshape(mb, -1)
    pad = width - flat.shape[1]
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat

def _unflatten(xf: jax.Array, shape: Sequence[int], dtype) -> jax.Array:
    """(mb, width) -> (mb, *shape) cast back to the true dtype."""
    n = _sample_size(shape)
    return xf[:, :n].reshape((xf.shape[0],) + tuple(shape)).astype(dtype)


class PipelinePlan:
    """Static compilation plan: unit partition, shapes, pack/unpack.

    ``seq_axis``: when the mesh carries a ``seq`` axis > 1, the input
    conveyor and activation ring shard their per-sample FEATURE width
    over it (position-aligned chunks — every transported shape's leading
    dim is the sequence), stage closures see local T-shards and run ring
    attention via raw collectives (Context.manual_axes), and the loss
    slices the width-replicated labels by seq rank.  Round-4 verdict #3:
    pp×sp composed inside the memory-bounded schedule, not just under
    GPipe tape."""

    def __init__(self, wf, mesh, n_microbatches: int, *,
                 axis_name: str = "pipe", seq_axis: str = "seq",
                 interleave: int = 1):
        from ..units.parallel_nn import PipelineStack
        from ..units.workflow import WorkflowError
        if wf.evaluator is None:
            raise WorkflowError("pipeline training needs an evaluator")
        order = [u for u in wf.topo_order()
                 if not getattr(u, "is_evaluator", False)]
        # The fused schedule streams ONE activation through the ring, so
        # the forward graph must be a linear chain @input -> ... -> loss.
        prev = "@input"
        for u in order:
            if tuple(u.inputs) != (prev,):
                raise WorkflowError(
                    f"1F1B pipeline training requires a linear unit chain; "
                    f"{u.name!r} consumes {list(u.inputs)}, expected "
                    f"[{prev!r}]")
            prev = u.name
        ev = wf.evaluator
        if ev.inputs[0] != prev:
            raise WorkflowError(
                f"evaluator must consume the last forward unit {prev!r}, "
                f"got {ev.inputs[0]!r}")
        for src in ev.inputs[1:]:
            if not src.startswith("@"):
                raise WorkflowError(
                    f"evaluator side input {src!r} must be a batch key "
                    "(it rides the label conveyor)")
        for u in order:
            # stochastic units draw per-microbatch keys and aux-loss
            # units accumulate through the stage closures' aux channel
            # (round-4 lift); only self-updating units stay out — their
            # state writes do not ride the pipeline ring
            if getattr(u, "self_updating", False):
                raise WorkflowError(
                    f"self-updating unit {u.name!r} is not supported in "
                    "the fused 1F1B step (its state updates do not ride "
                    "the pipeline ring); use the GPipe/AD path")
        stacks = [u for u in order if isinstance(u, PipelineStack)]
        if len(stacks) != 1:
            raise WorkflowError(
                f"1F1B pipeline training requires exactly one "
                f"PipelineStack unit, found {len(stacks)}")
        self.stack = stacks[0]
        S = mesh.shape[axis_name]
        self.v = int(interleave)
        self.L = S * self.v
        if self.stack.n_stages != self.L:
            raise WorkflowError(
                f"PipelineStack has {self.stack.n_stages} stages but the "
                f"{axis_name!r} mesh axis is {S}"
                + (f" with interleave {self.v} (needs {self.L} stages, "
                   "one chunk lane per virtual stage)"
                   if self.v > 1 else ""))
        si = order.index(self.stack)
        self.pre: List = order[:si]
        self.post: List = order[si + 1:]
        self.evaluator = ev
        self.axis_name = axis_name
        self.S = S

        specs: Dict[str, Spec] = wf._specs
        in_spec = wf._input_specs["@input"]
        self.batch_size = int(in_spec.shape[0])
        self.n_mb = int(n_microbatches)
        if self.batch_size % self.n_mb:
            raise WorkflowError(
                f"batch {self.batch_size} not divisible into "
                f"{self.n_mb} microbatches")
        if self.n_mb % S:
            raise WorkflowError(
                f"n_microbatches={self.n_mb} must be a multiple of the "
                f"pipeline depth {S}")
        self.mb = self.batch_size // self.n_mb
        self.in_shape = tuple(in_spec.shape[1:])
        self.in_dtype = in_spec.dtype
        act_spec = specs[self.stack.inputs[0]] if self.pre else in_spec
        self.act_shape = tuple(act_spec.shape[1:])
        self.act_dtype = act_spec.dtype
        y_spec = specs[order[-1].name]
        self.y_shape = tuple(y_spec.shape[1:])
        self.y_dtype = y_spec.dtype
        # three independent transports (module doc): ring width must not
        # depend on the output/vocab width
        self.in_width = _sample_size(self.in_shape)
        self.act_width = _sample_size(self.act_shape)
        self.y_width = _sample_size(self.y_shape)
        # label conveyor layout: evaluator side inputs packed in order
        self.label_keys = tuple(ev.inputs[1:])
        self.label_shapes = []
        self.label_dtypes = []
        for k in self.label_keys:
            s = wf._input_specs[k]
            self.label_shapes.append(tuple(s.shape[1:]))
            self.label_dtypes.append(s.dtype)
        self.label_width = max(
            1, sum(_sample_size(s) for s in self.label_shapes))

        # -- sequence parallelism over the transports ---------------------
        self.seq_axis = seq_axis
        self.seq_shards = int(mesh.shape.get(seq_axis, 1))
        n_sp = self.seq_shards
        if n_sp > 1:
            for what, shape in (("input", self.in_shape),
                                ("activation", self.act_shape),
                                ("output", self.y_shape)):
                if not shape or shape[0] % n_sp:
                    raise WorkflowError(
                        f"sequence-parallel pipeline: the {what} shape "
                        f"{shape} must have a leading sequence dim "
                        f"divisible by the {seq_axis!r} axis ({n_sp})")
            from ..units.parallel_nn import MultiHeadAttention
            for u in self.pre + self.post:
                uspecs = [specs.get(s) or wf._input_specs[s]
                          for s in u.inputs]
                ospec = specs[u.name]
                t_in = uspecs[0].shape[1] if len(uspecs[0].shape) > 1 \
                    else None
                t_out = ospec.shape[1] if len(ospec.shape) > 1 else None
                if (isinstance(u, MultiHeadAttention) or t_in != t_out
                        or len(ospec.shape) < len(uspecs[0].shape)):
                    # a folded edge unit that mixes or drops positions
                    # (seq_last, flatten, attention) would silently
                    # compute on ONE rank's chunk as if it were the
                    # whole sequence
                    raise WorkflowError(
                        f"unit {u.name!r} is not positionwise; under "
                        f"sequence parallelism ({seq_axis}={n_sp}) "
                        "folded pre/post units must preserve the "
                        "sequence dim (use a per-position head, and put "
                        "attention inside the pipeline stages)")

    def _local(self, shape: Sequence[int]) -> Tuple[int, ...]:
        """Per-rank shard of a transported per-sample shape: the leading
        (sequence) dim divides over the seq axis."""
        if self.seq_shards <= 1 or not shape:
            return tuple(shape)
        return (shape[0] // self.seq_shards,) + tuple(shape[1:])

    @property
    def uniform_stages(self) -> bool:
        """True when every pipeline stage has the same structure (unit
        types/configs, names aside) — the precondition for the SHARED
        stage dispatch that in-stage collectives require (one SPMD
        program cannot diverge its collective sequence across pipe
        ranks, so ``lax.switch`` stage dispatch is off the table)."""
        cfgs = self.stack.stages_cfg
        if cfgs is None:
            return True  # legacy homogeneous stack
        def norm(stage):
            return tuple(
                tuple(sorted((k, repr(v)) for k, v in spec.items()
                             if k != "name"))
                for spec in stage)
        return len({norm(s) for s in cfgs}) == 1

    # -- packing -----------------------------------------------------------
    def pack_input(self, x: jax.Array) -> jax.Array:
        """(B, *in) -> (n_mb, mb, in_width), input dtype preserved."""
        xm = x.reshape((self.n_mb, self.mb) + self.in_shape)
        return jax.vmap(lambda b: _flatten_pad(b, self.in_width))(xm)

    def pack_labels(self, batch: Dict[str, jax.Array]) -> jax.Array:
        """Evaluator side inputs -> (n_mb, mb, label_width)."""
        parts = []
        for k in self.label_keys:
            a = batch[k].reshape(self.n_mb, self.mb, -1)
            parts.append(a.astype(jnp.float32))
        if not parts:
            return jnp.zeros((self.n_mb, self.mb, 1), jnp.float32)
        flat = jnp.concatenate(parts, axis=-1)
        pad = self.label_width - flat.shape[-1]
        if pad:
            flat = jnp.pad(flat, ((0, 0), (0, 0), (0, pad)))
        return flat

    def unpack_labels(self, lf: jax.Array) -> List[jax.Array]:
        out, off = [], 0
        for shape, dtype in zip(self.label_shapes, self.label_dtypes):
            n = _sample_size(shape)
            out.append(lf[:, off:off + n]
                       .reshape((lf.shape[0],) + tuple(shape))
                       .astype(dtype))
            off += n
        return out

    # -- stage closures ----------------------------------------------------
    @staticmethod
    def _apply_acc(u, p, x, ictx, aux, states=None):
        """One unit with aux-loss accumulation (the workflow AD path's
        aux channel, folded into the stage closure).  ``states`` carries
        READ-ONLY unit state (MeanDispNormalizer dataset statistics —
        round-4 verdict #5): the fused schedule replicates it into the
        closures but has no channel to write updates back, so a unit
        that MUTATES its state is rejected at trace time (the identity
        check below; self-updating units were rejected at plan time)."""
        st_in = (states or {}).get(u.name, {})
        y, st = u.apply(p.get(u.name, {}), st_in, [x], ictx)
        if getattr(u, "has_aux_loss", False):
            aux = aux + u.aux_weight * st["aux_loss"]
        # jax arrays are immutable, so "mutation" is rebinding a key —
        # leaf identity catches it whether the unit rebuilt the dict or
        # assigned in place (dict identity would miss the latter and
        # wrongly reject an untouched dict(state) copy)
        mutated = [k for k in set(st or {}) | set(st_in)
                   if k != "aux_loss"
                   and (st or {}).get(k) is not st_in.get(k)]
        if mutated:
            from ..units.workflow import WorkflowError
            raise WorkflowError(
                f"unit {u.name!r} mutates its state ({sorted(mutated)}) "
                "in apply(); the fused 1F1B step treats unit state as "
                "read-only statistics (no write-back channel) — use the "
                "GPipe/AD path")
        return y, aux

    def stage_fns(self, ctx: Context, states=None) -> List:
        """Per-stage closures in ``pipeline_train_step``'s heterogeneous-
        buffer contract: ``(p, x_in, x_ring, key) -> (ring, out, aux)``
        where ``key`` is the schedule's per-microbatch key (stochastic
        units read it through their unit ctx) and ``aux`` the stage's
        summed weighted aux losses.

        The closures execute inside the schedule's shard_map, where a
        unit opening its own shard_map (the ring-attention wrapper)
        would illegally nest — ``ctx.manual_axes`` names the axes the
        schedule HAS prepared for raw in-body collectives (seq when the
        transports are width-sharded, expert when microbatches shard
        over it), and units route to their manual formulations
        (``_ring_attention_local``, ``moe_apply_manual``) on those.
        ``stage_fns``/``stage_fn_shared``/``loss_fn`` are registered
        shard-map roots in ``analysis/registry.py`` for the same
        reason (veles-tpu-lint VS502).
        Under sequence parallelism all shapes here are per-rank shards;
        the per-microbatch key additionally folds in the seq rank so
        stochastic draws decorrelate across sequence chunks."""
        n_sp = self.seq_shards
        in_l = self._local(self.in_shape)
        act_l = self._local(self.act_shape)
        act_w = _sample_size(act_l)
        y_l = self._local(self.y_shape)
        y_w = _sample_size(y_l)
        fns = []
        for i in range(self.S):
            def fn(p, x_in, x_ring, key, _i=i):
                if n_sp > 1 and key is not None:
                    key = jax.random.fold_in(
                        key, jax.lax.axis_index(self.seq_axis))
                ictx = Context(train=ctx.train, key=key, mesh=ctx.mesh,
                               manual_axes=ctx.manual_axes)
                mb = x_in.shape[0]
                aux = jnp.zeros((), jnp.float32)
                if _i == 0:
                    x = _unflatten(x_in, in_l, self.in_dtype)
                    for u in self.pre:
                        x, aux = self._apply_acc(u, p, x, ictx, aux,
                                                 states)
                else:
                    x = _unflatten(x_ring, act_l, self.act_dtype)
                x, a = self.stack.stage_apply_aux(
                    _i, p["__stack__"], x, ictx)
                aux = aux + a
                # transports carry the DECLARED spec dtypes: a unit that
                # internally promotes (f32 math on a bf16 stream) is cast
                # back at the stage boundary, exactly like the spec
                # contract between workflow units
                if _i == self.S - 1:
                    for u in self.post:
                        x, aux = self._apply_acc(u, p, x, ictx, aux,
                                                 states)
                    # logits are consumed by the loss locally — the ring
                    # slot is a zeros placeholder nobody reads
                    return (jnp.zeros((mb, act_w), self.act_dtype),
                            _flatten_pad(x.astype(self.y_dtype), y_w),
                            aux)
                return (_flatten_pad(x.astype(self.act_dtype), act_w),
                        jnp.zeros((mb, y_w), self.y_dtype), aux)
            fns.append(fn)
        return fns

    def loss_fn(self, ctx: Context, *, norm=None, scale: float = 1.0):
        """Per-microbatch loss closure.

        Default (``norm=None``): the evaluator's masked MEAN over the
        local slice — exact for uniform masks, where mean-of-means
        equals the global masked mean.

        Weighted (``norm`` = the batch's total mask count, a tracer
        captured from the enclosing step trace; ``scale`` = the static
        product of the schedule's later divisions): returns
        ``sum(masked losses) * scale / norm`` so the scheduled
        sum-then-divide chain lands exactly on the GLOBAL masked mean —
        a ragged tail batch (non-uniform @mask) trains identically to
        the AD path (round-4 verdict #4).  The aux channel keeps its
        own mean semantics untouched."""
        ev = self.evaluator
        n_sp = self.seq_shards
        y_l = self._local(self.y_shape)
        t_glob = self.y_shape[0] if self.y_shape else None
        # the mask is the evaluator's third input when present
        mask_pos = 1 if len(self.label_keys) >= 2 else None

        def loss(yf, lf):
            y = _unflatten(yf, y_l, self.y_dtype)
            labels = self.unpack_labels(lf)
            if n_sp > 1:
                # labels ride the conveyor width-REPLICATED (their
                # concatenated packing does not chunk position-aligned);
                # slice each per-position part down to this rank's
                # sequence chunk here instead
                t_loc = t_glob // n_sp
                r = jax.lax.axis_index(self.seq_axis)
                labels = [
                    jax.lax.dynamic_slice_in_dim(a, r * t_loc, t_loc, 1)
                    if a.ndim >= 2 and a.shape[1] == t_glob else a
                    for a in labels]
            xs = [y] + labels
            out, _ = ev.apply({}, {}, xs, ctx)
            if norm is None or mask_pos is None:
                return out
            m = labels[mask_pos]
            # the evaluator broadcasts a per-sample mask across label
            # positions; count what its denominator counted
            cnt = jnp.sum(m.astype(jnp.float32))
            if m.ndim < labels[0].ndim:
                cnt = cnt * float(math.prod(labels[0].shape[m.ndim:]))
            # masked mean * count = masked SUM (0 when cnt == 0: the
            # CE denominator is clamped, so out is finite)
            return out * cnt * scale / norm
        return loss

    # -- parameter plumbing ------------------------------------------------
    def split_params(self, params: dict) -> List[dict]:
        out = []
        for i in range(self.S):
            d = {}
            if i == 0:
                for u in self.pre:
                    if u.name in params:
                        d[u.name] = params[u.name]
            if i == self.S - 1:
                for u in self.post:
                    if u.name in params:
                        d[u.name] = params[u.name]
            d["__stack__"] = self.stack.stage_param_slice(
                params[self.stack.name], i)
            out.append(d)
        return out

    def merge_grads(self, sgrads: List[dict], params: dict) -> dict:
        g = {self.stack.name: self.stack.restack_stage_grads(
            [sg["__stack__"] for sg in sgrads])}
        for u in self.pre:
            if u.name in params:
                g[u.name] = sgrads[0][u.name]
        for u in self.post:
            if u.name in params:
                g[u.name] = sgrads[-1][u.name]
        missing = set(params) - set(g)
        if missing:  # paramless evaluators never get here; safety net
            raise ValueError(f"grads missing for units {sorted(missing)}")
        return g

    # -- shared-dispatch mode (in-stage collectives) -----------------------
    # One SPMD program cannot diverge its collective sequence across pipe
    # ranks, so when stage bodies run collectives (ring attention over
    # 'seq', MoE all_to_all over 'expert') the lax.switch dispatch is
    # replaced by ONE stage template applied with this device's param row.
    # Preconditions enforced by build_pipeline_step: uniform_stages, and
    # no collective-bearing unit folded into the pre/post edges.  Stage
    # param dicts are relabeled POSITIONALLY (u0, u1, ...) so every row
    # ravels to the same structure, and pre/post params ride along in
    # every row (replicated content; the where-masking keeps their grads
    # nonzero only on the edge rows).

    def split_params_shared(self, params: dict) -> List[dict]:
        units = self.stack._stage_units
        out = []
        for i in range(self.L):
            sp = self.stack.stage_param_slice(params[self.stack.name], i)
            if units is not None:
                sp = {f"u{j}": sp[u.name]
                      for j, u in enumerate(units[i]) if u.name in sp}
            d = {"__stack__": sp}
            d["__pre__"] = {u.name: params[u.name] for u in self.pre
                            if u.name in params}
            d["__post__"] = {u.name: params[u.name] for u in self.post
                             if u.name in params}
            out.append(d)
        return out

    def merge_grads_shared(self, sgrads: List[dict], params: dict) -> dict:
        units = self.stack._stage_units
        stack_g = []
        for i, sg in enumerate(sgrads):
            gs = sg["__stack__"]
            if units is not None:
                gs = {u.name: gs[f"u{j}"]
                      for j, u in enumerate(units[i]) if f"u{j}" in gs}
            stack_g.append(gs)
        g = {self.stack.name: self.stack.restack_stage_grads(stack_g)}
        for u in self.pre:
            if u.name in params:
                g[u.name] = sgrads[0]["__pre__"][u.name]
        for u in self.post:
            if u.name in params:
                g[u.name] = sgrads[-1]["__post__"][u.name]
        missing = set(params) - set(g)
        if missing:
            raise ValueError(f"grads missing for units {sorted(missing)}")
        return g

    def stage_fn_shared(self, ctx: Context, states=None):
        """The single stage template ``(idx, p, x_in, x_ring, key) ->
        (ring, out, aux)``.  Every device runs the pre chain, ITS stage's
        units (stage-0 instances with this row's params — structures are
        uniform), and the post chain + head; ``jnp.where`` on the device
        index selects which results are real.  The schedule already
        computes/masks the loss this way on every device, so the edge
        compute is uniform with the existing contract; aux from the edge
        chains is masked so the cross-ring psum counts it once."""
        n_sp = self.seq_shards
        in_l = self._local(self.in_shape)
        act_l = self._local(self.act_shape)
        act_w = _sample_size(act_l)
        y_l = self._local(self.y_shape)
        y_w = _sample_size(y_l)
        last = self.L - 1   # logical: with interleave the template gets
        stack = self.stack  # the LOGICAL stage index

        def template_apply(p_stack, x, ictx):
            if stack._stage_units is None:
                return stack._stage_fn(p_stack, x), \
                    jnp.zeros((), jnp.float32)
            aux = jnp.zeros((), jnp.float32)
            for j, u in enumerate(stack._stage_units[0]):
                y, st = u.apply(p_stack.get(f"u{j}", {}), {}, [x], ictx)
                if getattr(u, "has_aux_loss", False):
                    aux = aux + u.aux_weight * st["aux_loss"]
                x = y
            return x, aux

        def fn(idx, p, x_in, x_ring, key):
            if key is not None:
                # decorrelate stochastic draws across stages: the shared
                # template reuses stage-0 unit names, so the name-hash
                # fold alone would repeat streams stage-to-stage
                key = jax.random.fold_in(key, idx)
                if n_sp > 1:
                    key = jax.random.fold_in(
                        key, jax.lax.axis_index(self.seq_axis))
            ictx = Context(train=ctx.train, key=key, mesh=ctx.mesh,
                           manual_axes=ctx.manual_axes)
            mb = x_in.shape[0]
            is_first = idx == 0
            is_last = idx == last
            aux = jnp.zeros((), jnp.float32)
            # pre chain on every device (uniform trace; garbage-in on
            # non-edge rows is masked out by the where below)
            xp = _unflatten(x_in, in_l, self.in_dtype)
            aux_pre = jnp.zeros((), jnp.float32)
            for u in self.pre:
                xp, aux_pre = self._apply_acc(
                    u, p["__pre__"], xp, ictx, aux_pre, states)
            xr = _unflatten(x_ring, act_l, self.act_dtype)
            x = jnp.where(is_first, xp.astype(self.act_dtype), xr)
            aux = aux + jnp.where(is_first, aux_pre, 0.0)
            x, a = template_apply(p["__stack__"], x, ictx)
            aux = aux + a
            ring = _flatten_pad(x.astype(self.act_dtype), act_w)
            aux_post = jnp.zeros((), jnp.float32)
            for u in self.post:
                x, aux_post = self._apply_acc(
                    u, p["__post__"], x, ictx, aux_post, states)
            aux = aux + jnp.where(is_last, aux_post, 0.0)
            out = _flatten_pad(x.astype(self.y_dtype), y_w)
            # the last stage's ring slot is a placeholder nobody reads;
            # other stages' loss input likewise (schedule masks it)
            ring = jnp.where(is_last, jnp.zeros_like(ring), ring)
            assert out.shape == (mb, y_w)
            return ring, out, aux

        return fn


def build_pipeline_step(wf, optimizer, mesh, wstate, batch_spec, *,
                        n_microbatches: int, rule=None,
                        axis_name: str = "pipe",
                        batch_axes: Sequence[str] = ("data", "fsdp"),
                        donate: bool = True, interleave: int = 1):
    """The product entry point (used by ``Workflow.make_pipeline_train_
    step``): returns ``(step_fn, state_shardings, batch_shardings)`` with
    the same call contract as ``make_sharded_train_step`` — so the Trainer
    can swap schedules with a config switch.

    Loss/grad semantics match the AD path: the GLOBAL masked mean.
    With a mask-consuming evaluator each microbatch contributes its
    masked loss SUM weighted by the schedule's static rescale chain and
    normalized by the batch's total mask count (round-4 verdict #4) —
    so a ragged tail batch (non-uniform @mask, padded rows) trains
    identically to the AD path instead of being rejected.  Without a
    mask input the loss is the mean of per-microbatch means (equal by
    construction).

    The returned program is IMMORTAL for the workflow lifetime: the
    optimizer update reads its lr (and the rollback multiplier) from
    traced state (``ops.optimizers.LR_MULT_KEY``), so Decision rollbacks
    and checkpoint restores never force this — by far the most expensive
    — compile to rerun (runtime/step_cache.py caches the AOT
    executable and logs its cost analysis).
    """
    from .mesh import batch_shardings, state_shardings
    from .pipeline import pipeline_train_step
    from ..units.workflow import new_state

    plan = PipelinePlan(wf, mesh, n_microbatches, axis_name=axis_name,
                        interleave=interleave)
    import logging
    logging.getLogger("PipelinePlan").info(
        "1F1B plan: %d stages (pipe=%d × v=%d), %d microbatches of %d, "
        "transports in=%d/act=%d/out=%d lanes, seq shards=%d",
        plan.L, plan.S, plan.v, plan.n_mb, plan.mb, plan.in_width,
        plan.act_width, plan.y_width, plan.seq_shards)
    # Unit state (MeanDispNormalizer dataset statistics) is READ-ONLY in
    # this framework's non-self-updating units — round-5 lift (round-4
    # verdict #5): the step threads wstate["state"] into the stage
    # closures as replicated constants instead of rejecting stateful
    # units.  Mutation is caught at trace time (_apply_acc's identity
    # check); self-updating units were rejected at plan time.
    from ..units.workflow import WorkflowError
    from .pipeline import pick_batch_axes
    # microbatch samples may also shard over the EXPERT axis: outside
    # MoE units that is plain data parallelism; inside them the manual
    # all_to_all dispatch redistributes tokens by expert (round-4
    # verdict #3 — Megatron-style pp×ep in the fused schedule)
    candidates = tuple(batch_axes)
    from ..units.parallel_nn import MoEFFN as _MoE
    stack_units = [u for us in (plan.stack._stage_units or [])
                   for u in us]
    has_moe = any(isinstance(u, _MoE)
                  for u in plan.pre + stack_units + plan.post)
    if has_moe and "expert" not in candidates:
        # only a MoE-bearing model gets its microbatches sharded over
        # 'expert' — an expert axis on a MoE-free mesh stays pure
        # replication, so heterogeneous-stage configs keep working
        candidates += ("expert",)
    baxes = pick_batch_axes(dict(mesh.shape), plan.mb,
                            candidates=candidates)
    # the axes stage bodies may run raw collectives over — see
    # PipelinePlan.stage_fns; everything else keeps the local
    # formulation exactly as before
    manual = ()
    if plan.seq_shards > 1:
        manual += (plan.seq_axis,)
    if "expert" in baxes:
        manual += ("expert",)
    ctx = Context(train=True, key=None, mesh=mesh, manual_axes=manual)
    # in-stage collectives AND virtual-stage interleaving both demand
    # the shared (uniform-template) dispatch
    shared = bool(manual) or plan.v > 1
    if shared:
        # In-stage collectives demand the SHARED stage dispatch (one
        # SPMD program cannot diverge its collective sequence across
        # pipe ranks — see PipelinePlan.stage_fn_shared), which in turn
        # demands uniform stage structure and collective-free edges.
        if not plan.uniform_stages:
            raise WorkflowError(
                "composing seq/expert parallelism inside the fused 1F1B "
                "schedule requires structurally IDENTICAL pipeline "
                "stages (one SPMD program cannot run different "
                "collective sequences on different pipe ranks); make "
                "every stage the same block, or drop the seq/expert "
                "mesh axes to use the heterogeneous-stage dispatch")
        from ..units.parallel_nn import MoEFFN
        for u in plan.pre + plan.post:
            if isinstance(u, MoEFFN) and "expert" in manual:
                raise WorkflowError(
                    f"MoE unit {u.name!r} cannot fold into a pipeline "
                    "edge under expert parallelism (its all_to_all "
                    "would run inside a masked edge chain); put it in "
                    "the pipeline stages")
    state_sh = state_shardings(wstate, mesh, rule)
    batch_sh = batch_shardings(batch_spec, mesh)
    wf.mesh = mesh
    wf.state_sharding = state_sh
    n_samples = jnp.asarray(plan.batch_size, jnp.float32)
    ring_spec = jax.ShapeDtypeStruct((plan.act_width,), plan.act_dtype)
    width_axes = (plan.seq_axis,) if plan.seq_shards > 1 else ()
    # mask weighting (global masked mean over ragged batches): `scale`
    # statically cancels the schedule's later divisions (/n_mb and the
    # cross-shard /bsz over batch AND width axes) so the summed weighted
    # microbatch losses land on sum(masked loss)/norm exactly
    mask_key = plan.label_keys[1] if len(plan.label_keys) >= 2 else None
    w_scale = float(plan.n_mb)
    for a in baxes + width_axes:
        if mesh.shape[a] > 1:
            w_scale *= mesh.shape[a]
    # a per-sample mask broadcasts across label positions; the global
    # count must match what the per-slice counts sum to
    pos_factor = 1.0
    if mask_key is not None and len(plan.label_shapes) >= 2:
        s_l, s_m = plan.label_shapes[0], plan.label_shapes[1]
        pos_factor = float(math.prod(s_l[len(s_m):])) if len(s_l) > \
            len(s_m) else 1.0

    # anomaly sentinel knobs, read at build time like the AD path
    # (Workflow._build_step): the guarded update skips non-finite steps
    # via a traced select, so the IMMORTAL program stays immortal even
    # while it is skipping anomalies (docs/robustness.md)
    from ..config import root as _root
    _sentinel = bool(_root.common.train.get("sentinel", True))
    _clip = float(_root.common.train.get("clip_norm", 0.0) or 0.0)
    from ..runtime.faults import get_plan as _get_plan
    _inject = _get_plan().nan_grad_at_step

    def step(wstate, batch):
        params = wstate["params"]
        # closures built inside the trace so they can capture this
        # step's tracers: the mask-count normalizer and the read-only
        # unit state (both replicate into the schedule's shard_map)
        states = wstate["state"]
        stage_fns = (plan.stage_fn_shared(ctx, states) if shared
                     else plan.stage_fns(ctx, states))
        if mask_key is not None:
            norm = jnp.maximum(
                jnp.sum(batch[mask_key].astype(jnp.float32))
                * pos_factor, 1.0)
            loss_fn = plan.loss_fn(ctx, norm=norm, scale=w_scale)
        else:
            loss_fn = plan.loss_fn(ctx)
        xf = plan.pack_input(batch["@input"])
        lf = plan.pack_labels(batch)
        # the SAME key split as Workflow._build_step: both schedules
        # derive per-microbatch unit keys from `sub`, so a stochastic
        # stage draws identical masks under either — the grad-exactness
        # contract (tests/test_pipeline_product.py)
        key, sub = jax.random.split(wstate["key"])
        split = (plan.split_params_shared if shared
                 else plan.split_params)
        loss, aux, sgrads = pipeline_train_step(
            stage_fns, loss_fn, split(params), xf, lf, mesh,
            axis_name=axis_name, batch_axes=baxes,
            width_axes=width_axes, rng=sub,
            ring_spec=ring_spec, with_aux=True, shared=shared,
            interleave=plan.v)
        merge = (plan.merge_grads_shared if shared
                 else plan.merge_grads)
        grads = merge(sgrads, params)
        from ..ops.optimizers import guarded_update
        nparams, opt_state, ok, gnorm = guarded_update(
            optimizer, grads, wstate["opt_state"], params,
            wstate["step"], loss, clip_norm=_clip, sentinel=_sentinel,
            inject_nan_steps=_inject)
        nws = new_state(nparams, wstate["state"], opt_state,
                        wstate["step"] + 1, key)
        # `loss` excludes aux (the AD path's metric contract); the
        # gradient step above includes it
        mets = {"loss": loss, "aux": aux, "n_samples": n_samples}
        if ok is not None:
            mets = {k: jnp.where(ok, v, jnp.zeros_like(v))
                    for k, v in mets.items()}
            mets["anomaly_steps"] = (~ok).astype(jnp.float32)
        if gnorm is not None:
            # gated like the AD path: a skipped step's NaN norm must
            # not poison the epoch grad_norm aggregate
            mets["grad_norm"] = gnorm if ok is None \
                else jnp.where(ok, gnorm, 0.0)
        return nws, mets

    fn = jax.jit(step,
                 in_shardings=(state_sh, batch_sh),
                 out_shardings=(state_sh, None),
                 donate_argnums=(0,) if donate else ())
    return fn, state_sh, batch_sh
