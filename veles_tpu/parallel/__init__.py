from .mesh import (MeshSpec, make_mesh, data_parallel_rules, fsdp_rules,
                   tensor_parallel_rules, batch_shardings, state_shardings,
                   compose_rules)
from .distributed import initialize_distributed, is_multihost, host_count
from .launcher import HostLauncher, launch_hosts
from .ring_attention import ring_attention, blockwise_attention
from .pipeline import (pipeline_apply, pipeline_train_step,
                       interleaved_train_step, stack_stage_params,
                       pipeline_stage_shardings)
from .moe import init_moe_params, moe_apply, moe_shardings
from .pool import CliRunner, ParallelMap
