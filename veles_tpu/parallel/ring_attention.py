"""Ring attention: sequence/context parallelism over the mesh 'seq' axis.

NOT in the reference (SURVEY.md §5.7: no attention, no sequence axis — the
reference's only scale axis was the batch). The build brief makes
long-context first-class, so this is new TPU-native design: each device in
the 'seq' ring holds a local block of Q/K/V; K/V blocks rotate around the
ring via ``jax.lax.ppermute`` over ICI while an online-softmax accumulator
(running max / denominator / output) folds in one block per step —
attention over sequences mesh['seq']× longer than one chip's HBM could
hold, with compute/communication overlap left to XLA's scheduler.

``blockwise_attention`` is the single-device analog (scan over K/V blocks,
FlashAttention-style numerics) used as the numerical reference and as the
memory-efficient local path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


from ..ops import check_attention_window as _check_window  # shared rule
from ..ops import check_gqa_heads as _check_gqa
from .mesh import shard_map


def _attn_block(q, k, v, m, l, o, *, scale, mask=None):
    """Fold one K/V block into the online-softmax accumulators.

    q: (B, Tq, H, D); k, v: (B, Tk, H, D); m, l: (B, H, Tq); o: like q
    (accumulated in f32)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard: fully-masked rows keep m=-inf; exp(-inf - -inf) -> use safe m
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - m_safe))
    p = jnp.exp(s - m_safe[..., None])
    l_new = alpha * l + jnp.sum(p, axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _finalize(l, o):
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o / denom


def blockwise_attention(q, k, v, *, block_size: int = 512,
                        causal: bool = False, scale: Optional[float] = None,
                        use_flash: Optional[bool] = None,
                        window: Optional[int] = None,
                        flash_blocks: Optional[tuple] = None):
    """Memory-efficient attention on one device: scan over K/V blocks with
    online softmax. q/k/v: (B, T, H, D) -> (B, T, H, D).

    On TPU this delegates to the hand-written Pallas kernel
    (ops/pallas_kernels.flash_attention); the jnp scan below is the
    numerical reference and the portable path.  ``window=W`` (causal
    only) restricts each query to keys in (q-W, q] — sliding-window
    local attention."""
    window = _check_window(window, causal)
    _check_gqa(q.shape[2], k.shape[2])
    if use_flash is None:
        from ..ops import use_pallas_default
        use_flash = use_pallas_default()
    if use_flash:
        # The kernel's block defaults (256x1024, swept on-chip —
        # BASELINE.md) beat any 128-capped choice; ``flash_blocks``
        # overrides them with a per-build-shape autotuned pair
        # (MultiHeadAttention.prepare).  ``block_size`` only describes
        # the jnp scan granularity below.
        from ..ops.pallas_kernels import flash_attention
        if flash_blocks is not None:
            bq, bk = flash_blocks
            return flash_attention(q, k, v, causal, scale, block_q=bq,
                                   block_k=bk, window=window)
        return flash_attention(q, k, v, causal, scale, window=window)
    # GQA on the portable path: expand kv heads (the kernel path above
    # indexes shared kv blocks instead of materializing the repeat)
    if k.shape[2] != q.shape[2]:
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    block_size = min(block_size, Tk)
    n_blocks = -(-Tk // block_size)
    pad = n_blocks * block_size - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kb = k.reshape(B, n_blocks, block_size, H, D)
    vb = v.reshape(B, n_blocks, block_size, H, D)
    q_idx = jnp.arange(Tq)

    # checkpoint: the scan otherwise saves each block's (B,H,Tq,block)
    # probability matrix for the backward pass — in total the full Tq×Tk
    # attention matrix, defeating the point. Rematerializing the block fold
    # keeps backward memory at one block.
    folded = jax.checkpoint(
        functools.partial(_attn_block, scale=scale))

    def body(carry, blk):
        m, l, o = carry
        k_blk, v_blk, blk_i = blk
        k_idx = blk_i * block_size + jnp.arange(block_size)
        mask = (k_idx < Tk)[None, None, None, :]
        if causal:
            mask = mask & (k_idx[None, None, None, :]
                           <= q_idx[None, None, :, None])
            if window is not None:
                mask = mask & (k_idx[None, None, None, :]
                               > q_idx[None, None, :, None] - window)
        m, l, o = folded(q, k_blk, v_blk, m, l, o, mask=mask)
        return (m, l, o), None

    init = (jnp.full((B, H, Tq), -jnp.inf, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32),
            jnp.zeros((B, Tq, H, D), jnp.float32))
    (m, l, o), _ = jax.lax.scan(
        body, init,
        (kb.transpose(1, 0, 2, 3, 4), vb.transpose(1, 0, 2, 3, 4),
         jnp.arange(n_blocks)))
    return _finalize(l, o).astype(q.dtype)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: Optional[float],
                          window: Optional[int] = None):
    """Per-shard body (runs under shard_map): rotate K/V around the ring.
    With GQA (fewer kv heads) the RING TRAFFIC stays kv-head sized; heads
    expand only transiently inside each fold.

    Registered in ``analysis/registry.py`` ``SHARD_MAP_ROOTS`` with
    axis environment ``("seq",)``: the raw ``ppermute``/``psum`` here
    are legal exactly because this body is shard_map-wrapped (callers:
    :func:`ring_attention`'s wrapper, and ``MultiHeadAttention.apply``
    when ``Context.manual_axes`` says a schedule already opened the
    shard_map) — veles-tpu-lint VS502 flags collectives outside such a
    registered scope."""
    axis_size = jax.lax.psum(1, axis_name)
    axis_idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    gqa = H // k.shape[2]
    scale_ = scale if scale is not None else D ** -0.5
    q_pos = axis_idx * Tq + jnp.arange(Tq)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        m, l, o, k_cur, v_cur = carry
        # K/V block currently held arrived from rank (axis_idx - step).
        src = (axis_idx - step) % axis_size
        k_pos = src * Tk + jnp.arange(Tk)
        if causal:
            mask = (k_pos[None, None, None, :]
                    <= q_pos[None, None, :, None])
            if window is not None:
                mask = mask & (k_pos[None, None, None, :]
                               > q_pos[None, None, :, None] - window)
        else:
            mask = None

        def fold(carry):
            m, l, o = carry
            k_use = jnp.repeat(k_cur, gqa, axis=2) if gqa > 1 else k_cur
            v_use = jnp.repeat(v_cur, gqa, axis=2) if gqa > 1 else v_cur
            return _attn_block(q, k_use, v_use, m, l, o,
                               scale=scale_, mask=mask)

        if causal:
            # Skip the fold when the visiting shard is entirely masked
            # for this device (after the diagonal; with a window, also
            # entirely before it) — the per-device compute becomes
            # O(T·window/P); the ring rotation itself still runs (K/V
            # must pass through to reach live devices).
            k_lo, k_hi = src * Tk, src * Tk + Tk - 1
            q_lo, q_hi = axis_idx * Tq, axis_idx * Tq + Tq - 1
            live = k_lo <= q_hi
            if window is not None:
                live &= k_hi > q_lo - window
            m, l, o = jax.lax.cond(live, fold, lambda c: c, (m, l, o))
        else:
            m, l, o = fold((m, l, o))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    init = (jnp.full((B, H, Tq), -jnp.inf, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32),
            jnp.zeros((B, Tq, H, D), jnp.float32), k, v)
    m, l, o, _, _ = jax.lax.fori_loop(0, axis_size, body, init)
    return _finalize(l, o).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "seq",
                   causal: bool = False, scale: Optional[float] = None,
                   window: Optional[int] = None):
    """Sequence-parallel attention: q/k/v (B, T, H, D) sharded on T over
    ``axis_name``; returns output with the same sharding.  ``window``
    (causal only) applies the sliding-window mask on GLOBAL positions —
    each ring step folds only the in-window part of the visiting block."""
    window = _check_window(window, causal)
    _check_gqa(q.shape[2], k.shape[2])
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def full_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None):
    """Plain O(T^2) attention — the numerical reference for the tests."""
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = jnp.arange(Tk)[None, :] <= jnp.arange(Tq)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)
