"""Multi-host process group.

Replaces the reference's Launcher master/slave mode selection + SSH slave
spawning + Twisted reactor (reference: veles/launcher.py:100,333-342,
617,808-842) with ``jax.distributed.initialize`` over DCN: one process per
host, gang-scheduled SPMD, coordinator-based failure detection. Elastic
membership (reference: slaves join/drop any time, veles/server.py:315-394)
becomes checkpoint-restart — see runtime/trainer.py + Snapshotter
(SURVEY.md §5.3 mapping).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..logger import setup_logging


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize the multi-host runtime (no-op single-host).

    Args mirror ``jax.distributed.initialize``; when None they come from the
    environment the way the reference's Launcher read ``-m master:port``
    flags (veles/launcher.py:333-342): VELES_COORDINATOR,
    VELES_NUM_PROCESSES, VELES_PROCESS_ID.
    """
    coordinator = coordinator or os.environ.get("VELES_COORDINATOR")
    if coordinator is None:
        return  # standalone
    # Leave None through to jax.distributed.initialize so it can auto-detect
    # from the cluster environment; only override from VELES_* when present.
    if num_processes is None and "VELES_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["VELES_NUM_PROCESSES"])
    if process_id is None and "VELES_PROCESS_ID" in os.environ:
        process_id = int(os.environ["VELES_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id)
    setup_logging()


def is_multihost() -> bool:
    return jax.process_count() > 1


def host_count() -> int:
    return jax.process_count()


def host_index() -> int:
    return jax.process_index()


def to_global_batch(batch, mesh, shardings):
    """Assemble each host's local batch shard into global arrays under the
    compiled step's batch ``shardings`` (the multi-host analog of the
    reference's 'loader ships index subsets to each slave',
    veles/loader/base.py:631-639: every host serves its own rows; this
    stitches them into the global SPMD batch).  The partition spec comes
    from each leaf's sharding — batches may be sharded over ('data','fsdp')
    or a seq axis, not just 'data'."""
    from jax.experimental import multihost_utils as mh

    return {k: mh.host_local_array_to_global_array(v, mesh, shardings[k].spec)
            for k, v in batch.items()}


def place_batch(batch, mesh, shardings):
    """Place one host batch under the compiled step's batch shardings —
    the single entry point the Trainer's prefetch worker thread calls, so
    the H2D transfer overlaps the previous step's compute.  Single-host:
    an async ``jax.device_put`` under the NamedShardings.  Multi-host:
    stitch this host's shard into the global SPMD batch
    (``to_global_batch`` is collective-free — purely local buffer
    assembly — hence safe off the main thread)."""
    if is_multihost():
        return to_global_batch(batch, mesh, shardings)
    return jax.device_put(batch, shardings)


def place_global_state(tree, shardings):
    """Place a host-replicated state pytree under (possibly
    non-addressable) global shardings — every host holds the same full
    values (identical seeds), and each device shard is sliced out locally.
    ``jax.device_put`` refuses non-addressable shardings; the callback form
    is the supported path (typed PRNG keys included)."""

    def put(x, sh):
        def cb(idx):
            return x[idx] if getattr(x, "ndim", 0) else x
        return jax.make_array_from_callback(
            getattr(x, "shape", ()), sh, cb)

    return jax.tree.map(put, tree, shardings)


def gather_to_host(tree):
    """Host-side numpy copy of a (possibly multi-host-sharded) state tree.
    Non-addressable leaves are all-gathered — a COLLECTIVE: every host must
    call this at the same point (the Trainer builds snapshot payloads on
    all hosts, then only host 0 writes)."""
    import numpy as np
    import jax.numpy as jnp

    def conv(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype,
                                                  jax.dtypes.prng_key):
            x = jax.random.key_data(x)
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils as mh
            x = mh.process_allgather(x, tiled=True)
        return np.asarray(jax.device_get(x))

    return jax.tree.map(conv, tree)
