"""Typed-ish configuration tree with dot-path access and overrides.

TPU-native re-design of the reference's global ``root`` Config tree
(reference: veles/config.py:60-152 — auto-vivifying attribute tree, defaults at
:178-291, ``--dump-config``, inline ``root.x.y=z`` overrides) and of the
genetics ``Range()`` tuneable markers (reference: veles/genetics/config.py:45-130
— "config doubles as the GA genome").

Differences from the reference, by design:
  * No executable-Python config files as the primary path (still supported via
    :func:`apply_config_file` for parity); dicts / JSON are first-class.
  * ``Range`` carries explicit (min, max) or choices and is discoverable by the
    genetic optimizer via :func:`collect_tuneables`.
"""

from __future__ import annotations

import json
import runpy
from typing import Any, Callable, Iterator


class Range:
    """A tuneable hyperparameter marker inside a :class:`Config`.

    Mirrors the reference's ``veles.genetics.config.Range`` (reference:
    veles/genetics/config.py:45-130): holds a current value plus the domain the
    genetic optimizer may explore.

    ``Range(0.01, 0.0001, 0.1)``  -> continuous domain [0.0001, 0.1]
    ``Range(16, 8, 256, integer=True)`` -> integer domain
    ``Range.choice("relu", ["relu", "tanh"])`` -> categorical
    """

    __slots__ = ("value", "min_value", "max_value", "choices", "integer")

    def __init__(self, value, min_value=None, max_value=None, *,
                 choices=None, integer=None):
        self.value = value
        self.min_value = min_value
        self.max_value = max_value
        self.choices = list(choices) if choices is not None else None
        if integer is None:
            integer = isinstance(value, int) and not isinstance(value, bool)
        self.integer = integer

    @classmethod
    def choice(cls, value, choices):
        return cls(value, choices=choices)

    def clip(self, v):
        if self.choices is not None:
            return v if v in self.choices else self.value
        if self.min_value is not None:
            v = max(self.min_value, v)
        if self.max_value is not None:
            v = min(self.max_value, v)
        if self.integer:
            v = int(round(v))
        return v

    def __repr__(self):
        if self.choices is not None:
            return f"Range({self.value!r}, choices={self.choices!r})"
        return f"Range({self.value!r}, {self.min_value!r}, {self.max_value!r})"


def _unwrap(v):
    return v.value if isinstance(v, Range) else v


class Config:
    """Auto-vivifying attribute tree (reference: veles/config.py:60-152).

    ``cfg.loader.minibatch_size = 100`` creates intermediate nodes on demand.
    Reading an attribute that does not exist also auto-vivifies (matching the
    reference's behavior where reading returns a fresh Config node), so use
    :meth:`get` / ``in`` checks when existence matters.
    """

    def __init__(self, path="", **kwargs):
        object.__setattr__(self, "_path", path)
        object.__setattr__(self, "_items", {})
        self.update(kwargs)

    # -- attribute protocol ------------------------------------------------
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        items = object.__getattribute__(self, "_items")
        if name not in items:
            child_path = f"{self._path}.{name}" if self._path else name
            items[name] = Config(child_path)
        return items[name]

    def __setattr__(self, name: str, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        self._items[name] = self._coerce(name, value)

    def __delattr__(self, name):
        self._items.pop(name, None)

    def _coerce(self, name, value):
        if isinstance(value, dict):
            child_path = f"{self._path}.{name}" if self._path else name
            node = Config(child_path)
            node.update(value)
            return node
        return value

    # -- mapping-ish protocol ----------------------------------------------
    def __contains__(self, name: str) -> bool:
        return name in self._items

    def __iter__(self) -> Iterator[str]:
        return iter(self._items)

    def items(self):
        return self._items.items()

    def keys(self):
        return self._items.keys()

    def get(self, name: str, default=None):
        v = self._items.get(name, default)
        return _unwrap(v) if isinstance(v, Range) else v

    def __getitem__(self, name):
        return getattr(self, name)

    def __setitem__(self, name, value):
        setattr(self, name, value)

    # -- bulk ops ----------------------------------------------------------
    def update(self, tree: dict) -> "Config":
        """Deep-merge a nested dict (reference: veles/config.py:100-117)."""
        for k, v in tree.items():
            if isinstance(v, dict) and isinstance(self._items.get(k), Config):
                self._items[k].update(v)
            else:
                setattr(self, k, v)
        return self

    def set_path(self, dotted: str, value):
        """``cfg.set_path("loader.minibatch_size", 64)``."""
        parts = dotted.split(".")
        node = self
        for p in parts[:-1]:
            node = getattr(node, p)
        setattr(node, parts[-1], value)

    def get_path(self, dotted: str, default=None):
        node = self
        for p in dotted.split("."):
            if not isinstance(node, Config) or p not in node:
                return default
            node = node._items[p]
        return _unwrap(node)

    def to_dict(self, unwrap_ranges: bool = True) -> dict:
        out = {}
        for k, v in self._items.items():
            if isinstance(v, Config):
                out[k] = v.to_dict(unwrap_ranges)
            elif isinstance(v, Range):
                out[k] = v.value if unwrap_ranges else v
            else:
                out[k] = v
        return out

    def value(self, name: str, default=None):
        """Fetch a leaf, unwrapping Range tuneables."""
        if name not in self._items:
            return default
        return _unwrap(self._items[name])

    def dump(self) -> str:
        """``--dump-config`` parity (reference: veles/__main__.py)."""
        return json.dumps(self.to_dict(), indent=2, default=repr, sort_keys=True)

    def __repr__(self):
        return f"Config({self._path or 'root'}: {self.to_dict()!r})"

    def __bool__(self):
        return bool(self._items)


def collect_tuneables(cfg: Config, prefix: str = "") -> dict:
    """Walk the tree, returning ``{dot.path: Range}`` for every tuneable.

    This is what makes "config is the GA genome" work (reference:
    veles/genetics/config.py:45-223).
    """
    found = {}
    for k, v in cfg.items():
        path = f"{prefix}.{k}" if prefix else k
        if isinstance(v, Config):
            found.update(collect_tuneables(v, path))
        elif isinstance(v, Range):
            found[path] = v
    return found


def apply_overrides(cfg: Config, overrides: list[str]) -> None:
    """Apply ``path=value`` strings (CLI ``root.x.y=z`` parity,
    reference: veles/__main__.py:474-481). Values parsed as JSON, falling
    back to raw string."""
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override must be path=value, got {ov!r}")
        path, _, raw = ov.partition("=")
        try:
            value = json.loads(raw)
        except (json.JSONDecodeError, ValueError):
            value = raw
        cfg.set_path(path.strip(), value)


def apply_config_file(cfg: Config, filename: str) -> None:
    """Load a config file into ``cfg``.

    ``.json`` files deep-merge; ``.py`` files are executed with ``root`` bound
    to ``cfg`` (reference parity: user configs are executed Python mutating the
    global root, veles/__main__.py:426-472).
    """
    if filename.endswith(".json"):
        with open(filename) as f:
            cfg.update(json.load(f))
    else:
        runpy.run_path(filename, init_globals={"root": cfg})


#: The global config tree, like the reference's ``veles.config.root``.
root = Config()


def _defaults():
    # NOTE: the reference's precision_type (host dtype) and a global
    # compute_dtype used to be declared here but nothing read them —
    # the on-device dtype is a per-unit/model knob (``compute_dtype=``
    # on units and StandardWorkflow layer specs).  veles_tpu.analysis
    # VK302 keeps this file honest about such drift.
    root.common.precision_level = 0          # 0 fast | 1 high | 2 highest (ref PRECISION_LEVEL)
    root.common.timings = False
    root.common.trace_file = ""              # JSONL event trace target
    root.common.cache_dir = ".veles_tpu"
    root.common.autotune = True              # measured per-device op picks
    root.common.snapshot_dir = "snapshots"
    # Persistent XLA compilation cache directory ("" = disabled): set via
    # --compile-cache or root.common.compile_cache=DIR overrides; see
    # runtime/step_cache.py and docs/compile_cache.md. Programs whose
    # backend compile is faster than compile_cache_min_compile_secs are
    # not persisted (0 = persist everything).
    root.common.compile_cache = ""
    root.common.compile_cache_min_compile_secs = 0.0
    # Upper bound (MiB) on the tensors blob compare_snapshots /
    # Snapshotter.load will download from an http(s):// snapshot URI.
    root.common.snapshot_http_max_mb = 2048
    # Snapshot retention: keep only the newest K manifests+blobs per
    # prefix (0 = keep everything).  The _current/_best symlink targets
    # are never collected (docs/robustness.md).
    root.common.snapshot_keep = 0
    # Training fault tolerance (runtime/trainer.py + docs/robustness.md).
    root.common.train.sentinel = True       # in-graph non-finite guard
    root.common.train.clip_norm = 0.0       # global grad-norm clip (0=off)
    root.common.train.anomaly_patience = 0  # consecutive bad steps before
    #                                         rollback escalation (0=never)
    # Loader transient-read retry (loader/base.py; the Veles
    # failed-minibatch-requeue analog).
    root.common.loader.retries = 2          # attempts beyond the first
    root.common.loader.retry_backoff_s = 0.05  # first retry delay (doubles)
    # Transient HTTP retry (forge/client.py, Snapshotter http loads;
    # backoff shape shared with the deploy watcher, runtime/deploy.py).
    root.common.net.http_retries = 3
    # Observability (runtime/metrics.py + runtime/status.py,
    # docs/observability.md "Metrics & tracing").
    root.common.observe.label_cap = 64       # label series per metric;
    #                                          beyond -> the _other series
    root.common.observe.span_ring = 512      # request/step spans kept for
    #                                          GET /trace.json / --trace-out
    root.common.observe.status_flush_s = 0.25  # min interval between
    #                                            status.json event flushes
    # Deep performance observability (docs/observability.md: memory
    # ledger, goodput/MFU, rolling SLO windows, profiler endpoint).
    root.common.observe.peak_tflops = 0.0    # measured peak for MFU; 0 =
    #                                          use runtime/benchmark.py's
    #                                          cached GEMM calibration
    root.common.observe.peak_hbm_gbps = 0.0  # HBM bandwidth peak for the
    #                                          decode MBU gauge (0 = MBU
    #                                          reported as 0 / unknown)
    root.common.observe.memory_poll_s = 2.0  # device memory_stats() poll
    #                                          period (0 = no poller)
    root.common.observe.slo.window_s = 60.0  # rolling SLO window length
    root.common.observe.slo.slices = 12      # bucket-snapshot ring slices
    root.common.observe.slo.ttft_p99_ms = 0.0       # p99 TTFT target
    #                                                 (0 = no target)
    root.common.observe.slo.queue_wait_p99_ms = 0.0  # p99 queue-wait
    #                                                  target (0 = none)
    root.common.observe.slo.burn_threshold = 2.0  # burn rate at/above
    #                                               which the SLO "burns"
    root.common.observe.slo.degrade_ready = False  # /ready 503s on
    #                                                sustained burn
    root.common.observe.profile_dir = ""     # POST /debug/profile capture
    #                                          dir ("" = cache_dir/profiles)
    root.common.observe.profile_max_s = 30.0  # per-capture duration cap
    root.common.random_seed = 42
    root.common.platform = ""                # "" = let JAX pick
    root.common.mesh = dict(data=-1)          # -1: all remaining devices
    # Serving knobs (runtime/engine.py + runtime/restful.py, docs/serving.md).
    root.common.serve.slots = 8              # decode slots (engine batch)
    root.common.serve.l_max = 512            # per-slot KV length cap
    root.common.serve.prefill_bucket_min = 16  # smallest pow2 prompt bucket
    # Paged KV cache + shared-prefix reuse (docs/serving.md "Paged KV
    # cache"): the pool, not slots*l_max, is the real token capacity.
    root.common.serve.paged = True           # page-pool KV layout
    root.common.serve.page_size = 16         # tokens per page (divides
    #                                          l_max; halves itself if not)
    root.common.serve.pages = None           # pool size; None = the
    #                                          dense-equivalent slots*l_max
    # Fused Pallas paged-attention decode kernel (docs/serving.md
    # "Paged KV cache"): gathers K/V pages inside the kernel instead of
    # materializing the flat pool[ptab] view.  BOUNDED-ERROR vs the
    # bitwise gather path (online softmax reorders the summation), so
    # it is opt-in and requires serve.paged.
    root.common.serve.paged_kernel = False
    # Speculative decoding (docs/serving.md "Speculative decoding"):
    # a host-side prompt-lookup drafter proposes up to spec.k tokens
    # per slot and ONE verify program (the third program kind) scores
    # all k+1 positions per call; emitted tokens stay bitwise the
    # non-speculative engine's.
    root.common.serve.spec.enabled = False   # speculative decode on/off
    root.common.serve.spec.k = 4             # draft tokens per verify
    root.common.serve.spec.drafter = "ngram"  # host drafter (prompt
    #                                           lookup; no second model)
    # Megastep decode (docs/serving.md "Megastep decode"): fuse N decode
    # micro-steps into ONE compiled dispatch (the fourth program kind),
    # amortizing the host scheduler pass to once per N tokens.  Engaged
    # only when every slot is busy and nothing is pending (admission,
    # chunked prefill, a speculative draft) — otherwise the engine runs
    # plain N=1 steps so interactive latency never waits on a fused
    # block.  Emitted tokens stay bitwise the N=1 engine's.
    root.common.serve.megastep = 1           # micro-steps per dispatch
    #                                          (1 = off)
    root.common.serve.window_ms = 2.0        # admission batching window
    root.common.serve.queue_depth = 64       # pending requests before 429
    # Overload survival (docs/serving.md "Overload survival"): chunked
    # prefill bounds how long one prompt can monopolize the scheduler,
    # priority classes give queue-jump + preemption, and the adaptive
    # admission controller resizes the admitted queue window off the
    # SLO burn rate instead of only flipping /ready.
    root.common.serve.prefill_chunk = 256    # split prefills longer than
    #                                          this into bucket-sized
    #                                          slices interleaved with
    #                                          decode steps (0 = off)
    root.common.serve.priorities = 3         # request classes (0 = the
    #                                          highest; default class 0)
    root.common.serve.preempt = True         # a higher-class arrival may
    #                                          retire-and-requeue the
    #                                          lowest-class youngest slot
    root.common.serve.admission.enabled = True  # SLO-driven admission
    #                                             window (no-op while no
    #                                             slo target is set)
    root.common.serve.admission.min_window = 2  # floor the window never
    #                                             shrinks below
    root.common.serve.admission.interval_s = 0.25  # controller eval step
    root.common.serve.admission.hold_s = 2.0  # burn must stay recovered
    #                                           this long before regrowth
    root.common.serve.admission.decrease = 0.5  # multiplicative shrink
    #                                             while burn >= threshold
    root.common.serve.admission.increase = 1.5  # multiplicative regrowth
    #                                             once recovery held
    # Fleet serving (runtime/fleet.py, docs/serving.md "Fleet
    # serving"): a lightweight router fronting N replica serving
    # stacks — load + prefix-affinity dispatch, coordinated hot swap,
    # rolling drain, replica ejection with resubmission.
    root.common.serve.fleet.replicas = 0     # CLI --fleet N (0 = single
    #                                          -replica serving, no router)
    root.common.serve.fleet.scrape_interval_s = 0.5  # replica load/
    #                                                  health poll period
    root.common.serve.fleet.hysteresis = 0.5  # load-score margin a rival
    #                                           replica must win by before
    #                                           routing switches (stale
    #                                           scrapes must not flap it)
    root.common.serve.fleet.affinity_pages = 4  # prompt-head pages hashed
    #                                             for prefix affinity
    root.common.serve.fleet.affinity_max = 4096  # prefix->replica map
    #                                              entries kept (LRU)
    root.common.serve.fleet.eject_failures = 2  # consecutive scrape/
    #                                             health failures before a
    #                                             replica is ejected
    root.common.serve.fleet.drain_poll_s = 0.05  # rolling-drain idle-
    #                                              check cadence
    root.common.serve.fleet.restart_timeout_s = 120.0  # rolling drain:
    #                                                    replica must be
    #                                                    /ready again
    #                                                    within this
    root.common.serve.fleet.role = "mixed"   # capacity class replicas
    #                                          join with (mixed | prefill
    #                                          | decode) unless add_replica
    #                                          / --join names one
    # Disaggregated prefill/decode (runtime/fleet.py + engine
    # export_pages/import_pages, docs/serving.md "Disaggregated
    # prefill/decode"): serialized KV-page transfer between replicas.
    root.common.serve.kv_transfer.enabled = True  # router-initiated
    #                                               page transfers
    root.common.serve.kv_transfer.min_pages = 2  # smallest prefix (full
    #                                              pages) worth shipping
    root.common.serve.kv_transfer.timeout_s = 5.0  # per-leg transfer
    #                                                HTTP deadline
    root.common.serve.kv_transfer.prewarm_pages = 64  # top-K hottest
    #                                                   pages the rolling
    #                                                   drain pushes to
    #                                                   the successor
    # Batch job lane (runtime/jobs.py, docs/serving.md "Batch lane"):
    # durable bulk-inference jobs riding the trough-filler class below
    # every interactive priority.
    root.common.serve.jobs.dir = ""          # durable job store root
    #                                          ("" = job API off)
    root.common.serve.jobs.workers = 2       # manager dispatch threads
    root.common.serve.jobs.min_headroom_slots = 1  # idle admissible slots
    #                                                required before batch
    #                                                enters (trough gate)
    root.common.serve.jobs.burn_ceiling = 1.0  # max SLO burn rate the
    #                                            trough gate admits under
    #                                            (interactive sheds at
    #                                            admission.burn_threshold)
    root.common.serve.jobs.trough_retry_s = 0.05  # Retry-After hint on a
    #                                               trough-closed 429 —
    #                                               sub-second because the
    #                                               trough reopens at slot
    #                                               granularity, unlike the
    #                                               >=1s interactive hint
    root.common.serve.jobs.retry_s = 0.25    # base backoff after a batch
    #                                          429 (Retry-After overrides
    #                                          upward)
    root.common.serve.jobs.max_prompts = 100000  # per-job prompt cap
    root.common.serve.jobs.page_limit = 256  # GET /jobs/<id>/results
    #                                          default page size
    # Streaming + mid-stream failover (docs/serving.md "Streaming and
    # mid-stream failover"): incremental token frames with the router
    # resuming an interrupted stream from its last delivered token.
    root.common.serve.stream.buffer_tokens = 4096  # undrained frames a
    #                                                consumer may leave
    #                                                buffered before its
    #                                                stream closes with
    #                                                an overflow error
    root.common.serve.stream.retry_budget = 3  # mid-stream failover
    #                                            resubmissions per
    #                                            request before the
    #                                            router gives up with an
    #                                            error terminal frame
    root.common.serve.stream.backoff_s = 0.05  # base sleep before a
    #                                            mid-stream resubmission
    #                                            (doubles per attempt)
    root.common.serve.stream.backoff_max_s = 2.0  # backoff growth cap —
    #                                               bounds a failover
    #                                               storm's dispatch rate
    root.common.serve.deadline_s = 120.0     # default per-request deadline
    root.common.serve.runner_cache = 32      # generate() compiled-runner LRU
    root.common.serve.max_body_mb = 64       # POST body cap -> 413
    # Model lifecycle control plane (runtime/deploy.py, docs/serving.md).
    root.common.serve.model_dir = ""         # registry/watcher snapshot dir
    root.common.serve.swap_timeout_s = 60.0  # step-boundary flip deadline
    root.common.serve.drain_timeout_s = 30.0  # graceful-drain deadline
    root.common.serve.drain_grace_s = 2.0    # min /ready-503 hold on drain
    root.common.serve.watch_interval_s = 5.0  # snapshot watcher poll period
    root.common.serve.watch_backoff_max_s = 300.0  # watcher retry ceiling
    # Experiment manager (experiments/, docs/experiments.md): the
    # autonomous train -> select -> hot-swap loop.
    root.common.experiment.dir = ""          # durable experiment store
    #                                          root ("" = API off)
    root.common.experiment.generations = 4   # default search generations
    root.common.experiment.population = 8    # default trials/generation
    root.common.experiment.workers = 1       # >1 + cli_argv: parallel
    #                                          trial subprocess pool
    root.common.experiment.promote_margin = 0.0  # score improvement over
    #                                              the baseline a winner
    #                                              must exceed to swap
    root.common.experiment.eval_steps = 8    # decode steps per eval
    #                                          prompt in the scoring sweep
    root.common.experiment.eval_timeout_s = 300.0  # batch-lane sweep
    #                                                wait deadline


_defaults()
