from .base import (Avatar, Context, Forward, InputJoiner, LambdaUnit, Spec,
                   TrivialUnit, Unit, UnitRegistry)
from .nn import (All2All, All2AllRELU, All2AllSincos, All2AllSoftmax,
                 All2AllTanh, AvgPooling, Conv, ConvRELU, ConvTanh, Deconv,
                 Depool, Dropout, Evaluator, EvaluatorMSE, EvaluatorSoftmax,
                 Embedding, Flatten, LayerNorm, LRN, MaxPooling,
                 MeanDispNormalizer,
                 Reshape, SeqLast,
                 StochasticAbsPooling)
from .parallel_nn import (MoEFFN, MultiHeadAttention, PipelineStack,
                          expert_rules, pipeline_rules)
from .kohonen import KohonenForward
from .recurrent import GRU, LSTM, RNN
from .rbm import RBM
from .workflow import Workflow, WorkflowError
