"""Workflow: the unit container + compiled step functions.

TPU-native re-design of the reference Workflow/scheduler (reference:
veles/workflow.py:87 — ordered unit set, dependency-ordered initialize
:303-349, run-by-gate-propagation :351-369; hot loop veles/units.py:782-803).

THE core architectural change of the rebuild: instead of a thread pool
propagating "gate open" notifications between live unit objects, the unit DAG
is topologically sorted once and traced into **two compiled XLA programs** —
``train_step`` (forward + backward + optimizer update, one fused program the
MXU pipeline never leaves) and ``eval_step``. The reference's data-dependent
gating (Decision blocking gradient units during validation,
SURVEY.md §7 "hard parts") maps exactly onto this train/eval phase split.

What survives from the reference design:
  * the Workflow as an inspectable container of named units,
  * wiring checks at build time (replacing ``demand()``'s runtime None
    checks, veles/units.py:682),
  * ``gather_results`` metric aggregation (veles/workflow.py:827-849),
  * graph export for visualization (DOT; veles/workflow.py:628),
  * checksum identifying the workflow for distributed handshakes
    (veles/workflow.py:851).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..logger import Logger, TraceContext
from ..ops.optimizers import Optimizer, guarded_update, tree_select
from .base import Context, Spec, Unit


class WorkflowError(Exception):
    pass


def new_state(params, state, opt_state, step, key):
    """The workflow state pytree: everything that is sharded, donated and
    checkpointed. Replaces the reference's pickled live-object graph
    (veles/snapshotter.py:387-409 pickled the whole Workflow)."""
    return {"params": params, "state": state, "opt_state": opt_state,
            "step": step, "key": key}


class Workflow(Logger):
    """Container + compiler for a unit DAG.

    Usage::

        wf = Workflow("mnist")
        h = wf.add(All2AllTanh(100, name="fc1", inputs=("@input",)))
        o = wf.add(All2AllSoftmax(10, name="fc2", inputs=("fc1",)))
        wf.add(EvaluatorSoftmax(name="ev", inputs=("fc2", "@labels")))
        wf.build({"@input": Spec((B, 784), f32), "@labels": Spec((B,), i32)})
        opt = SGD(0.1)
        wstate = wf.init_state(jax.random.key(0), opt)
        train = wf.make_train_step(opt)
        wstate, metrics = train(wstate, batch)
    """

    def __init__(self, name: str = "Workflow"):
        self.name = name
        self.units: List[Unit] = []
        self._by_name: Dict[str, Unit] = {}
        self._order: Optional[List[Unit]] = None
        self._specs: Dict[str, Spec] = {}
        self._input_specs: Dict[str, Spec] = {}
        self.evaluator: Optional[Unit] = None
        self.mesh = None
        self.state_sharding = None

    # -- construction ------------------------------------------------------
    def add(self, unit: Unit) -> Unit:
        if unit.name in self._by_name:
            raise WorkflowError(f"duplicate unit name {unit.name!r}")
        self.units.append(unit)
        self._by_name[unit.name] = unit
        self._order = None
        if getattr(unit, "is_evaluator", False):
            self.evaluator = unit
        return unit

    def __getitem__(self, name: str) -> Unit:
        return self._by_name[name]

    def __contains__(self, name):
        return name in self._by_name

    def topo_order(self) -> List[Unit]:
        """Topological order over data edges. Build-time cycle/wiring check
        (replaces runtime gate deadlock debugging in the reference)."""
        if self._order is not None:
            return self._order
        order, seen, visiting = [], set(), set()

        def visit(u: Unit):
            if u.name in seen:
                return
            if u.name in visiting:
                raise WorkflowError(f"cycle through unit {u.name!r}")
            visiting.add(u.name)
            for src in u.inputs:
                if src.startswith("@"):
                    continue
                if src not in self._by_name:
                    raise WorkflowError(
                        f"unit {u.name!r} consumes unknown source {src!r}")
                visit(self._by_name[src])
            visiting.discard(u.name)
            seen.add(u.name)
            order.append(u)

        for u in self.units:
            visit(u)
        self._order = order
        return order

    def build(self, input_specs: Dict[str, Spec]) -> Dict[str, Spec]:
        """Infer output specs in topo order; validates all wiring."""
        self._input_specs = dict(input_specs)
        specs = dict(input_specs)
        for u in self.topo_order():
            in_specs = []
            for src in u.inputs:
                if src not in specs:
                    raise WorkflowError(
                        f"unit {u.name!r} needs {src!r} which is neither a "
                        f"batch key nor an upstream unit output")
                in_specs.append(specs[src])
            u.prepare(in_specs)
            specs[u.name] = u.output_spec(in_specs)
        self._specs = specs
        return specs

    # -- state -------------------------------------------------------------
    def init_state(self, key: jax.Array,
                   optimizer: Optional[Optimizer] = None) -> dict:
        if not self._specs:
            raise WorkflowError("call build() before init_state()")
        params, state = {}, {}
        keys = jax.random.split(key, len(self.topo_order()) + 1)
        for u, k in zip(self.topo_order(), keys[:-1]):
            in_specs = [self._specs[s] for s in u.inputs]
            p, s = u.init(k, in_specs)
            if p:
                params[u.name] = p
            if s:
                state[u.name] = s
        opt_state = optimizer.init(params) if optimizer is not None else {}
        return new_state(params, state, opt_state,
                         jnp.zeros((), jnp.int32), keys[-1])

    # -- tracing -----------------------------------------------------------
    def forward(self, params, state, batch: Dict[str, jax.Array],
                ctx: Context, *, only: Optional[set] = None
                ) -> Tuple[Dict[str, jax.Array], dict]:
        """Pure forward over the DAG; returns (all outputs, new unit state).
        This is the reference's hot loop (veles/units.py:782-803) as a trace.
        ``only`` restricts execution to a subset of unit names (ancestors of
        a prediction target, so inference needs no labels)."""
        outputs = dict(batch)
        nstate = {}
        for u in self.topo_order():
            if only is not None and u.name not in only:
                continue
            xs = [outputs[s] for s in u.inputs]
            up = params.get(u.name, {})
            us = state.get(u.name, {})
            if getattr(u, "remat", False) and ctx.train:
                # activation rematerialization: recompute this unit's
                # internals in the backward instead of taping them —
                # jax.checkpoint over the unit apply (build brief: trade
                # FLOPs for HBM). Stochastic units are safe: the ctx key
                # is a closed-over tracer, so the recompute draws the
                # SAME mask.
                y, ns = jax.checkpoint(
                    lambda p, s, *xs, _u=u: _u.apply(p, s, list(xs),
                                                     ctx))(up, us, *xs)
            else:
                y, ns = u.apply(up, us, xs, ctx)
            outputs[u.name] = y
            # lint: disable=VT101 dict emptiness is static structure at
            # trace time (sparse nstate, not a value-dependent branch)
            if ns:
                nstate[u.name] = ns
        return outputs, nstate

    def ancestors(self, name: str) -> set:
        """Unit names needed to compute ``name`` (inclusive)."""
        need, stack = set(), [name]
        while stack:
            n = stack.pop()
            if n in need or n.startswith("@"):
                continue
            need.add(n)
            stack.extend(self._by_name[n].inputs)
        return need

    def _metrics(self, params, state, outputs, ctx) -> Dict[str, jax.Array]:
        if self.evaluator is None:
            return {}
        ev = self.evaluator
        xs = [outputs[s] for s in ev.inputs]
        return ev.metrics(params.get(ev.name, {}), state.get(ev.name, {}),
                          xs, ctx)

    # -- compiled steps ----------------------------------------------------
    def _build_step(self, optimizer: Optimizer) -> Callable:
        """The pure (wstate, batch) -> (wstate, metrics) train function.

        Carries the in-graph anomaly sentinel (``ops.optimizers.
        guarded_update``): a non-finite loss or gradient norm skips the
        whole update via a traced select — params, optimizer slots and
        unit state carry through unchanged, the skip counters in
        opt_state advance, and the step's metrics zero out so epoch
        aggregates stay finite.  All of it is data flow inside the one
        compiled program: no host sync per step, no recompile on a bad
        step (docs/robustness.md)."""
        selfupd = [u for u in self.units if getattr(u, "self_updating", False)]

        aux_units = [u for u in self.units
                     if getattr(u, "has_aux_loss", False)]

        # trace-time knobs: flipping them re-traces (a new build), so a
        # running program's behavior never changes under its feet
        from ..config import root
        sentinel = bool(root.common.train.get("sentinel", True))
        clip = float(root.common.train.get("clip_norm", 0.0) or 0.0)
        from ..runtime.faults import get_plan  # late: avoids import cycle
        inject = get_plan().nan_grad_at_step

        def step(wstate, batch):
            key, sub = jax.random.split(wstate["key"])
            ctx = Context(train=True, key=sub, mesh=self.mesh)

            if self.evaluator is not None:
                def loss_fn(params):
                    outputs, nstate = self.forward(
                        params, wstate["state"], batch, ctx)
                    loss = outputs[self.evaluator.name]
                    mets = self._metrics(params, wstate["state"], outputs, ctx)
                    # auxiliary losses (e.g. MoE load balance) ride the
                    # unit-state channel and are summed into the training
                    # loss with per-unit weights
                    for u in aux_units:
                        aux = nstate[u.name]["aux_loss"]
                        loss = loss + u.aux_weight * aux
                        mets = {**mets, f"aux_{u.name}": aux}
                    return loss, (outputs, nstate, mets)

                grads, (outputs, nstate, mets) = jax.grad(
                    loss_fn, has_aux=True)(wstate["params"])
                params, opt_state, ok, gnorm = guarded_update(
                    optimizer, grads, wstate["opt_state"],
                    wstate["params"], wstate["step"],
                    outputs[self.evaluator.name], clip_norm=clip,
                    sentinel=sentinel, inject_nan_steps=inject)
                if ok is not None:
                    # a skipped step contributes nothing to the epoch
                    # aggregates (its loss/n_samples would be NaN or
                    # meaningless) and one tick to the anomaly count
                    mets = {k: jnp.where(ok, v, jnp.zeros_like(v))
                            for k, v in mets.items()}
                    mets["anomaly_steps"] = (~ok).astype(jnp.float32)
                if gnorm is not None:
                    # gated too: a skipped step's NaN norm must not
                    # poison the epoch grad_norm aggregate
                    mets["grad_norm"] = gnorm if ok is None \
                        else jnp.where(ok, gnorm, 0.0)
            else:  # pure self-organizing workflows (SOM etc.)
                outputs, nstate = self.forward(
                    wstate["params"], wstate["state"], batch, ctx)
                mets = {}
                params, opt_state = wstate["params"], wstate["opt_state"]
                ok = None

            state = {**wstate["state"], **nstate}
            for u in selfupd:
                xs = [outputs[s] for s in u.inputs]
                state[u.name] = u.update_state(
                    params.get(u.name, {}), state.get(u.name, {}), xs, ctx)
            if ok is not None:
                # unit state (normalizer stats, recurrent carries, aux
                # accumulators) also freezes on an anomalous step — the
                # skip must be a complete no-op on the training state
                state = {k: (tree_select(ok, v, wstate["state"][k])
                             if k in wstate["state"] else v)
                         for k, v in state.items()}

            nws = new_state(params, state, opt_state,
                            wstate["step"] + 1, key)
            return nws, mets

        return step

    def make_train_step(self, optimizer: Optimizer, *, jit: bool = True,
                        donate: bool = True) -> Callable:
        """(wstate, batch) -> (wstate, metrics): forward + grad + update as
        ONE XLA program. Single-device / auto-sharded form; for explicit
        mesh placement use :meth:`make_sharded_train_step`."""
        step = self._build_step(optimizer)
        if jit:
            return jax.jit(step, donate_argnums=(0,) if donate else ())
        return step

    def make_sharded_train_step(self, optimizer: Optimizer, mesh,
                                wstate, batch_spec, *, rule=None,
                                donate: bool = True):
        """Compile the train step under an explicit device mesh.

        Shardings are computed from ``rule`` over the state pytree (see
        veles_tpu.parallel.mesh) and from the batch spec (leading axis over
        data×fsdp). GSPMD inserts the gradient psum over ICI — the TPU
        replacement for the reference's master-side update merging
        (veles/workflow.py:533-548, SURVEY.md §2.5).

        Returns (step_fn, state_shardings, batch_shardings); place the
        initial wstate with ``jax.device_put(wstate, state_shardings)``.
        """
        from ..parallel.mesh import batch_shardings, state_shardings
        state_sh = state_shardings(wstate, mesh, rule)
        batch_sh = batch_shardings(batch_spec, mesh)
        self.mesh = mesh  # BEFORE _build_step: the traced ctx carries it
        self.state_sharding = state_sh
        step = self._build_step(optimizer)
        fn = jax.jit(step,
                     in_shardings=(state_sh, batch_sh),
                     out_shardings=(state_sh, None),
                     donate_argnums=(0,) if donate else ())
        return fn, state_sh, batch_sh

    def make_pipeline_train_step(self, optimizer: Optimizer, mesh,
                                 wstate, batch_spec, *,
                                 n_microbatches: int, rule=None,
                                 batch_axes: Sequence[str] = ("data",
                                                              "fsdp"),
                                 donate: bool = True,
                                 interleave: int = 1):
        """Compile the FUSED 1F1B pipeline training step (the model IS the
        pipeline): pre-units fold into stage 0, post-units + evaluator
        loss into the last stage, one PipelineStack supplies the stages.
        Same return contract as :meth:`make_sharded_train_step` —
        ``(step_fn, state_shardings, batch_shardings)`` — so the Trainer
        swaps schedules on a config switch.  Backward memory is bounded
        by pipeline depth, not microbatch count (parallel/pipeline.py).

        ``interleave=v`` runs the INTERLEAVED schedule: the stack must
        have v·S uniform stages, device d hosts virtual chunks d, S+d,
        ... — up to ~2× less pipeline bubble than folding the chunks
        into plain 1F1B (see parallel/pipeline.py::_interleaved_local
        for the exact accounting) at v× the activation stash.
        """
        from ..parallel.pipeline_compile import build_pipeline_step
        return build_pipeline_step(
            self, optimizer, mesh, wstate, batch_spec,
            n_microbatches=n_microbatches, rule=rule,
            batch_axes=batch_axes, donate=donate,
            interleave=interleave)

    def make_sharded_eval_step(self, mesh, wstate, batch_spec, *, rule=None):
        from ..parallel.mesh import batch_shardings, state_shardings
        state_sh = state_shardings(wstate, mesh, rule)
        batch_sh = batch_shardings(batch_spec, mesh)
        self.mesh = mesh

        def step(wstate, batch):
            ctx = Context(train=False, key=None, mesh=self.mesh)
            outputs, _ = self.forward(wstate["params"], wstate["state"],
                                      batch, ctx)
            return self._metrics(wstate["params"], wstate["state"],
                                 outputs, ctx)

        return jax.jit(step, in_shardings=(state_sh, batch_sh),
                       out_shardings=None), state_sh, batch_sh

    def make_eval_step(self, *, jit: bool = True) -> Callable:
        """(wstate, batch) -> metrics. Separate compiled program = the
        reference's Decision-gated validation phase."""

        def step(wstate, batch):
            ctx = Context(train=False, key=None, mesh=self.mesh)
            outputs, _ = self.forward(wstate["params"], wstate["state"],
                                      batch, ctx)
            return self._metrics(wstate["params"], wstate["state"],
                                 outputs, ctx)

        return jax.jit(step) if jit else step

    def default_output(self) -> str:
        """Name of the last forward (non-evaluator) unit — the chain's
        natural prediction head (shared by predict/serve/decode)."""
        cands = [u.name for u in self.topo_order()
                 if not getattr(u, "is_evaluator", False)]
        if not cands:
            raise WorkflowError("no forward units")
        return cands[-1]

    def make_predict_step(self, output_unit: Optional[str] = None, *,
                          jit: bool = True) -> Callable:
        """(wstate, batch) -> output of the last forward (or named) unit."""
        if output_unit is None:
            output_unit = self.default_output()
        needed = self.ancestors(output_unit)

        def step(wstate, batch):
            ctx = Context(train=False, key=None, mesh=self.mesh)
            outputs, _ = self.forward(wstate["params"], wstate["state"],
                                      batch, ctx, only=needed)
            return outputs[output_unit]

        return jax.jit(step) if jit else step

    @staticmethod
    def state_struct(wstate) -> dict:
        """ShapeDtypeStruct skeleton of a workflow state pytree — the
        argument signature ``runtime.step_cache.StepCache`` lowers the
        step programs against (AOT ``.lower().compile()``), typed PRNG
        key leaves included."""
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                getattr(x, "shape", ()), x.dtype), wstate)

    # -- introspection / parity extras -------------------------------------
    def checksum(self) -> str:
        """Stable identity of the graph topology (reference:
        veles/workflow.py:851 — used in the distributed handshake)."""
        desc = [(u.name, type(u).__name__, list(u.inputs))
                for u in self.topo_order()]
        return hashlib.sha256(
            json.dumps(desc, sort_keys=True).encode()).hexdigest()

    def generate_graph(self) -> str:
        """DOT source of the data DAG (reference: veles/workflow.py:628)."""
        lines = [f'digraph "{self.name}" {{', "  rankdir=LR;"]
        inputs = {s for u in self.units for s in u.inputs
                  if s.startswith("@")}
        for i in sorted(inputs):
            lines.append(f'  "{i}" [shape=oval, style=dashed];')
        for u in self.units:
            shape = "diamond" if getattr(u, "is_evaluator", False) else "box"
            lines.append(
                f'  "{u.name}" [shape={shape}, '
                f'label="{u.name}\\n{type(u).__name__}"];')
            for s in u.inputs:
                lines.append(f'  "{s}" -> "{u.name}";')
        lines.append("}")
        return "\n".join(lines)

    def generate_svg(self) -> str:
        """Self-contained SVG of the data DAG — a native renderer for the
        browser workflow viewer (reference: the web UI's live graph,
        /root/reference/web/viz.js fed by veles/workflow.py:628's DOT).
        The reference shelled out to graphviz; this image has none, so a
        simple layered layout (layer = 1 + max layer of inputs, left to
        right) is computed here — exact enough for the linear-ish unit
        chains workflows are."""
        layer: Dict[str, int] = {}
        inputs = sorted({s for u in self.units for s in u.inputs
                         if s.startswith("@")})
        for s in inputs:
            layer[s] = 0
        for u in self.topo_order():
            layer[u.name] = 1 + max(
                (layer.get(s, 0) for s in u.inputs), default=0)
        cols: Dict[int, List[str]] = {}
        kinds: Dict[str, str] = {s: "input" for s in inputs}
        for u in self.topo_order():
            kinds[u.name] = ("evaluator"
                             if getattr(u, "is_evaluator", False)
                             else type(u).__name__)
        for name, li in layer.items():
            cols.setdefault(li, []).append(name)
        BW, BH, GX, GY, PAD = 148, 42, 52, 18, 16
        pos: Dict[str, Tuple[int, int]] = {}
        for li in sorted(cols):
            for ri, name in enumerate(sorted(cols[li])):
                pos[name] = (PAD + li * (BW + GX),
                             PAD + ri * (BH + GY))
        width = PAD * 2 + (max(cols) + 1) * (BW + GX) - GX
        height = PAD * 2 + max(
            len(v) for v in cols.values()) * (BH + GY) - GY
        fills = {"input": "#eef", "evaluator": "#fee"}
        parts = [
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
            f'height="{height}" font-family="monospace" font-size="11">',
            '<defs><marker id="arr" viewBox="0 0 10 10" refX="9" refY="5"'
            ' markerWidth="6" markerHeight="6" orient="auto">'
            '<path d="M0,0L10,5L0,10z" fill="#555"/></marker></defs>']
        for u in self.units:
            x1, y1 = pos[u.name]
            for s in u.inputs:
                if s not in pos:
                    continue
                x0, y0 = pos[s]
                parts.append(
                    f'<line x1="{x0 + BW}" y1="{y0 + BH // 2}" '
                    f'x2="{x1}" y2="{y1 + BH // 2}" stroke="#555" '
                    'marker-end="url(#arr)"/>')
        from html import escape
        for name, (x, y) in pos.items():
            kind = kinds.get(name, "")
            fill = fills.get(kind, "#efe")
            dash = ' stroke-dasharray="4 2"' if kind == "input" else ""
            label = name if kind in ("input", "") else kind
            parts.append(
                f'<rect x="{x}" y="{y}" width="{BW}" height="{BH}" '
                f'rx="6" fill="{fill}" stroke="#333"{dash}/>')
            parts.append(f'<text x="{x + 6}" y="{y + 17}">'
                         f'{escape(name[:20])}</text>')
            if label != name:
                parts.append(
                    f'<text x="{x + 6}" y="{y + 33}" fill="#666">'
                    f'{escape(label[:20])}</text>')
        parts.append("</svg>")
        return "\n".join(parts)

    def n_params(self, wstate) -> int:
        return sum(int(x.size) for x in jax.tree.leaves(wstate["params"]))

    def profile_units(self, wstate, batch, *, train: bool = False,
                      reps: int = 3) -> List[Dict[str, Any]]:
        """Per-unit wall timing: run each unit's apply as its own jitted
        call with a forced device sync — the analog of the reference's
        ``--sync-run`` honest per-unit timers (veles/accelerated_units.py
        :186-193, per-unit timers veles/units.py:805-817). In the fused
        production step XLA erases unit boundaries, so this instrumented
        mode is how per-unit cost is attributed."""
        import time as _time
        ctx = Context(train=train, key=wstate.get("key"))
        outputs = dict(batch)
        rows = []

        def drain(tree):
            leaf = jax.tree.leaves(tree)[0]
            jax.device_get(leaf.ravel()[:1])  # scalar read = full sync

        for u in self.topo_order():
            xs = [outputs[s] for s in u.inputs]
            fn = jax.jit(lambda p, s, *xs, _u=u: _u.apply(p, s, list(xs),
                                                          ctx))
            params = wstate["params"].get(u.name, {})
            state = wstate["state"].get(u.name, {})
            y, _ = fn(params, state, *xs)
            drain(y)  # compile + warm
            best = float("inf")
            for _ in range(reps):
                t0 = _time.perf_counter()
                y, _ = fn(params, state, *xs)
                drain(y)
                best = min(best, _time.perf_counter() - t0)
            outputs[u.name] = y
            rows.append({"unit": u.name, "type": type(u).__name__,
                         "ms": best * 1e3})
        return rows

    @staticmethod
    def format_profile(rows: List[Dict[str, Any]], top: int = 5) -> str:
        """Top-N table with share of total (reference: Workflow.print_stats
        top-5 table, veles/workflow.py:788-825)."""
        total = sum(r["ms"] for r in rows) or 1e-9
        ranked = sorted(rows, key=lambda r: -r["ms"])[:top]
        lines = [f"{'unit':>20s} {'type':>18s} {'ms':>9s} {'share':>7s}"]
        for r in ranked:
            lines.append(f"{r['unit']:>20s} {r['type']:>18s} "
                         f"{r['ms']:9.3f} {100 * r['ms'] / total:6.1f}%")
        lines.append(f"{'TOTAL':>20s} {'':>18s} {total:9.3f}")
        return "\n".join(lines)

    def gather_results(self, metrics: Dict[str, Any]) -> Dict[str, Any]:
        """JSON-able result dict (reference: IResultProvider →
        gather_results → --result-file, veles/workflow.py:827-849)."""
        out = {"workflow": self.name, "checksum": self.checksum()}
        for k, v in metrics.items():
            try:
                out[k] = float(v)
            except (TypeError, ValueError):
                out[k] = repr(v)
        return out
