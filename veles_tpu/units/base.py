"""Functional Unit core.

TPU-native re-design of the reference Unit/IUnit dataflow node (reference:
veles/units.py:59,108 — control-flow gate graph run on a thread pool,
``link_from``/``open_gate``/``run_dependent`` :485-554, ``link_attrs``/
``demand`` attribute plumbing :638-682) and the unit registry metaclass
(reference: veles/unit_registry.py:51,178).

The execution model changes completely — this is the core design decision of
the rebuild: a Unit is **pure data + pure functions**, not a live object with
mutable gates.  A unit declares

  * ``inputs``   — names of upstream units whose outputs it consumes
                   (replaces ``link_attrs``; checked at workflow build time,
                   replacing ``demand()``'s runtime None-checks),
  * ``init(key, in_specs)``   — build its parameter/state pytrees,
  * ``apply(params, state, xs, ctx)`` — pure forward computation.

The Workflow (units/workflow.py) topologically sorts units and traces them
into a single XLA computation under ``jax.jit`` — the reference's hot loop
(veles/units.py:782-803, lock-per-unit thread fan-out) disappears into the
compiled program, where XLA schedules operations on the MXU/VPU directly.
Control flow that was data-dependent gating (Decision blocking gradient units
during validation, reference: docs manualrst_veles_units.rst) becomes separate
compiled step functions per phase — see Workflow.train_step/eval_step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..logger import Logger

# A shape/dtype spec for tracing; same role as the reference's demand()-ed
# attribute shapes at initialize() time (veles/workflow.py:303-349).
Spec = jax.ShapeDtypeStruct


def spec_of(x) -> Spec:
    return Spec(jnp.shape(x), jnp.result_type(x))


@dataclasses.dataclass
class Context:
    """Per-call context threaded through apply(): train/eval phase flag and
    a PRNG key (replaces the reference's per-unit reproducible generators,
    veles/units.py:859-885 — keys are split per unit name, so adding units
    never perturbs other units' streams).  ``mesh`` is the device mesh the
    step was compiled under (None on single-device paths) — parallelism-
    aware units (ring attention, pipeline stacks, MoE) read their axis
    sizes off it.

    ``manual_axes`` distinguishes the two collective regimes a unit can
    find itself in.  ``None`` (the default) means ordinary traced code
    under jit: a unit may open its own ``shard_map`` (the ring-attention
    wrapper) or rely on GSPMD sharding propagation.  A tuple means the
    unit is ALREADY executing inside an enclosing ``shard_map`` (a
    pipeline schedule body) where opening another shard_map would
    illegally nest — but raw named-axis collectives (psum / ppermute /
    all_to_all) over the listed axes are legal and the schedule has laid
    the unit's data out for them (round-4 verdict #3: collectives inside
    fused-1F1B stages)."""
    train: bool = True
    key: Optional[jax.Array] = None
    mesh: Optional[Any] = None
    manual_axes: Optional[Tuple[str, ...]] = None

    def axis_size(self, name: str) -> int:
        if self.mesh is None or name not in self.mesh.shape:
            return 1
        return self.mesh.shape[name]

    def collective_mode(self, name: str) -> str:
        """How a unit should parallelize over mesh axis ``name``:
        ``"none"`` (axis absent/size 1, or inside a schedule that has not
        prepared this axis — use the local formulation), ``"wrapper"``
        (ordinary jit — open a shard_map / let GSPMD shard), or
        ``"manual"`` (inside an enclosing shard_map — use raw collectives
        over the named axis)."""
        if self.axis_size(name) <= 1:
            return "none"
        if self.manual_axes is None:
            return "wrapper"
        return "manual" if name in self.manual_axes else "none"

    def unit_key(self, name: str) -> Optional[jax.Array]:
        if self.key is None:
            return None
        # Fold the unit name in deterministically.
        h = 0
        for c in name:
            h = (h * 131 + ord(c)) % (2 ** 31 - 1)
        return jax.random.fold_in(self.key, h)


class UnitRegistry:
    """Name -> class registry for introspection/factories (reference:
    veles/unit_registry.py:51 metaclass; also the UUID factory of libVeles,
    libVeles/inc/veles/unit_factory.h). Used by the export/serving path."""

    _units: Dict[str, type] = {}

    @classmethod
    def register(cls, klass):
        cls._units[klass.__name__] = klass
        return klass

    @classmethod
    def get(cls, name: str) -> type:
        return cls._units[name]

    @classmethod
    def names(cls):
        return sorted(cls._units)


class UnitMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)
        if name != "Unit":
            UnitRegistry.register(cls)


class Unit(Logger, metaclass=UnitMeta):
    """Base of every dataflow node.

    Subclasses override :meth:`output_spec`, :meth:`init` and :meth:`apply`.
    Units are cheap descriptor objects; all tensors live in the workflow-owned
    state pytree (params/state dicts keyed by unit name), which is what gets
    sharded, donated, and checkpointed.
    """

    #: set by subclasses: does apply() consume a PRNG key when training?
    stochastic: bool = False

    def __init__(self, name: Optional[str] = None,
                 inputs: Sequence[str] = ("@input",)):
        self.name = name or type(self).__name__
        self.inputs: Tuple[str, ...] = tuple(inputs)

    # -- graph wiring (replaces link_from/link_attrs) ----------------------
    def link_from(self, *sources: "Unit | str") -> "Unit":
        """Declare upstream data dependencies. Reference parity:
        veles/units.py:554 link_from + :638 link_attrs collapsed into one
        concept, because in a pure dataflow design control order *is* data
        order."""
        self.inputs = tuple(
            s.name if isinstance(s, Unit) else s for s in sources)
        return self

    # -- functional contract ----------------------------------------------
    def prepare(self, in_specs: Sequence[Spec]) -> None:
        """Build-time hook: called once by Workflow.build with resolved
        input specs, OUTSIDE any jit trace — the place for shape-dependent
        decisions that must not happen during tracing (e.g. resolving an
        ``"auto"`` formulation via runtime.autotune, which times real
        device executions). Default: nothing."""

    def output_spec(self, in_specs: Sequence[Spec]) -> Spec:
        """Shape/dtype inference. Default: identity on the first input."""
        return in_specs[0]

    def init(self, key: jax.Array, in_specs: Sequence[Spec]
             ) -> Tuple[Any, Any]:
        """Return (params, state) pytrees. params are differentiated;
        state is carried across steps (e.g. SOM weights, BN stats)."""
        return {}, {}

    def apply(self, params, state, xs: Sequence[jax.Array], ctx: Context
              ) -> Tuple[jax.Array, Any]:
        """Pure forward: returns (output, new_state)."""
        raise NotImplementedError

    # ----------------------------------------------------------------------
    def __repr__(self):
        return f"{type(self).__name__}({self.name!r} <- {list(self.inputs)})"


class TrivialUnit(Unit):
    """Identity passthrough (reference: veles/units.py:916)."""

    def apply(self, params, state, xs, ctx):
        return xs[0], state


class Forward(Unit):
    """Marker base for trainable forward layers (what the reference calls a
    Znicz forward unit)."""


class LambdaUnit(Unit):
    """Wrap an arbitrary pure function as a unit."""

    def __init__(self, fn: Callable, name=None, inputs=("@input",),
                 out_spec: Optional[Callable] = None):
        super().__init__(name or getattr(fn, "__name__", "LambdaUnit"), inputs)
        self._fn = fn
        self._out_spec = out_spec

    def output_spec(self, in_specs):
        if self._out_spec is not None:
            return self._out_spec(in_specs)
        return jax.eval_shape(lambda *xs: self._fn(*xs), *in_specs)

    def apply(self, params, state, xs, ctx):
        return self._fn(*xs), state


class InputJoiner(Unit):
    """Concatenate inputs along the feature axis (reference:
    veles/input_joiner.py:49 — device-side concat via Jinja-generated
    join.jcl kernel; here a single jnp.concatenate the XLA fuser handles)."""

    def __init__(self, name=None, inputs=(), axis: int = -1):
        super().__init__(name, inputs)
        self.axis = axis

    def output_spec(self, in_specs):
        return jax.eval_shape(
            lambda *xs: jnp.concatenate(xs, axis=self.axis), *in_specs)

    def apply(self, params, state, xs, ctx):
        return jnp.concatenate(xs, axis=self.axis), state


class Avatar(TrivialUnit):
    """Decouples pipelines by cloning a loader output (reference:
    veles/avatar.py:22). In a pure dataflow graph an output can simply be
    consumed twice, so Avatar is an identity kept for graph readability."""
