"""Recurrent layer units: RNN, GRU, LSTM over lax.scan cells.

Znicz parity+ (reference declared RNN/LSTM units, "created but not
tested" — docs/source/manualrst_veles_algorithms.rst:115-134). Input is
batch-major (B, T, F); units transpose to time-major for the scan and back,
so the rest of the framework keeps the batch-leading convention of every
other unit. ``return_sequences=False`` yields the last hidden state (B, H)
— the natural input to an All2All classifier head.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from ..ops import recurrent as rec_ops
from .base import Forward, Spec


class _RecurrentBase(Forward):
    n_gates = 1  # columns of the fused gate weight = n_gates * hidden

    def __init__(self, hidden: int, *, return_sequences: bool = True,
                 compute_dtype=None, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.hidden = int(hidden)
        self.return_sequences = bool(return_sequences)
        self.compute_dtype = (None if compute_dtype in (None, "")
                              else jnp.dtype(compute_dtype))

    def _dims(self, in_spec: Spec):
        if len(in_spec.shape) != 3:
            raise ValueError(
                f"{self.name}: recurrent input must be (batch, time, "
                f"features), got {in_spec.shape}")
        return in_spec.shape  # (B, T, F)

    def output_spec(self, in_specs):
        b, t, _ = self._dims(in_specs[0])
        if self.return_sequences:
            return Spec((b, t, self.hidden), in_specs[0].dtype)
        return Spec((b, self.hidden), in_specs[0].dtype)

    def init(self, key, in_specs):
        _, _, f = self._dims(in_specs[0])
        fan_in = f + self.hidden
        params = {
            "w": ops.smart_uniform_init(
                key, (fan_in, self.n_gates * self.hidden), fan_in),
            "b": jnp.zeros((self.n_gates * self.hidden,), jnp.float32),
        }
        return params, {}

    def _scan(self, params, xs_tm, batch):
        raise NotImplementedError

    def apply(self, params, state, xs, ctx):
        x = jnp.swapaxes(xs[0], 0, 1)  # (T, B, F) time-major for scan
        ys_tm, _ = self._scan(params, x, x.shape[1])
        if self.return_sequences:
            return jnp.swapaxes(ys_tm, 0, 1), state
        return ys_tm[-1], state


class RNN(_RecurrentBase):
    """Elman RNN with tanh (or relu) activation."""

    n_gates = 1

    def __init__(self, hidden, *, activation: str = "tanh", **kw):
        super().__init__(hidden, **kw)
        self.activation = activation

    def _scan(self, params, xs_tm, batch):
        act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[self.activation]
        h0 = jnp.zeros((batch, self.hidden), jnp.float32)
        return rec_ops.rnn_scan(xs_tm, h0, params["w"], params["b"],
                                activation=act,
                                compute_dtype=self.compute_dtype)


class GRU(_RecurrentBase):
    n_gates = 3

    def _scan(self, params, xs_tm, batch):
        h0 = jnp.zeros((batch, self.hidden), jnp.float32)
        return rec_ops.gru_scan(xs_tm, h0, params["w"], params["b"],
                                compute_dtype=self.compute_dtype)


class LSTM(_RecurrentBase):
    n_gates = 4

    def __init__(self, hidden, *, forget_bias: float = 1.0, **kw):
        super().__init__(hidden, **kw)
        self.forget_bias = float(forget_bias)

    def _scan(self, params, xs_tm, batch):
        h0 = jnp.zeros((batch, self.hidden), jnp.float32)
        c0 = jnp.zeros((batch, self.hidden), jnp.float32)
        return rec_ops.lstm_scan(xs_tm, h0, c0, params["w"], params["b"],
                                 compute_dtype=self.compute_dtype,
                                 forget_bias=self.forget_bias)
