"""Kohonen self-organizing map — a non-SGD, self-updating unit.

Reference: Znicz Kohonen SOM units (docs manualrst_veles_algorithms.rst:61-70
— "Kohonen" forward + trainer units; one of BASELINE.json's non-SGD configs).

TPU redesign: SOM weights live in unit *state* (not params — nothing is
differentiated); the competitive update is a batched, fully-vectorized
einsum (winner search + Gaussian neighborhood pull) that the Workflow's
train step applies via the ``self_updating`` hook — one fused XLA program,
no per-sample loop."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import Spec, Unit


class KohonenForward(Unit):
    """Forward: winner (BMU) indices for each sample; state carries the
    (sx*sy, features) codebook."""

    self_updating = True

    def __init__(self, shape=(8, 8), *, init_radius=None, init_lr=0.1,
                 decay_steps=1000.0, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.sx, self.sy = shape
        self.n_neurons = self.sx * self.sy
        self.init_radius = init_radius or max(self.sx, self.sy) / 2.0
        self.init_lr = init_lr
        self.decay_steps = decay_steps

    def output_spec(self, in_specs):
        return Spec((in_specs[0].shape[0],), jnp.int32)

    def init(self, key, in_specs):
        feat = int(np.prod(in_specs[0].shape[1:]))
        w = jax.random.uniform(key, (self.n_neurons, feat), jnp.float32,
                               -0.1, 0.1)
        gx, gy = jnp.meshgrid(jnp.arange(self.sx), jnp.arange(self.sy),
                              indexing="ij")
        coords = jnp.stack([gx.ravel(), gy.ravel()], axis=1).astype(
            jnp.float32)
        return {}, {"weights": w, "coords": coords,
                    "t": jnp.zeros((), jnp.float32)}

    def _dists(self, state, x):
        x = x.reshape(x.shape[0], -1)
        w = state["weights"]
        return (jnp.sum(jnp.square(x), 1, keepdims=True)
                - 2.0 * x @ w.T + jnp.sum(jnp.square(w), 1)[None, :])

    def apply(self, params, state, xs, ctx):
        d = self._dists(state, xs[0])
        return jnp.argmin(d, axis=1).astype(jnp.int32), state

    def update_state(self, params, state, xs, ctx):
        """Batch SOM update with exponentially decaying lr/radius."""
        x = xs[0].reshape(xs[0].shape[0], -1).astype(jnp.float32)
        w, coords, t = state["weights"], state["coords"], state["t"]
        d = self._dists(state, x)
        winners = jnp.argmin(d, axis=1)
        decay = jnp.exp(-t / self.decay_steps)
        sigma = jnp.maximum(self.init_radius * decay, 0.5)
        eta = self.init_lr * decay
        wc = coords[winners]                                  # (B, 2)
        g2 = jnp.sum(jnp.square(coords[None] - wc[:, None]), -1)  # (B, N)
        h = jnp.exp(-g2 / (2.0 * jnp.square(sigma)))          # (B, N)
        num = jnp.einsum("bn,bf->nf", h, x)
        den = jnp.sum(h, axis=0)[:, None]
        dw = num - den * w
        w_new = w + eta / x.shape[0] * dw
        return {"weights": w_new, "coords": coords, "t": t + 1.0}

    def quantization_error(self, state, x) -> jax.Array:
        """Mean distance to BMU — the SOM quality metric."""
        d = self._dists(state, jnp.asarray(x))
        return jnp.sqrt(jnp.maximum(jnp.min(d, axis=1), 0.0)).mean()
