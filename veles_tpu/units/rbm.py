"""Bernoulli RBM with contrastive-divergence training — self-updating unit.

Reference: Znicz RBM units ("numpy only" in the reference — docs
manualrst_veles_algorithms.rst:101-114). Here CD-k runs fully on the MXU:
the positive/negative phase gemms batch over the minibatch, Gibbs sampling
uses the ctx PRNG key."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import Spec, Unit


class RBM(Unit):
    """Forward: hidden activation probabilities. State: W, vbias, hbias."""

    self_updating = True
    stochastic = True

    def __init__(self, n_hidden: int, *, lr=0.05, cd_k: int = 1,
                 name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.n_hidden = int(n_hidden)
        self.lr = lr
        self.cd_k = cd_k

    def output_spec(self, in_specs):
        return Spec((in_specs[0].shape[0], self.n_hidden), jnp.float32)

    def init(self, key, in_specs):
        feat = int(np.prod(in_specs[0].shape[1:]))
        w = jax.random.normal(key, (feat, self.n_hidden)) * 0.01
        return {}, {"w": w.astype(jnp.float32),
                    "vbias": jnp.zeros((feat,), jnp.float32),
                    "hbias": jnp.zeros((self.n_hidden,), jnp.float32)}

    @staticmethod
    def _h_prob(state, v):
        return jax.nn.sigmoid(v @ state["w"] + state["hbias"])

    @staticmethod
    def _v_prob(state, h):
        return jax.nn.sigmoid(h @ state["w"].T + state["vbias"])

    def apply(self, params, state, xs, ctx):
        v = xs[0].reshape(xs[0].shape[0], -1).astype(jnp.float32)
        return self._h_prob(state, v), state

    def update_state(self, params, state, xs, ctx):
        v0 = xs[0].reshape(xs[0].shape[0], -1).astype(jnp.float32)
        key = ctx.unit_key(self.name)
        if key is None:
            key = jax.random.key(0)
        h0p = self._h_prob(state, v0)
        hk = (jax.random.uniform(key, h0p.shape) < h0p).astype(jnp.float32)
        vk = v0
        for i in range(self.cd_k):
            key, k1 = jax.random.split(key)
            vk = self._v_prob(state, hk)
            hkp = self._h_prob(state, vk)
            hk = (jax.random.uniform(k1, hkp.shape) < hkp).astype(
                jnp.float32)
        hkp = self._h_prob(state, vk)
        n = v0.shape[0]
        dw = (v0.T @ h0p - vk.T @ hkp) / n
        dv = jnp.mean(v0 - vk, axis=0)
        dh = jnp.mean(h0p - hkp, axis=0)
        return {"w": state["w"] + self.lr * dw,
                "vbias": state["vbias"] + self.lr * dv,
                "hbias": state["hbias"] + self.lr * dh}

    def reconstruction_error(self, state, v) -> jax.Array:
        v = jnp.asarray(v).reshape(len(v), -1).astype(jnp.float32)
        h = self._h_prob(state, v)
        vr = self._v_prob(state, h)
        return jnp.mean(jnp.square(v - vr))
