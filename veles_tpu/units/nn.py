"""NN forward-layer units — the Znicz layer library rebuilt TPU-first.

Reference capability checklist (SURVEY.md §2.10; docs
manualrst_veles_algorithms.rst:10-134): fully-connected (all2all with
softmax/tanh/relu/sincos), conv, pooling (max/avg), deconv, depool, dropout,
LRN, plus evaluators (softmax CE, MSE). Kohonen SOM and RBM live in
units/kohonen.py / units/rbm.py (non-SGD custom updates).

Every unit here is a thin declarative wrapper over veles_tpu.ops — pure
functions the Workflow traces into one jitted step. Weights initialize with
the Znicz "smart init" (uniform ±1/sqrt(fan_in)).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from ..ops.activations import ACTIVATIONS
from .base import Context, Forward, Spec, Unit


def _cast_policy(dtype):
    return None if dtype in (None, "") else jnp.dtype(dtype)


class All2All(Forward):
    """Fully-connected layer (reference Znicz all2all; gemm on the MXU)."""

    def __init__(self, output_size: int, *, activation: str = "linear",
                 weights_scale: float = 1.0, include_bias: bool = True,
                 compute_dtype=None, name=None, inputs=("@input",),
                 per_position: bool = False):
        super().__init__(name, inputs)
        self.output_size = int(output_size)
        self.activation = activation
        self.weights_scale = weights_scale
        self.include_bias = include_bias
        self.compute_dtype = _cast_policy(compute_dtype)
        # per_position: project the TRAILING feature axis only, keeping
        # leading (B, T, ...) dims — e.g. (B, T, E) -> (B, T, V) logits
        # for the sequence evaluator. Default flattens per sample (the
        # reference all2all semantics).
        self.per_position = bool(per_position)

    def _in_features(self, in_spec: Spec) -> int:
        if self.per_position:
            return int(in_spec.shape[-1])
        return int(np.prod(in_spec.shape[1:]))

    def output_spec(self, in_specs):
        s = in_specs[0]
        if self.per_position:
            return Spec(tuple(s.shape[:-1]) + (self.output_size,), s.dtype)
        return Spec((s.shape[0], self.output_size), s.dtype)

    def init(self, key, in_specs):
        fan_in = self._in_features(in_specs[0])
        kw, _ = jax.random.split(key)
        params = {"w": ops.smart_uniform_init(
            kw, (fan_in, self.output_size), fan_in,
            scale=self.weights_scale)}
        if self.include_bias:
            params["b"] = jnp.zeros((self.output_size,), jnp.float32)
        return params, {}

    def apply(self, params, state, xs, ctx):
        x = xs[0]
        if self.per_position:
            lead = x.shape[:-1]
            x = x.reshape(-1, x.shape[-1])
        else:
            lead = None
            x = x.reshape(x.shape[0], -1)
        y = ops.dense(x, params["w"], params.get("b"),
                      compute_dtype=self.compute_dtype)
        if lead is not None:
            y = y.reshape(lead + (self.output_size,))
        return ACTIVATIONS[self.activation](y), state


class All2AllTanh(All2All):
    def __init__(self, output_size, **kw):
        kw.setdefault("activation", "tanh")
        kw.setdefault("weights_scale", 1.0)
        super().__init__(output_size, **kw)


class All2AllRELU(All2All):
    def __init__(self, output_size, **kw):
        kw.setdefault("activation", "relu")
        super().__init__(output_size, **kw)


class All2AllSincos(All2All):
    def __init__(self, output_size, **kw):
        kw.setdefault("activation", "sincos")
        super().__init__(output_size, **kw)


class All2AllSoftmax(All2All):
    """Output layer: emits LOGITS (softmax itself fuses into the CE loss —
    the reference computed softmax in the evaluator's kernel too)."""

    def __init__(self, output_size, **kw):
        kw.setdefault("activation", "linear")
        super().__init__(output_size, **kw)


class Conv(Forward):
    """2-D convolution (NHWC) with optional activation."""

    def __init__(self, n_kernels: int, kx: int = 3, ky: Optional[int] = None,
                 *, stride=1, padding="SAME", activation="linear",
                 weights_scale=1.0, include_bias=True, compute_dtype=None,
                 name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.n_kernels = int(n_kernels)
        self.kx = int(kx)
        self.ky = int(ky if ky is not None else kx)
        self.stride = stride
        self.padding = padding
        self.activation = activation
        self.weights_scale = weights_scale
        self.include_bias = include_bias
        self.compute_dtype = _cast_policy(compute_dtype)

    def output_spec(self, in_specs):
        s = in_specs[0]
        w = Spec((self.ky, self.kx, s.shape[-1], self.n_kernels), s.dtype)
        return jax.eval_shape(
            lambda x, w_: ops.conv2d(x, w_, stride=self.stride,
                                     padding=self.padding), s, w)

    def init(self, key, in_specs):
        cin = in_specs[0].shape[-1]
        fan_in = self.kx * self.ky * cin
        kw, _ = jax.random.split(key)
        params = {"w": ops.smart_uniform_init(
            kw, (self.ky, self.kx, cin, self.n_kernels), fan_in,
            scale=self.weights_scale)}
        if self.include_bias:
            params["b"] = jnp.zeros((self.n_kernels,), jnp.float32)
        return params, {}

    def apply(self, params, state, xs, ctx):
        y = ops.conv2d(xs[0], params["w"], params.get("b"),
                       stride=self.stride, padding=self.padding,
                       compute_dtype=self.compute_dtype)
        return ACTIVATIONS[self.activation](y), state


class ConvRELU(Conv):
    def __init__(self, n_kernels, kx=3, ky=None, **kw):
        kw.setdefault("activation", "relu")
        super().__init__(n_kernels, kx, ky, **kw)


class ConvTanh(Conv):
    def __init__(self, n_kernels, kx=3, ky=None, **kw):
        kw.setdefault("activation", "tanh")
        super().__init__(n_kernels, kx, ky, **kw)


class Deconv(Forward):
    """Transposed convolution (reference Znicz deconv)."""

    def __init__(self, n_kernels: int, kx: int = 3, ky: Optional[int] = None,
                 *, stride=1, padding="SAME", activation="linear",
                 weights_scale=1.0, compute_dtype=None, name=None,
                 inputs=("@input",)):
        super().__init__(name, inputs)
        self.n_kernels = int(n_kernels)
        self.kx = int(kx)
        self.ky = int(ky if ky is not None else kx)
        self.stride = stride
        self.padding = padding
        self.activation = activation
        self.weights_scale = weights_scale
        self.compute_dtype = _cast_policy(compute_dtype)

    def output_spec(self, in_specs):
        s = in_specs[0]
        w = Spec((self.ky, self.kx, s.shape[-1], self.n_kernels), s.dtype)
        return jax.eval_shape(
            lambda x, w_: ops.deconv2d(x, w_, stride=self.stride,
                                       padding=self.padding), s, w)

    def init(self, key, in_specs):
        cin = in_specs[0].shape[-1]
        fan_in = self.kx * self.ky * cin
        kw, _ = jax.random.split(key)
        return {"w": ops.smart_uniform_init(
            kw, (self.ky, self.kx, cin, self.n_kernels), fan_in,
            scale=self.weights_scale),
            "b": jnp.zeros((self.n_kernels,), jnp.float32)}, {}

    def apply(self, params, state, xs, ctx):
        y = ops.deconv2d(xs[0], params["w"], params["b"],
                         stride=self.stride, padding=self.padding,
                         compute_dtype=self.compute_dtype)
        return ACTIVATIONS[self.activation](y), state


class MaxPooling(Unit):
    def __init__(self, window=2, stride=None, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.window = window
        self.stride = stride

    def output_spec(self, in_specs):
        return jax.eval_shape(
            lambda x: ops.max_pool(x, self.window, self.stride), in_specs[0])

    def apply(self, params, state, xs, ctx):
        return ops.max_pool(xs[0], self.window, self.stride), state


class AvgPooling(Unit):
    def __init__(self, window=2, stride=None, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.window = window
        self.stride = stride

    def output_spec(self, in_specs):
        return jax.eval_shape(
            lambda x: ops.avg_pool(x, self.window, self.stride), in_specs[0])

    def apply(self, params, state, xs, ctx):
        return ops.avg_pool(xs[0], self.window, self.stride), state


class StochasticAbsPooling(MaxPooling):
    """Pool by max |x| keeping sign (Znicz's stochastic abs-pooling family;
    deterministic variant used at inference)."""

    def apply(self, params, state, xs, ctx):
        x = xs[0]
        mag = ops.max_pool(jnp.abs(x), self.window, self.stride)
        pos = ops.max_pool(x, self.window, self.stride)
        neg = -ops.max_pool(-x, self.window, self.stride)
        return jnp.where(pos >= mag, pos, neg), state


class Depool(Unit):
    """Unpooling by uniform spread (pairs with Deconv for autoencoders)."""

    def __init__(self, window=2, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.window = window

    def output_spec(self, in_specs):
        return jax.eval_shape(
            lambda x: ops.avg_unpool(x, self.window), in_specs[0])

    def apply(self, params, state, xs, ctx):
        return ops.avg_unpool(xs[0], self.window), state


class Dropout(Unit):
    """Inverted dropout; identity at eval (reference Znicz dropout;
    RNG = jax threefry via ctx.unit_key, replacing ocl/random.cl's
    xorshift1024* states).

    use_pallas: True/False forces a formulation; None = measure both
    fwd+bwd at the build shape and persist the winner (autotune;
    barrier'd v5e measurement at 4096x4096: Pallas 1.13x), falling back
    to the static platform default when autotune is disabled."""

    stochastic = True

    def __init__(self, dropout_ratio=0.5, name=None, inputs=("@input",),
                 use_pallas=None):
        super().__init__(name, inputs)
        self.ratio = float(dropout_ratio)
        self.use_pallas = use_pallas
        self._resolved = use_pallas

    def prepare(self, in_specs):
        from ..config import root
        if self.use_pallas is not None:
            self._resolved = self.use_pallas
            return
        if not bool(root.common.autotune):
            self._resolved = None  # static platform default at apply
            return
        if not ops.use_pallas_default():
            # Off-TPU the Pallas candidate runs in interpret mode — timing
            # it is a foregone conclusion; keep off-TPU builds
            # measurement-free.
            self._resolved = False
            return
        from ..runtime import autotune
        spec = in_specs[0]
        ratio, keep = self.ratio, 1.0 - self.ratio
        op = f"dropout_fwd_bwd_r{ratio}"
        specs = [jax.ShapeDtypeStruct(spec.shape, spec.dtype),
                 jax.ShapeDtypeStruct((), jnp.uint32)]
        names = ("pallas", "xla")
        cached = autotune.lookup(op, names, specs)
        if cached is not None:  # warm start: no arrays materialized
            self._resolved = cached == "pallas"
            return
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            spec.shape), spec.dtype)
        seed = jnp.uint32(123)
        key = jax.random.key(0)

        def g(f):
            # value_and_grad, both outputs returned: plain grad discards
            # the primal and the fused kernel's forward would be
            # dead-code-eliminated (its vjp residual is just the seed),
            # timing half the real training cost.
            def timed(x, s):
                v, gx = jax.value_and_grad(
                    lambda x: jnp.sum(f(x, s).astype(jnp.float32)))(x)
                return v, gx
            return timed

        winner = autotune.pick(
            op,
            {"pallas": g(lambda x, s: ops.fused_dropout(x, s, ratio)),
             "xla": g(lambda x, s: jnp.where(
                 jax.random.bernoulli(jax.random.fold_in(key, s), keep,
                                      x.shape),
                 x / keep, 0.0).astype(x.dtype))},
            [x, seed], default="pallas")
        self._resolved = winner == "pallas"

    def apply(self, params, state, xs, ctx):
        x = xs[0]
        if not ctx.train or self.ratio <= 0.0:
            return x, state
        key = ctx.unit_key(self.name)
        use_pallas = (ops.use_pallas_default()
                      if self._resolved is None else self._resolved)
        if use_pallas:
            # In-kernel counter-based RNG; mask regenerated in backward
            # (ops/pallas_kernels.py, parity: ocl/random.cl).
            seed = jax.random.bits(key, dtype=jnp.uint32)
            return ops.fused_dropout(x, seed, self.ratio), state
        keep = 1.0 - self.ratio
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state


class LRN(Unit):
    """Local response normalization across channels.

    method: "cumsum" (default — stable across devices, keeps test
    numerics fixed) | "band" (see ops/lrn.py) | "auto" — measure both
    formulations fwd+bwd on the actual device at build time and persist
    the winner per (device kind, shape) in the autotune DB (the
    reference's per-device bench-and-persist discipline,
    veles/backends.py:672-731; motivated by a real regression where a
    hand-picked default cost ~40% AlexNet throughput on v5e —
    BASELINE.md AlexNet r3 row)."""

    def __init__(self, n=5, k=2.0, alpha=1e-4, beta=0.75, name=None,
                 inputs=("@input",), method="cumsum"):
        super().__init__(name, inputs)
        self.n, self.k, self.alpha, self.beta = n, k, alpha, beta
        self.method = method
        self._resolved = method if method != "auto" else None

    def prepare(self, in_specs):
        if self.method != "auto":
            self._resolved = self.method
            return
        from ..config import root
        from ..runtime import autotune
        spec = in_specs[0]
        op = f"lrn_fwd_bwd_n{self.n}_b{self.beta}"
        names = ("cumsum", "band", "band_bf16")
        if not bool(root.common.autotune):
            self._resolved = "cumsum"
            self.method = self._resolved
            return
        cached = autotune.lookup(
            op, names, [jax.ShapeDtypeStruct(spec.shape, spec.dtype)])
        if cached is not None:  # warm start: no arrays materialized
            self._resolved = cached
            self.method = cached
            return
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal(spec.shape),
            spec.dtype)

        def run(method):
            # Time the training cost: forward + backward, like the unit
            # executes inside the train step. value_and_grad (not grad):
            # returning the primal too keeps the whole forward alive
            # under DCE.
            def f(x):
                return jax.value_and_grad(lambda x: jnp.sum(
                    ops.local_response_norm(
                        x, n=self.n, k=self.k, alpha=self.alpha,
                        beta=self.beta, method=method)
                    .astype(jnp.float32)))(x)
            return f

        # n/beta in the key: band's C x C matmul cost is n-independent
        # while cumsum's isn't, so different windows may have different
        # winners even at one shape
        self._resolved = autotune.pick(
            op,
            {"cumsum": run("cumsum"), "band": run("band"),
             "band_bf16": run("band_bf16")},
            [x], default="cumsum")
        # expose the concrete choice (export serializes `method`; the
        # serving runtime must never see "auto")
        self.method = self._resolved

    def apply(self, params, state, xs, ctx):
        method = self._resolved or self.method
        if method == "auto":
            raise RuntimeError(
                f"LRN {self.name!r} has method='auto' but prepare() was "
                "never called — build the workflow (Workflow.build calls "
                "prepare), or propagate prepare() from the composite "
                "unit wrapping this one, or set a concrete method")
        return ops.local_response_norm(
            xs[0], n=self.n, k=self.k, alpha=self.alpha, beta=self.beta,
            method=method), state


class MeanDispNormalizer(Unit):
    """(x - mean) * rdisp with dataset statistics stored in unit state
    (reference: veles/mean_disp_normalizer.py:50-138)."""

    def __init__(self, mean=None, rdisp=None, name=None, inputs=("@input",),
                 use_pallas=None):
        super().__init__(name, inputs)
        self._mean = mean
        self._rdisp = rdisp
        # None = autotune at build shape (static XLA default when
        # disabled — the barrier'd v5e measurement has XLA 2.5x ahead on
        # this op, but the winner is persisted per shape, not assumed);
        # True/False forces.
        self.use_pallas = use_pallas
        self._resolved = use_pallas

    def prepare(self, in_specs):
        from ..config import root
        if self.use_pallas is not None or not bool(root.common.autotune):
            self._resolved = self.use_pallas
            return
        if not ops.use_pallas_default():
            # interpret-mode Pallas off-TPU: skip the measurement
            self._resolved = False
            return
        from ..runtime import autotune
        spec = in_specs[0]
        feat = spec.shape[1:]
        specs = [jax.ShapeDtypeStruct(spec.shape, spec.dtype),
                 jax.ShapeDtypeStruct(feat, jnp.float32),
                 jax.ShapeDtypeStruct(feat, jnp.float32)]
        names = ("xla", "pallas")
        cached = autotune.lookup("mean_disp_normalize", names, specs)
        if cached is not None:  # warm start: no arrays materialized
            self._resolved = cached == "pallas"
            return
        rng = np.random.default_rng(0)
        x = jnp.asarray(
            rng.integers(0, 256, spec.shape)
            if np.issubdtype(np.dtype(spec.dtype), np.integer)
            else rng.standard_normal(spec.shape), spec.dtype)
        mean = jnp.asarray(rng.uniform(100, 150, feat), jnp.float32)
        rdisp = jnp.asarray(rng.uniform(0.01, 0.02, feat), jnp.float32)
        winner = autotune.pick(
            "mean_disp_normalize",
            {"xla": lambda x, m, r: ops.mean_disp_normalize(
                x, m, r, use_pallas=False),
             "pallas": lambda x, m, r: ops.mean_disp_normalize(
                 x, m, r, use_pallas=True)},
            [x, mean, rdisp], default="xla")
        self._resolved = winner == "pallas"

    def output_spec(self, in_specs):
        return Spec(in_specs[0].shape, jnp.float32)

    def init(self, key, in_specs):
        shape = in_specs[0].shape[1:]
        mean = jnp.asarray(self._mean, jnp.float32) if self._mean is not None \
            else jnp.zeros(shape, jnp.float32)
        rdisp = jnp.asarray(self._rdisp, jnp.float32) \
            if self._rdisp is not None else jnp.ones(shape, jnp.float32)
        return {}, {"mean": mean, "rdisp": rdisp}

    def apply(self, params, state, xs, ctx):
        return ops.mean_disp_normalize(
            xs[0], state["mean"], state["rdisp"],
            use_pallas=bool(self._resolved)), state


class Flatten(Unit):
    def output_spec(self, in_specs):
        s = in_specs[0]
        return Spec((s.shape[0], int(np.prod(s.shape[1:]))), s.dtype)

    def apply(self, params, state, xs, ctx):
        return xs[0].reshape(xs[0].shape[0], -1), state


class LayerNorm(Unit):
    """Layer normalization over the trailing feature axis with learnable
    scale/shift — the standard companion of the attention stack (no
    reference analog; LRN is the reference's only normalizer)."""

    def __init__(self, eps: float = 1e-5, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.eps = float(eps)

    def output_spec(self, in_specs):
        return in_specs[0]

    def init(self, key, in_specs):
        d = in_specs[0].shape[-1]
        return {"scale": jnp.ones((d,)), "shift": jnp.zeros((d,))}, {}

    def apply(self, params, state, xs, ctx):
        x = xs[0]
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps)
        out = y * params["scale"] + params["shift"]
        return out.astype(x.dtype), state


class FFN(Unit):
    """Per-position two-layer MLP with residual — the transformer block's
    FFN half (y = x + W2·act(W1·x)); pairs with the attention unit the
    way MoEFFN does for the sparse case. No reference analog (the
    reference has no sequence models — SURVEY.md §5.7)."""

    def __init__(self, d_hidden: int, activation: str = "relu",
                 residual: bool = True, name=None, inputs=("@input",),
                 compute_dtype=None):
        super().__init__(name, inputs)
        self.d_hidden = int(d_hidden)
        self.activation = activation
        self.residual = bool(residual)
        self.compute_dtype = _cast_policy(compute_dtype)

    def output_spec(self, in_specs):
        return in_specs[0]

    def init(self, key, in_specs):
        E = in_specs[0].shape[-1]
        k1, k2 = jax.random.split(key)
        return {"w1": ops.smart_uniform_init(k1, (E, self.d_hidden), E),
                "b1": jnp.zeros((self.d_hidden,), jnp.float32),
                "w2": ops.smart_uniform_init(k2, (self.d_hidden, E),
                                             self.d_hidden),
                "b2": jnp.zeros((E,), jnp.float32)}, {}

    def apply(self, params, state, xs, ctx):
        x = xs[0]
        lead = x.shape[:-1]
        flat = x.reshape(-1, x.shape[-1])
        h = ops.dense(flat, params["w1"], params["b1"],
                      compute_dtype=self.compute_dtype)
        h = ACTIVATIONS[self.activation](h)
        y = ops.dense(h, params["w2"], params["b2"],
                      compute_dtype=self.compute_dtype)
        y = y.reshape(lead + (x.shape[-1],))
        if self.residual:
            y = y + x
        return y.astype(x.dtype), state


class Embedding(Unit):
    """Token embedding: int tokens (B, T) -> (B, T, dim) by table lookup.

    The front door of the sequence/long-context model family (the
    reference had no sequence models in core — SURVEY.md §5.7); float
    inputs from generic loaders are cast to int32 indices."""

    def __init__(self, vocab: int, dim: int, name=None,
                 inputs=("@input",)):
        super().__init__(name, inputs)
        self.vocab = int(vocab)
        self.dim = int(dim)

    def output_spec(self, in_specs):
        s = in_specs[0]
        return Spec(tuple(s.shape) + (self.dim,), jnp.float32)

    def init(self, key, in_specs):
        return {"table": ops.smart_uniform_init(
            key, (self.vocab, self.dim), self.vocab)}, {}

    def apply(self, params, state, xs, ctx):
        idx = xs[0].astype(jnp.int32)
        return jnp.take(params["table"], idx, axis=0), state


def input_vocab(workflow, params) -> Optional[int]:
    """Embedding-table rows of the chain's front (None without an
    Embedding) — THE bound on acceptable input token ids, shared by the
    REST /predict out-of-vocab 400 guard (restful._vocab_size) and the
    compiled-artifact export's sealed ``input_vocab`` so the two can
    never drift."""
    for u in workflow.topo_order():
        if isinstance(u, Embedding):
            return int(np.shape(params[u.name]["table"])[0])
    return None


class SeqLast(Unit):
    """(B, T, ...) -> (B, ...): the final time step (e.g. next-token
    readout after causal attention)."""

    def output_spec(self, in_specs):
        s = in_specs[0]
        return Spec((s.shape[0],) + tuple(s.shape[2:]), s.dtype)

    def apply(self, params, state, xs, ctx):
        return xs[0][:, -1], state


class Reshape(Unit):
    """Reshape the per-sample trailing dims (e.g. flat 784 -> 28x28x1 for a
    conv trunk fed by a vector loader)."""

    def __init__(self, shape, name=None, inputs=("@input",)):
        super().__init__(name, inputs)
        self.shape = tuple(int(s) for s in shape)

    def output_spec(self, in_specs):
        s = in_specs[0]
        if int(np.prod(s.shape[1:])) != int(np.prod(self.shape)):
            raise ValueError(
                f"cannot reshape {s.shape[1:]} to {self.shape}")
        return Spec((s.shape[0],) + self.shape, s.dtype)

    def apply(self, params, state, xs, ctx):
        return xs[0].reshape((xs[0].shape[0],) + self.shape), state


# -- evaluators (loss units) -------------------------------------------------

class Evaluator(Unit):
    """Base loss unit: consumes (output, labels/targets); its output is the
    scalar loss; metrics are returned via state-free aux (collected by the
    Workflow). Reference: Znicz evaluator units feeding Decision."""

    is_evaluator = True

    def metrics(self, params, state, xs, ctx) -> dict:
        raise NotImplementedError


class EvaluatorSoftmax(Evaluator):
    """Softmax cross-entropy over logits + integer labels
    (reference 'evaluator' for classification). An optional third input
    "@mask" (loader-provided, 1.0 per real sample) keeps metrics exact with
    padded fixed-shape batches.

    Sequence form: logits (B, T, V) with labels (B, T) compute the
    per-position loss (next-token LM training); the per-sample mask
    broadcasts across positions and metrics count positions."""

    def __init__(self, name=None, inputs=("@input", "@labels", "@mask")):
        super().__init__(name, inputs)

    def output_spec(self, in_specs):
        return Spec((), jnp.float32)

    @staticmethod
    def _mask(xs):
        m = xs[2] if len(xs) > 2 else None
        labels = xs[1]
        if m is not None and m.ndim < labels.ndim:
            m = jnp.broadcast_to(
                m.reshape(m.shape + (1,) * (labels.ndim - m.ndim)),
                labels.shape)
        return m

    def apply(self, params, state, xs, ctx):
        loss, _ = ops.softmax_cross_entropy(xs[0], xs[1], mask=self._mask(xs))
        return loss, state

    def metrics(self, params, state, xs, ctx):
        mask = self._mask(xs)
        loss, n_err = ops.softmax_cross_entropy(xs[0], xs[1], mask=mask)
        n = mask.sum() if mask is not None else jnp.asarray(
            float(np.prod(xs[1].shape)), jnp.float32)
        return {"loss": loss, "n_err": n_err, "n_samples": n}


class EvaluatorMSE(Evaluator):
    """MSE against targets (reference MSE evaluator / autoencoder path)."""

    def __init__(self, name=None, inputs=("@input", "@targets", "@mask")):
        super().__init__(name, inputs)

    def output_spec(self, in_specs):
        return Spec((), jnp.float32)

    @staticmethod
    def _mask(xs):
        return xs[2] if len(xs) > 2 else None

    def apply(self, params, state, xs, ctx):
        loss, _ = ops.mse_loss(xs[0], xs[1], mask=self._mask(xs))
        return loss, state

    def metrics(self, params, state, xs, ctx):
        mask = self._mask(xs)
        loss, agg = ops.mse_loss(xs[0], xs[1], mask=mask)
        n = mask.sum() if mask is not None else jnp.asarray(
            xs[0].shape[0], jnp.float32)
        return {"loss": loss, "mse_sum": agg, "n_samples": n}
