"""Parallelism-aware NN units: sequence-parallel attention, pipelined
stacks, mixture-of-experts — the sp/pp/ep axes as *product features*
constructible from StandardWorkflow configs (round-1 verdict #3: these were
library functions exercised only by dryrun demos).

No reference counterpart (SURVEY.md §5.7/§2.5: the reference's only
parallel axis was the batch); the build brief makes long-context and
multi-axis distribution first-class, so these are new TPU-native designs
layered on parallel/{ring_attention,pipeline,moe}.py.

Mesh discipline: each unit reads its axis size off ``ctx.mesh`` (threaded
by Workflow.make_sharded_train_step).  On a single device — or when the
relevant mesh axis has size 1 — every unit falls back to the numerically
identical local computation, so the same config runs anywhere.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..ops import smart_uniform_init as _uniform_init
from .base import Context, Forward, Spec


class MultiHeadAttention(Forward):
    """Self-attention over (B, T, E) activations.

    Sequence parallelism: when ``ctx.mesh`` has a ``seq`` axis > 1, the
    attention core runs as ring attention (parallel/ring_attention.py) —
    K/V blocks rotate over ICI while each device holds one sequence shard.
    Otherwise the blockwise/flash local kernel handles arbitrary T on one
    device.  Projections are plain gemms GSPMD shards by rule.
    """

    stochastic = False

    def __init__(self, n_heads: int, head_dim: Optional[int] = None,
                 name=None, inputs=("@input",), *, causal: bool = True,
                 seq_axis: str = "seq", block_size: int = 512,
                 compute_dtype=None, window: Optional[int] = None,
                 n_kv_heads: Optional[int] = None, rope: bool = False,
                 residual: bool = False,
                 use_flash: Optional[bool] = None):
        super().__init__(name, inputs)
        self.n_heads = int(n_heads)
        self.head_dim = head_dim
        self.causal = causal
        self.seq_axis = seq_axis
        self.block_size = int(block_size)
        self.compute_dtype = compute_dtype
        # sliding-window width (causal local attention); None = full
        self.window = None if window is None else int(window)
        self.rope = bool(rope)  # rotary position embedding on q/k
        # y = x + attn(x): the transformer residual stream (stacked
        # attention layers can't compose circuits without it)
        self.residual = bool(residual)
        # grouped-query attention: fewer K/V heads than Q heads
        from ..ops import check_gqa_heads
        self.n_kv_heads = (self.n_heads if n_kv_heads is None
                           else int(n_kv_heads))
        check_gqa_heads(self.n_heads, self.n_kv_heads)
        # None = measured Pallas-vs-XLA pick at build shape (prepare);
        # True/False forces; falls back to the platform default
        self.use_flash = use_flash
        self._resolved_flash = use_flash
        # per-shape autotuned (block_q, block_k) for the flash kernel;
        # None = the kernel's globally-swept defaults
        self._resolved_blocks = None

    def prepare(self, in_specs):
        """Measure flash-kernel vs XLA blockwise attention fwd+bwd at
        the actual (B, T, H, D) build shape and persist the winner — the
        reference's per-device bench-and-persist discipline
        (veles/backends.py:672-731) applied to the framework's most
        important op (round-3 verdict #6)."""
        from .. import ops
        from ..config import root
        if self.use_flash is not None:
            self._resolved_flash = self.use_flash
            return
        if not bool(root.common.autotune):
            self._resolved_flash = None  # platform default at apply
            return
        if not ops.use_pallas_default():
            self._resolved_flash = False  # off-TPU: measurement-free
            return
        import numpy as np
        from ..parallel.ring_attention import blockwise_attention
        from ..runtime import autotune
        spec = in_specs[0]
        B, T, E = spec.shape
        H, Hk = self.n_heads, self.n_kv_heads
        D = self.head_dim or E // H
        dt = self.compute_dtype or spec.dtype
        # Very long sequences are the sequence-parallel territory where
        # apply() takes the ring-attention path and ignores this pick —
        # and where a full-shape fwd+bwd probe could OOM one device at
        # build time. Skip the measurement past a probe budget.
        if B * T * (H + 2 * Hk) * D > 10 ** 8:
            self._resolved_flash = None  # platform default
            return
        # block_size changes the XLA candidate's schedule, so it keys
        # the persisted winner alongside causal/window/kv-heads
        op = (f"attention_fwd_bwd_c{int(self.causal)}"
              f"_w{self.window}_hk{Hk}_bs{self.block_size}")
        shapes = [(B, T, H, D), (B, T, Hk, D), (B, T, Hk, D)]
        specs = [jax.ShapeDtypeStruct(s, dt) for s in shapes]

        def parse(name):
            # swept candidates carry their blocks in the name; a
            # pre-sweep DB record fails lookup's candidate-set check
            # and simply re-measures once
            if name.startswith("flash_"):
                bq, bk = name[len("flash_"):].split("x")
                return True, (int(bq), int(bk))
            return False, None

        # flash candidates: the global on-chip default plus per-shape
        # alternatives; dedupe by the kernel's EFFECTIVE clamped blocks
        # so tiny T doesn't measure the same program four times
        from ..ops.pallas_kernels import _flash_blocks
        cand_blocks, seen = [], set()
        for bq, bk in ((256, 1024), (512, 512), (256, 512), (128, 1024)):
            eff = _flash_blocks(T, T, bq, bk)
            if eff not in seen:
                seen.add(eff)
                cand_blocks.append((bq, bk))
        names = tuple(f"flash_{bq}x{bk}" for bq, bk in cand_blocks) \
            + ("xla",)
        cached = autotune.lookup(op, names, specs)
        if cached is not None:
            self._resolved_flash, self._resolved_blocks = parse(cached)
            return
        rng = np.random.default_rng(0)
        args = [jnp.asarray(rng.standard_normal(s), dt) for s in shapes]

        def run(use_flash, blocks=None):
            def f(q, k, v):
                # value_and_grad: the primal keeps the forward alive
                # under DCE, timing the full training cost
                return jax.value_and_grad(
                    lambda q, k, v: jnp.sum(blockwise_attention(
                        q, k, v, block_size=self.block_size,
                        causal=self.causal, window=self.window,
                        use_flash=use_flash,
                        flash_blocks=blocks).astype(jnp.float32)),
                    argnums=(0, 1, 2))(q, k, v)
            return f

        candidates = {f"flash_{bq}x{bk}": run(True, (bq, bk))
                      for bq, bk in cand_blocks}
        candidates["xla"] = run(False)
        winner = autotune.pick(op, candidates, args,
                               default=f"flash_{cand_blocks[0][0]}"
                                       f"x{cand_blocks[0][1]}")
        self._resolved_flash, self._resolved_blocks = parse(winner)

    def output_spec(self, in_specs: Sequence[Spec]) -> Spec:
        return in_specs[0]

    def init(self, key, in_specs):
        E = in_specs[0].shape[-1]
        H, Hk = self.n_heads, self.n_kv_heads
        D = self.head_dim or E // H
        if self.head_dim is None and E % H:
            raise ValueError(f"model dim {E} not divisible by {H} heads")
        kq, kk, kv, ko = jax.random.split(key, 4)
        return {
            "wq": _uniform_init(kq, (E, H * D), E),
            "wk": _uniform_init(kk, (E, Hk * D), E),
            "wv": _uniform_init(kv, (E, Hk * D), E),
            "wo": _uniform_init(ko, (H * D, E), H * D),
        }, {}

    def apply(self, params, state, xs, ctx: Context):
        from ..parallel.ring_attention import (_ring_attention_local,
                                               blockwise_attention,
                                               ring_attention)
        x = xs[0]
        B, T, E = x.shape
        H = self.n_heads
        dt = self.compute_dtype or x.dtype
        xq = x.astype(dt)
        mode = ctx.collective_mode(self.seq_axis)

        def proj(w, nh):
            return (xq @ w.astype(dt)).reshape(B, T, nh, -1)

        q = proj(params["wq"], H)
        k = proj(params["wk"], self.n_kv_heads)
        v = proj(params["wv"], self.n_kv_heads)
        if self.rope:
            from ..ops import rotary_embedding
            # manual mode: x is this rank's T-shard inside an enclosing
            # shard_map (a pipeline schedule) — rotate by GLOBAL
            # positions (rank offset); elsewhere x is logically global
            off = (jax.lax.axis_index(self.seq_axis) * T
                   if mode == "manual" else 0)
            q = rotary_embedding(q, offset=off)
            k = rotary_embedding(k, offset=off)
        if mode == "manual":
            # inside the fused-1F1B / schedule shard_map: the wrapper
            # would illegally nest, but the ring body's raw ppermutes
            # over the seq axis are legal — call it directly
            o = _ring_attention_local(q, k, v, axis_name=self.seq_axis,
                                      causal=self.causal, scale=None,
                                      window=self.window)
        elif mode == "wrapper":
            o = ring_attention(q, k, v, ctx.mesh, axis_name=self.seq_axis,
                               causal=self.causal, window=self.window)
        else:
            o = blockwise_attention(q, k, v, block_size=self.block_size,
                                    causal=self.causal, window=self.window,
                                    use_flash=self._resolved_flash,
                                    flash_blocks=self._resolved_blocks)
        y = o.reshape(B, T, -1) @ params["wo"].astype(dt)
        if self.residual:
            y = y + xq
        return y.astype(x.dtype), state


class MoEFFN(Forward):
    """Mixture-of-experts FFN over (B, T, E) or (N, E) activations.

    Expert parallelism: the expert banks shard over the ``expert`` mesh
    axis (see ``expert_rules`` below); the dispatch/combine einsums become
    all_to_all over ICI under GSPMD.  The Switch/GShard load-balance
    auxiliary loss rides the unit-state channel — Workflow._build_step sums
    ``aux_loss * aux_weight`` into the training loss automatically.
    """

    has_aux_loss = True

    def __init__(self, n_experts: int, d_hidden: int, name=None,
                 inputs=("@input",), *, top_k: int = 2,
                 capacity_factor: float = 1.25, aux_weight: float = 0.01,
                 dispatch_mode: str = "sort", expert_axis: str = "expert"):
        super().__init__(name, inputs)
        self.n_experts = int(n_experts)
        self.d_hidden = int(d_hidden)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.aux_weight = float(aux_weight)
        # "sort" (scalable scatter/gather) or "dense" (one-hot einsums);
        # see parallel/moe.py module docstring
        self.dispatch_mode = dispatch_mode
        self.expert_axis = expert_axis

    def output_spec(self, in_specs):
        return in_specs[0]

    def init(self, key, in_specs):
        from ..parallel.moe import init_moe_params
        E = in_specs[0].shape[-1]
        params = init_moe_params(key, self.n_experts, E, self.d_hidden)
        return params, {"aux_loss": jnp.zeros((), jnp.float32)}

    def apply(self, params, state, xs, ctx: Context):
        from ..parallel.moe import moe_apply, moe_apply_manual
        x = xs[0]
        flat = x.reshape(-1, x.shape[-1])
        if ctx.collective_mode(self.expert_axis) == "manual":
            # inside a pipeline-schedule shard_map with tokens sharded
            # over the expert axis: explicit all_to_all dispatch to the
            # rank owning each expert (round-4 verdict #3); GSPMD cannot
            # see inside the manual body, so the exchange is hand-written
            y, aux = moe_apply_manual(
                params, flat, axis_name=self.expert_axis,
                top_k=self.top_k, capacity_factor=self.capacity_factor)
        else:
            # ordinary jit: GSPMD lowers the dispatch/combine einsums to
            # all_to_all when the expert banks are sharded; with no
            # expert axis this IS the local dense-expert formulation
            y, aux = moe_apply(params, flat, top_k=self.top_k,
                               capacity_factor=self.capacity_factor,
                               dispatch_mode=self.dispatch_mode)
        return (y.reshape(x.shape),
                {"aux_loss": aux.astype(jnp.float32)})


class PipelineStack(Forward):
    """A stack of S stages pipelined over the ``pipe`` mesh axis.

    Two forms:

    * **Homogeneous (legacy)**: ``PipelineStack(n_stages, d_hidden)`` — S
      identical residual-MLP blocks, params stage-stacked ``(S, ...)`` and
      sharded ``P('pipe')``.
    * **Config stages (round-3)**: ``PipelineStack(stages=[[cfg, ...],
      ...])`` — each stage is an arbitrary layer-config sublist (e.g. an
      attention block ``[{"type": "attention", "residual": True}, {"type":
      "layer_norm"}]``), resolved through ``models.standard.LAYER_TYPES``.
      Stages may differ (the heterogeneous ravel+switch machinery of
      ``parallel/pipeline.py`` handles mixed param structures); every
      stage must PRESERVE the activation shape/dtype — that is what
      physically rides the pipeline ring.

    With pipe size 1 (or no mesh) stages run sequentially — the same
    math, so configs are portable.  Under ``Workflow.make_pipeline_
    train_step`` the stack trains on the fused 1F1B schedule; under plain
    AD it forwards on the GPipe schedule.  The batch is split into
    microbatches along axis 0; batch size must divide evenly.
    """

    def __init__(self, n_stages: Optional[int] = None,
                 d_hidden: Optional[int] = None, name=None,
                 inputs=("@input",), *, pipe_axis: str = "pipe",
                 n_microbatches: Optional[int] = None,
                 stages: Optional[Sequence[Sequence[dict]]] = None,
                 compute_dtype=None):
        super().__init__(name, inputs)
        self.pipe_axis = pipe_axis
        self.n_microbatches = n_microbatches
        self.stages_cfg = stages
        if stages is not None:
            self.n_stages = len(stages)
            self.d_hidden = None
            self._stage_units = [
                self._build_stage_units(i, cfg, compute_dtype)
                for i, cfg in enumerate(stages)]
            subs = [u for us in self._stage_units for u in us]
            # sub-unit aux losses surface through the stack's own aux
            # channel (weights already applied per sub-unit, so the
            # stack-level weight is 1); stochastic sub-units make the
            # stack itself stochastic for workflow bookkeeping
            self.has_aux_loss = any(
                getattr(u, "has_aux_loss", False) for u in subs)
            self.aux_weight = 1.0
            self.stochastic = any(
                getattr(u, "stochastic", False) for u in subs)
        else:
            if n_stages is None or d_hidden is None:
                raise ValueError(
                    "PipelineStack needs (n_stages, d_hidden) or stages=")
            self.n_stages = int(n_stages)
            self.d_hidden = int(d_hidden)
            self._stage_units = None
            self.has_aux_loss = False
            self.aux_weight = 1.0

    @staticmethod
    def _build_stage_units(i: int, cfg: Sequence[dict], compute_dtype):
        # Lazy import: models.standard imports this module at load time;
        # by the time a stack is instantiated the registry exists.
        from ..models.standard import COMPUTE_DTYPE_TYPES, LAYER_TYPES
        units = []
        for j, spec in enumerate(cfg):
            spec = dict(spec)
            ltype = spec.pop("type")
            lname = spec.pop("name", f"s{i}u{j}_{ltype}")
            # stage bodies are already rematerialized by both pipeline
            # schedules (GPipe wraps each stage in jax.checkpoint; 1F1B
            # recomputes inside the VJP), so a per-sub-unit remat flag
            # is a no-op here — accept and drop it for config symmetry
            spec.pop("remat", None)
            if "hyperparams" in spec:
                # per-layer optimizer hyperparams key on unit names; the
                # stack is ONE unit, so they cannot reach the optimizer
                # table — reject instead of silently dropping them
                raise ValueError(
                    f"per-layer 'hyperparams' on {lname!r} are not "
                    "supported inside pipeline stages (the stack is one "
                    "optimizer unit); set them on the stack's unit name")
            if compute_dtype is not None and ltype.startswith(
                    COMPUTE_DTYPE_TYPES):
                spec.setdefault("compute_dtype", compute_dtype)
            # Stochastic units (dropout) and aux-loss units (MoE) are
            # fine inside stages: both pipeline schedules thread a
            # per-microbatch key (fold_in(step_key, mb_index)) and an
            # aux-loss channel through the stage contract.
            units.append(LAYER_TYPES[ltype](name=lname, inputs=("@x",),
                                            **spec))
        return units

    def _thread_stage_specs(self, spec, visit=None):
        """Single source of truth for threading the activation spec
        through every stage sub-unit (prepare/output_spec/init all need
        this walk). ``visit(unit, in_spec)`` runs before each unit's
        output_spec advances the spec; returns per-stage final specs."""
        outs = []
        for units in self._stage_units:
            s = spec
            for u in units:
                if visit is not None:
                    visit(u, s)
                s = u.output_spec([s])
            outs.append(s)
        return outs

    def prepare(self, in_specs):
        # Composite unit: Workflow.build only calls prepare() on
        # top-level units, so the stack must propagate it to its stage
        # sub-units (an LRN with method="auto" inside a stage resolves
        # here, never reaching trace/export as "auto").
        if self._stage_units is not None:
            self._thread_stage_specs(
                in_specs[0], lambda u, s: u.prepare([s]))

    def output_spec(self, in_specs):
        if self._stage_units is not None:
            spec = in_specs[0]
            for i, s in enumerate(self._thread_stage_specs(spec)):
                if (tuple(s.shape), s.dtype) != (tuple(spec.shape),
                                                 spec.dtype):
                    raise ValueError(
                        f"pipeline stage {i} must preserve the activation "
                        f"spec {tuple(spec.shape)}/{spec.dtype} (it rides "
                        f"the ring), got {tuple(s.shape)}/{s.dtype}")
        return in_specs[0]

    def init(self, key, in_specs):
        if self._stage_units is not None:
            params = {}
            keys = jax.random.split(key, self.n_stages)
            for i, (units, k) in enumerate(zip(self._stage_units, keys)):
                spec = in_specs[0]
                sp, uks = {}, jax.random.split(k, max(len(units), 1))
                for u, uk in zip(units, uks):
                    p, s = u.init(uk, [spec])
                    # an aux-loss channel is a per-step OUTPUT, not
                    # persistent state — it rides the stack's own aux
                    # accumulator, so it needs no stage state
                    if s and set(s) - {"aux_loss"}:
                        raise ValueError(
                            f"stateful unit {u.name!r} inside a pipeline "
                            "stage is unsupported (stage state does not "
                            "ride the ring)")
                    if p:
                        sp[u.name] = p
                    spec = u.output_spec([spec])
                params[f"s{i}"] = sp
            state = ({"aux_loss": jnp.zeros((), jnp.float32)}
                     if self.has_aux_loss else {})
            return params, state
        E = in_specs[0].shape[-1]
        H = self.d_hidden
        keys = jax.random.split(key, self.n_stages)

        def one(k):
            k1, k2 = jax.random.split(k)
            return {"w1": _uniform_init(k1, (E, H), E),
                    "w2": _uniform_init(k2, (H, E), H)}

        from ..parallel.pipeline import stack_stage_params
        stacked = stack_stage_params([one(k) for k in keys])
        # flat per-unit param dict (optimizer contract); the leading axis
        # of each stage_* array is the stage axis sharded over 'pipe'
        return {"stage_w1": stacked["w1"], "stage_w2": stacked["w2"]}, {}

    @staticmethod
    def _stage_fn(p, x):
        return x + jax.nn.relu(x @ p["w1"]) @ p["w2"]

    # -- per-stage access (the fused-1F1B compiler's contract,
    # parallel/pipeline_compile.py) ---------------------------------------
    def stage_param_slice(self, params, i: int):
        """Stage i's param pytree, as stage_apply(i, ...) consumes it."""
        if self._stage_units is not None:
            return params[f"s{i}"]
        return {"w1": params["stage_w1"][i], "w2": params["stage_w2"][i]}

    def restack_stage_grads(self, glist):
        """Inverse of stage_param_slice over a list of per-stage grads."""
        if self._stage_units is not None:
            return {f"s{i}": g for i, g in enumerate(glist)}
        return {"stage_w1": jnp.stack([g["w1"] for g in glist]),
                "stage_w2": jnp.stack([g["w2"] for g in glist])}

    def stage_apply(self, i: int, p, x, ctx: Context):
        """Apply stage i's computation to one activation block."""
        return self.stage_apply_aux(i, p, x, ctx)[0]

    def stage_apply_aux(self, i: int, p, x, ctx: Context):
        """Stage i on one activation block -> ``(y, aux)`` where ``aux``
        is the weighted sum of the stage's unit aux losses (MoE load
        balance) — the fused-1F1B compiler's stage contract."""
        aux = jnp.zeros((), jnp.float32)
        if self._stage_units is not None:
            for u in self._stage_units[i]:
                x, st = u.apply(p.get(u.name, {}), {}, [x], ctx)
                if getattr(u, "has_aux_loss", False):
                    aux = aux + u.aux_weight * st["aux_loss"]
            return x, aux
        return self._stage_fn(p, x), aux

    def _inner_ctx(self, ctx: Context) -> Context:
        # Stage bodies execute inside pipeline_apply's shard_map; a unit
        # starting its own collective there (ring attention reading
        # ctx.mesh) would illegally nest shard_maps — so stage units see
        # mesh=None and use their local formulations.
        return Context(train=ctx.train, key=ctx.key, mesh=None)

    def apply(self, params, state, xs, ctx: Context):
        x = xs[0]
        S = ctx.axis_size(self.pipe_axis)
        n_mb = self.n_microbatches or S
        if S > 1 and S != self.n_stages:
            if self.n_stages % S == 0 and not ctx.train:
                # interleaved fused training (n_stages = v·S virtual
                # chunks): the GPipe forward has no interleaved
                # schedule, so EVAL/PREDICT run the numerically
                # identical sequential form (GSPMD still shards the
                # batch over the data axes).  At TRAIN time a mismatch
                # stays an error — silently idling the pipe axis would
                # be a large hidden perf cliff.
                S = 1
            else:
                raise ValueError(
                    f"PipelineStack has {self.n_stages} stages but the "
                    f"{self.pipe_axis!r} mesh axis is {S}"
                    + (" (interleaved stacks train via "
                       "pipeline_microbatches + pipeline_interleave)"
                       if self.n_stages % S == 0 else ""))
        if S > 1:
            if x.shape[0] % n_mb and ctx.train:
                # At eval/predict an indivisible batch (single-sample
                # serving) falls through to the numerically identical
                # sequential path below; during TRAINING it is a config
                # error — silently idling the whole pipe axis would be a
                # large hidden perf cliff.
                raise ValueError(
                    f"batch {x.shape[0]} not divisible into {n_mb} "
                    "microbatches")
        rich = self.has_aux_loss or getattr(self, "stochastic", False)
        if S > 1 and x.shape[0] % n_mb == 0:
            from ..parallel.pipeline import pick_batch_axes, pipeline_apply
            B = x.shape[0]
            xm = x.reshape((n_mb, B // n_mb) + x.shape[1:])
            dp = pick_batch_axes(
                {a: ctx.axis_size(a) for a in ("data", "fsdp")}, B // n_mb)
            if self._stage_units is not None and rich:
                # keyed schedule: per-microbatch keys fold_in(step_key,
                # mb) — identical to the fused 1F1B derivation, so both
                # schedules draw the same dropout masks — and sub-unit
                # aux losses return through the stack's aux channel
                rng = ctx.key if ctx.key is not None else jax.random.key(0)
                fns = [(lambda p, x, k, _i=i: self.stage_apply_aux(
                            _i, p, x,
                            Context(train=ctx.train, key=k, mesh=None)))
                       for i in range(self.n_stages)]
                plist = [params[f"s{i}"] for i in range(self.n_stages)]
                y, aux = pipeline_apply(fns, plist, xm, ctx.mesh,
                                        axis_name=self.pipe_axis,
                                        batch_axes=tuple(dp), rng=rng)
                return y.reshape(x.shape), (
                    {"aux_loss": aux} if self.has_aux_loss else state)
            if self._stage_units is not None:
                ictx = self._inner_ctx(ctx)
                fns = [(lambda p, x, _i=i: self.stage_apply(_i, p, x, ictx))
                       for i in range(self.n_stages)]
                plist = [params[f"s{i}"] for i in range(self.n_stages)]
                y = pipeline_apply(fns, plist, xm, ctx.mesh,
                                   axis_name=self.pipe_axis,
                                   batch_axes=tuple(dp))
            else:
                stages = {"w1": params["stage_w1"],
                          "w2": params["stage_w2"]}
                y = pipeline_apply(self._stage_fn, stages, xm, ctx.mesh,
                                   axis_name=self.pipe_axis,
                                   batch_axes=tuple(dp))
            return y.reshape(x.shape), state
        if self._stage_units is not None:
            aux_t = jnp.zeros((), jnp.float32)
            for i in range(self.n_stages):
                x, a = self.stage_apply_aux(i, params[f"s{i}"], x, ctx)
                aux_t = aux_t + a
            return x, ({"aux_loss": aux_t} if self.has_aux_loss else state)
        stages = {"w1": params["stage_w1"], "w2": params["stage_w2"]}

        # sequential fallback: scan over the stage axis
        def body(h, p):
            return self._stage_fn(p, h), None

        y, _ = jax.lax.scan(body, x, stages)
        return y, state


def expert_rules(axis: str = "expert"):
    """Sharding rule for MoEFFN params: expert banks split on the expert
    axis, router replicated (compose with other rules via
    parallel.mesh.compose_rules)."""
    from jax.sharding import PartitionSpec as P

    def rule(path, spec):
        if len(path) >= 2 and path[-1] in ("w1", "w2") \
                and spec.ndim == 3:
            return P(axis)
        return P()

    return rule


def pipeline_rules(axis: str = "pipe"):
    """Sharding rule for PipelineStack params: stage axis over 'pipe'."""
    from jax.sharding import PartitionSpec as P

    def rule(path, spec):
        if path and path[-1].startswith("stage_"):
            return P(axis, *([None] * (spec.ndim - 1)))
        return P()

    return rule
