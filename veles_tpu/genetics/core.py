"""Genetic hyperparameter optimization over config Range tuneables.

Reference parity: veles/genetics/ — GA over ``Range(...)`` markers inside
the config tree (veles/genetics/config.py:45-223: "config doubles as the
hyperparameter search space"), population with roulette/tournament
selection, multiple crossover and mutation operators
(veles/genetics/core.py:371-460), each chromosome evaluated as a full
training run (optimization_workflow.py:70-339).

Redesign: evaluations are a plain ``fitness_fn(config) -> float`` callback
(lower = better, e.g. validation error). The reference farmed evaluations
to slaves over ZMQ (optimization_workflow.py:70-339); the rebuild keeps the
farm-out as an optional ``evaluator`` hook that receives the whole batch of
unevaluated configs per generation: ``SubprocessEvaluator`` runs each
config as a standalone CLI training on a bounded worker pool
(parallel/pool.py), which is exactly the reference's
one-standalone-run-per-chromosome semantic without the master/slave
plumbing. The default stays the sequential in-process loop (one training
already fills the device mesh)."""

from __future__ import annotations

import dataclasses
import json
import math
import os
import tempfile
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..config import Config, Range, collect_tuneables
from ..logger import Logger


@dataclasses.dataclass
class Individual:
    genome: Dict[str, object]       # path -> value
    fitness: float = math.inf
    evaluated: bool = False


class GeneticOptimizer(Logger):
    """GA driver.

    selection: "tournament" | "roulette";
    crossover ops: uniform, single-point (the reference's "pointed"),
    blend, arithmetic mean, geometric mean (reference operator set:
    veles/genetics/core.py:371-460);
    mutation ops: gaussian (continuous), reset (any), creep (integers).

    ``binary_bits=N`` switches to the reference's binary-code mode:
    numeric genes are Gray-free fixed-point N-bit codes over their
    range, crossover cuts the concatenated bitstring, and mutation
    flips individual bits.
    """

    def __init__(self, config: Config,
                 fitness_fn: Optional[Callable[[Config], float]] = None, *,
                 population_size: int = 16, generations: int = 10,
                 elite: int = 2, crossover_rate: float = 0.9,
                 mutation_rate: float = 0.15,
                 selection: str = "tournament",
                 tournament_k: int = 3, seed: int = 0,
                 on_generation: Optional[Callable] = None,
                 binary_bits: Optional[int] = None,
                 evaluator: Optional[Callable[
                     [List[Config], List[Dict[str, object]]],
                     Sequence[float]]] = None):
        self.config = config
        self.tuneables = collect_tuneables(config)
        if not self.tuneables:
            raise ValueError("config contains no Range tuneables")
        if fitness_fn is None and evaluator is None:
            raise ValueError("need fitness_fn or evaluator")
        self.fitness_fn = fitness_fn
        self.evaluator = evaluator
        self.population_size = population_size
        self.generations = generations
        self.elite = elite
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.selection = selection
        self.tournament_k = tournament_k
        self.seed = int(seed)
        self.rng = np.random.default_rng(seed)
        self.on_generation = on_generation
        self.binary_bits = binary_bits
        self.history: List[dict] = []
        self.best: Optional[Individual] = None

    # -- deterministic replay -----------------------------------------------
    def generation_rng(self, generation: int) -> np.random.Generator:
        """The RNG stream for one generation's variation ops, derived from
        ``(seed, generation)`` alone.  ``run()`` draws the initial random
        population from ``generation_rng(0)`` and breeds generation ``g``
        from ``generation_rng(g)``, so any generation's genomes replay
        bitwise given the seed and the previous generation's evaluated
        population — the contract crash-safe experiment resume relies on
        (experiments/policies.py re-proposes instead of persisting
        genomes it can re-derive)."""
        return np.random.default_rng([self.seed, int(generation)])

    # -- genome ops ---------------------------------------------------------
    def _random_value(self, p: str, r: Range,
                      rng: Optional[np.random.Generator] = None):
        rng = self.rng if rng is None else rng
        if r.choices is not None:
            return r.choices[rng.integers(len(r.choices))]
        lo, hi = self._gene_bounds(p)
        v = rng.uniform(lo, hi)
        return int(round(v)) if r.integer else float(v)

    def random_individual(self, rng: Optional[np.random.Generator] = None
                          ) -> Individual:
        return Individual({p: self._random_value(p, r, rng)
                           for p, r in self.tuneables.items()})

    def seed_individual(self) -> Individual:
        """The config's current values — always in the initial population
        (reference: the original config is generation 0's elite)."""
        return Individual({p: r.value for p, r in self.tuneables.items()})

    # -- binary-code mode (reference: BinaryChromosome) ---------------------
    def _gene_bounds(self, p: str):
        r = self.tuneables[p]
        if r.choices is not None:
            return 0, len(r.choices) - 1
        lo = r.min_value if r.min_value is not None else r.value * 0.1
        hi = r.max_value if r.max_value is not None else r.value * 10.0
        return lo, hi

    def encode_bits(self, genome: Dict[str, object]) -> np.ndarray:
        """Concatenated fixed-point bit code of all genes."""
        nb = self.binary_bits
        out = []
        for p, r in self.tuneables.items():
            lo, hi = self._gene_bounds(p)
            if r.choices is not None:
                q = r.choices.index(genome[p]) \
                    if genome[p] in r.choices else 0
            else:
                span = (hi - lo) or 1.0
                q = int(round((float(genome[p]) - lo) / span
                              * (2 ** nb - 1)))
            q = int(np.clip(q, 0, 2 ** nb - 1))
            out.extend((q >> i) & 1 for i in reversed(range(nb)))
        return np.asarray(out, np.uint8)

    def decode_bits(self, bits: np.ndarray) -> Dict[str, object]:
        nb = self.binary_bits
        genome, off = {}, 0
        for p, r in self.tuneables.items():
            q = 0
            for bit in bits[off:off + nb]:
                q = (q << 1) | int(bit)
            off += nb
            lo, hi = self._gene_bounds(p)
            if r.choices is not None:
                genome[p] = r.choices[min(q, len(r.choices) - 1)]
            else:
                v = lo + (hi - lo) * q / (2 ** nb - 1)
                genome[p] = r.clip(int(round(v)) if r.integer else float(v))
        return genome

    def crossover(self, a: Individual, b: Individual,
                  rng: Optional[np.random.Generator] = None) -> Individual:
        rng = self.rng if rng is None else rng
        if self.binary_bits:
            # binary-code single-point: cut the concatenated bitstring
            ba, bb = self.encode_bits(a.genome), self.encode_bits(b.genome)
            cut = rng.integers(1, max(len(ba), 2))
            return Individual(self.decode_bits(
                np.concatenate([ba[:cut], bb[cut:]])))
        paths = list(self.tuneables)
        child = {}
        op = rng.integers(5)
        if op == 0:      # uniform
            for p in paths:
                child[p] = a.genome[p] if rng.random() < 0.5 \
                    else b.genome[p]
        elif op == 1:    # single-point (reference "pointed")
            cut = rng.integers(1, max(len(paths), 2))
            for i, p in enumerate(paths):
                child[p] = a.genome[p] if i < cut else b.genome[p]
        elif op in (2, 3, 4):
            # numeric combinators; categorical genes fall back to uniform
            for p in paths:
                r = self.tuneables[p]
                va, vb = a.genome[p], b.genome[p]
                if r.choices is not None or not isinstance(va, (int, float)):
                    child[p] = va if rng.random() < 0.5 else vb
                    continue
                if op == 2:      # blend: random convex combination
                    t = rng.random()
                    v = va * t + vb * (1 - t)
                elif op == 3:    # arithmetic mean (reference :409)
                    v = (va + vb) / 2.0
                else:            # geometric mean (reference :430); falls
                    # back to arithmetic when signs differ / zero-crossing
                    if va * vb > 0:
                        v = math.copysign(math.sqrt(va * vb), va)
                    else:
                        v = (va + vb) / 2.0
                child[p] = r.clip(int(round(v)) if r.integer else float(v))
        return Individual(child)

    def mutate(self, ind: Individual,
               rng: Optional[np.random.Generator] = None) -> Individual:
        rng = self.rng if rng is None else rng
        if self.binary_bits:
            # bit-flip mutation: expected flips per genome track the
            # gene-level mutation_rate
            bits = self.encode_bits(ind.genome)
            rate = self.mutation_rate / self.binary_bits
            flips = rng.random(len(bits)) < rate
            bits = bits ^ flips.astype(np.uint8)
            return Individual(self.decode_bits(bits))
        g = dict(ind.genome)
        for p, r in self.tuneables.items():
            if rng.random() >= self.mutation_rate:
                continue
            if r.choices is not None:
                g[p] = r.choices[rng.integers(len(r.choices))]
            elif r.integer:
                lo = r.min_value if r.min_value is not None else g[p] - 5
                hi = r.max_value if r.max_value is not None else g[p] + 5
                step = max(1, int((hi - lo) * 0.1))
                g[p] = r.clip(g[p] + int(rng.integers(-step, step + 1)))
            else:
                lo = r.min_value if r.min_value is not None else g[p] * 0.1
                hi = r.max_value if r.max_value is not None else g[p] * 10
                sigma = (hi - lo) * 0.1
                g[p] = r.clip(float(g[p] + rng.normal(0, sigma)))
        return Individual(g)

    # -- selection ----------------------------------------------------------
    def _select(self, pop: List[Individual],
                rng: Optional[np.random.Generator] = None) -> Individual:
        rng = self.rng if rng is None else rng
        if self.selection == "tournament":
            idx = rng.choice(len(pop), size=self.tournament_k,
                             replace=False)
            return min((pop[i] for i in idx), key=lambda i: i.fitness)
        # roulette on inverse fitness (lower fitness = larger slice)
        inv = np.array([1.0 / (1e-9 + i.fitness) for i in pop])
        probs = inv / inv.sum()
        return pop[rng.choice(len(pop), p=probs)]

    # -- evaluation ---------------------------------------------------------
    def materialize(self, genome: Dict[str, object]) -> Config:
        cfg = Config()
        cfg.update(self.config.to_dict(unwrap_ranges=True))
        for p, v in genome.items():
            cfg.set_path(p, v)
        return cfg

    def _evaluate_all(self, pop: List[Individual]) -> None:
        """Evaluate every not-yet-evaluated individual — as one batch when
        an ``evaluator`` is installed (parallel farm-out), else one by one
        through ``fitness_fn``."""
        todo = [i for i in pop if not i.evaluated]
        if not todo:
            return
        cfgs = [self.materialize(i.genome) for i in todo]
        if self.evaluator is not None:
            # contract: evaluator(materialized_configs, genomes) — genomes
            # let override-style evaluators rerun the original config file
            # with path=value args instead of dumping whole configs.
            fits = self.evaluator(cfgs, [i.genome for i in todo])
            if len(fits) != len(todo):
                raise ValueError(
                    f"evaluator returned {len(fits)} fitnesses for "
                    f"{len(todo)} configs; score failed runs as math.inf "
                    "instead of dropping them")
        else:
            fits = [self.fitness_fn(c) for c in cfgs]
        for ind, fit in zip(todo, fits):
            ind.fitness = float(fit)
            ind.evaluated = True

    # -- breeding -----------------------------------------------------------
    def breed(self, pop: List[Individual],
              rng: Optional[np.random.Generator] = None
              ) -> List[Individual]:
        """Produce the next population from an evaluated one: elites carry
        over (still evaluated — they are never retrained), the rest come
        from selection + crossover/copy + mutation.  With ``rng`` from
        ``generation_rng(g)`` the offspring are a pure function of ``pop``
        and ``(seed, g)``."""
        rng = self.rng if rng is None else rng
        ranked = sorted(pop, key=lambda i: i.fitness)
        nxt = ranked[:self.elite]
        while len(nxt) < self.population_size:
            if rng.random() < self.crossover_rate:
                child = self.crossover(self._select(ranked, rng),
                                       self._select(ranked, rng), rng)
            else:
                child = dataclasses.replace(
                    self._select(ranked, rng),
                    fitness=math.inf, evaluated=False)
            nxt.append(self.mutate(child, rng))
        return nxt

    # -- main loop ----------------------------------------------------------
    def run(self) -> Individual:
        g0 = self.generation_rng(0)
        pop = [self.seed_individual()] + [
            self.random_individual(g0)
            for _ in range(self.population_size - 1)]
        for gen in range(self.generations):
            self._evaluate_all(pop)
            pop.sort(key=lambda i: i.fitness)
            if self.best is None or pop[0].fitness < self.best.fitness:
                self.best = dataclasses.replace(pop[0])
            self.history.append({
                "generation": gen,
                "best": pop[0].fitness,
                "mean": float(np.mean([i.fitness for i in pop])),
                "best_genome": dict(pop[0].genome)})
            self.info("gen %d: best=%.5f mean=%.5f", gen, pop[0].fitness,
                      self.history[-1]["mean"])
            if self.on_generation is not None:
                self.on_generation(gen, pop)
            if gen == self.generations - 1:
                break
            pop = self.breed(pop, self.generation_rng(gen + 1))
        return self.best


class SubprocessEvaluator(Logger):
    """Farm chromosome evaluations out as standalone CLI trainings.

    With ``base_config`` (a workflow config file path), each genome becomes
    ``python -m veles_tpu <base_config> path=value ... [extra_argv...]`` —
    inline overrides, so executed-Python configs with ``create()`` keep
    working. Without it, each materialized config is dumped to a temp JSON
    and run directly. Runs land on a bounded pool of ``n_workers``
    subprocesses (parallel/pool.py CliRunner) — the reference's
    one-standalone-run-per-chromosome farm-out (reference:
    veles/genetics/optimization_workflow.py:70-339) without master/slave
    plumbing. Fitness = the run's ``best_value``; failed runs score +inf
    (the reference likewise dropped failed evaluations rather than
    aborting the GA)."""

    def __init__(self, extra_argv: Sequence[str] = (), *,
                 base_config: Optional[str] = None,
                 n_workers: int = 1, env: Optional[Dict[str, str]] = None,
                 fitness_key: str = "best_value",
                 timeout: Optional[float] = None):
        from ..parallel.pool import CliRunner
        self.extra_argv = list(extra_argv)
        self.base_config = base_config
        self.fitness_key = fitness_key
        self.runner = CliRunner(n_workers=n_workers, env=env,
                                timeout=timeout)

    def __call__(self, configs: List[Config],
                 genomes: Optional[List[Dict[str, object]]] = None
                 ) -> List[float]:
        paths, jobs = [], []
        if self.base_config is not None and genomes is not None:
            for genome in genomes:
                ovs = [f"{p}={json.dumps(v)}" for p, v in genome.items()]
                jobs.append([self.base_config, *ovs, *self.extra_argv])
        else:
            for cfg in configs:
                fd, path = tempfile.mkstemp(prefix="veles_ga_",
                                            suffix=".json")
                with os.fdopen(fd, "w") as f:
                    json.dump(cfg.to_dict(), f)
                paths.append(path)
                jobs.append([path, *self.extra_argv])
        try:
            results = self.runner.run_jobs(jobs)
        finally:
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        fits = []
        for res in results:
            if "error" in res or self.fitness_key not in res:
                self.warning("evaluation failed: %s",
                             res.get("error", "no fitness in result")[:300])
                fits.append(math.inf)
            else:
                fits.append(float(res[self.fitness_key]))
        return fits
