from .core import GeneticOptimizer, Individual
