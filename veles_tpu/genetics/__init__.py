from .core import (GeneticOptimizer, Individual,
                   SubprocessEvaluator)
