"""Pickle-free wire format for socket payloads.

The reference streamed pickles over its ZMQ transport
(veles/txzmq/connection.py:140-143) and trusted the network; round-1 of
this rebuild kept that and the advisor flagged it — ``pickle.loads`` on
bytes from any connector is arbitrary code execution.  This module is the
replacement: a restricted serializer that can represent exactly

* JSON scalars (``None``/bool/int/float/str),
* lists / dicts (string keys) of the above,
* numpy arrays of non-object dtype (raw buffer + dtype + shape).

Frame layout: ``u32 header_len | u32 sizes_len | header_json |
sizes_json | buf0 | buf1 | ...`` where ``sizes_json`` is the list of
buffer byte lengths and arrays in the structure are replaced by
``{"\\u0000nd": i, dtype, shape}`` placeholders indexing the
concatenated raw buffers.  Deserialization never
constructs arbitrary objects — worst case a hostile peer hands us wrong
numbers, never code.
"""

from __future__ import annotations

import json
import struct
from typing import Any, List, Tuple

import numpy as np

# Placeholder key; NUL-prefixed so it cannot collide with normal payload dict keys
# that callers build from identifiers.
_ND = "\x00nd"

#: refuse frames larger than this (hostile length prefix → OOM guard)
MAX_FRAME = 1 << 30


class WireError(ValueError):
    pass


def _encode(obj: Any, bufs: List[bytes]) -> Any:
    if isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise WireError("object arrays are not wire-serializable")
        idx = len(bufs)
        bufs.append(np.ascontiguousarray(obj).tobytes())
        return {_ND: idx, "dtype": obj.dtype.str, "shape": list(obj.shape)}
    if isinstance(obj, (np.generic,)):
        return _encode(np.asarray(obj), bufs)
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if not isinstance(k, str):
                raise WireError(f"non-string dict key {k!r}")
            if k.startswith("\x00"):
                raise WireError("reserved key prefix")
            out[k] = _encode(v, bufs)
        return out
    if isinstance(obj, (list, tuple)):
        return [_encode(v, bufs) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    raise WireError(f"type {type(obj).__name__} is not wire-serializable")


def _decode(obj: Any, bufs: List[Tuple[int, int]], data: bytes) -> Any:
    if isinstance(obj, dict):
        if _ND in obj:
            idx = obj[_ND]
            if not isinstance(idx, int) or not 0 <= idx < len(bufs):
                raise WireError("bad buffer index")
            # Hostile headers can be malformed in every field; the module
            # contract is "malformed frame ⇒ WireError", never a raw
            # ValueError/KeyError escaping to the caller.
            try:
                dtype = np.dtype(str(obj["dtype"]))
                if dtype.hasobject:
                    raise WireError("object dtype refused")
                shape = tuple(int(s) for s in obj["shape"])
                start, end = bufs[idx]
                arr = np.frombuffer(data[start:end], dtype=dtype)
                return arr.reshape(shape).copy()
            except WireError:
                raise
            except (TypeError, KeyError, ValueError, OverflowError) as e:
                raise WireError(f"bad array header: {e}") from None
        return {k: _decode(v, bufs, data) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v, bufs, data) for v in obj]
    return obj


def dumps(payload: Any) -> bytes:
    """Serialize ``payload`` to a self-contained frame body."""
    bufs: List[bytes] = []
    header = json.dumps(_encode(payload, bufs),
                        separators=(",", ":")).encode("utf-8")
    sizes = [len(b) for b in bufs]
    head = json.dumps(sizes, separators=(",", ":")).encode("utf-8")
    return (struct.pack("<II", len(header), len(head))
            + header + head + b"".join(bufs))


def loads(data: bytes) -> Any:
    """Deserialize a frame body produced by :func:`dumps`."""
    if len(data) < 8:
        raise WireError("short frame")
    hlen, slen = struct.unpack("<II", data[:8])
    if 8 + hlen + slen > len(data):
        raise WireError("truncated header")
    try:
        header = json.loads(data[8:8 + hlen].decode("utf-8"))
        sizes = json.loads(data[8 + hlen:8 + hlen + slen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError, RecursionError) as e:
        raise WireError(f"bad header: {e}") from None
    if not isinstance(sizes, list):
        raise WireError("bad size table")
    offsets: List[Tuple[int, int]] = []
    pos = 8 + hlen + slen
    for s in sizes:
        if not isinstance(s, int) or s < 0 or pos + s > len(data):
            raise WireError("buffer overruns frame")
        offsets.append((pos, pos + s))
        pos += s
    return _decode_checked(header, offsets, data)


def _decode_checked(header, offsets, data) -> Any:
    # A hostile header like "[[[[...1...]]]]" passes json.loads but can
    # blow the stack inside _decode — that must surface as WireError, not
    # RecursionError (receivers catch only WireError).
    try:
        return _decode(header, offsets, data)
    except RecursionError:
        raise WireError("header nesting too deep") from None
