"""Interactive use: callable module + in-training Shell.

Reference parity:

* ``import veles; veles("workflow.py", "config.py")`` — the reference
  replaced its module object with a callable ``VelesModule``
  (veles/__init__.py:126-189) that drove the same path as the CLI.
  ``veles_tpu`` does the same via ``run()`` here, wired to the module's
  ``__call__`` in ``veles_tpu/__init__.py``.
* ``Shell`` — the reference embedded IPython inside a running workflow as
  a unit (veles/interaction.py:49). Here Shell is an epoch callback the
  Trainer invokes through the recorder interface: every ``interval``
  epochs (or on demand) it drops into an interactive console with the
  trainer/workflow/state in scope. Gated to interactive stdin — under a
  driver/CI it degrades to a no-op with a log line instead of hanging on
  input().
"""

from __future__ import annotations

import sys
from typing import Optional

from .logger import Logger


def run(config: str, *overrides, argv=(), **kwargs):
    """Programmatic equivalent of ``python -m veles_tpu <config> ...``:
    ``veles_tpu("cfg.py", "root.loader.name=mnist", max_epochs=3)``.

    kwargs become ``--key value`` flags (underscores -> dashes; True means
    a bare flag). Returns the CLI exit code.
    """
    from .__main__ import main
    args = [config, *overrides, *argv]
    for key, val in kwargs.items():
        flag = "--" + key.replace("_", "-")
        if val is True:
            args.append(flag)
        elif val is False or val is None:
            continue  # omitted flag, not "--flag False"
        else:
            args += [flag, str(val)]
    return main(args)


class Shell(Logger):
    """Interactive console breakpoints inside a training run.

    Pass as (or chain behind) the Trainer's ``recorder``: its ``record``
    hook fires each epoch. When stdin is a TTY and the epoch matches
    ``interval``, opens IPython if available, else ``code.interact``,
    with ``trainer``, ``workflow``, ``wstate`` and the latest metrics in
    the namespace. Exiting the console resumes training.
    """

    def __init__(self, trainer=None, *, interval: int = 0,
                 chain=None):
        self.trainer = trainer
        self.interval = int(interval)  # 0 = only explicit .interact()
        self.chain = chain  # optional downstream recorder

    @property
    def series(self):
        """Delegate to the chained recorder so Publisher.gather still sees
        the metric series when Shell wraps a MetricsRecorder."""
        return getattr(self.chain, "series", None) if self.chain else None

    # recorder interface ---------------------------------------------------
    def record(self, step: int, **values) -> None:
        if self.chain is not None:
            self.chain.record(step, **values)
        if self.interval and step and step % self.interval == 0:
            self.interact(step=step, **values)

    def close(self):
        if self.chain is not None and hasattr(self.chain, "close"):
            self.chain.close()

    # ----------------------------------------------------------------------
    def interact(self, **extra) -> None:
        if not sys.stdin.isatty():
            self.info("Shell: stdin is not a TTY, skipping interactive "
                      "breakpoint (epoch data: %s)", extra)
            return
        ns = dict(extra)
        if self.trainer is not None:
            ns.update(trainer=self.trainer,
                      workflow=self.trainer.workflow,
                      wstate=self.trainer.wstate,
                      loader=self.trainer.loader)
        banner = ("veles_tpu Shell — objects in scope: "
                  + ", ".join(sorted(ns)))
        try:
            import IPython
            IPython.embed(banner1=banner, user_ns=ns,
                          colors="neutral")
        except ImportError:
            import code
            code.interact(banner=banner, local=ns)
