"""Live graphics channel: in-process publisher → separate renderer process.

Reference parity: GraphicsServer broadcast plot payloads on a ZMQ PUB
socket (veles/graphics_server.py:65,153 — plotter units pickle themselves,
veles/plotter.py:147-158) and a forked GraphicsClient process rendered them
with matplotlib (veles/graphics_client.py:84).

TPU redesign: the payloads are tiny host-side scalars/arrays (metrics,
confusion matrices, weight tiles) published *outside* the jit step — the
device pipeline is never synced for plotting.  Transport is a plain TCP
fan-out socket (stdlib; no zmq dependency): length-prefixed frames in the
pickle-free :mod:`veles_tpu.wire` format (JSON header + raw array bytes),
PUB semantics — slow or dead subscribers are dropped, never block training
(the reference used ZMQ PUB for exactly this property).  Unlike the
reference's pickle streams, a hostile peer can at worst inject wrong
numbers, never code.

Run a renderer:  ``python -m veles_tpu.graphics <endpoint> --out plots/``
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional

from . import wire
from .logger import Logger

_MAGIC = b"VTPL"  # frame: magic + u32 length + wire body


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_MAGIC + struct.pack("<I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_frame(sock: socket.socket):
    head = _recv_exact(sock, 8)
    if head is None or head[:4] != _MAGIC:
        return None
    (length,) = struct.unpack("<I", head[4:])
    if length > wire.MAX_FRAME:
        raise wire.WireError(f"frame length {length} exceeds cap")
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return wire.loads(body)


class GraphicsServer(Logger):
    """Fan-out publisher of plot payloads (reference:
    veles/graphics_server.py:65 ZMQ PUB endpoints)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(8)
        self.endpoint = "tcp://%s:%d" % self._listener.getsockname()[:2]
        self._subs: List[socket.socket] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        self.info("graphics server at %s", self.endpoint)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._listener.settimeout(0.2)
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(1.0)
            with self._lock:
                self._subs.append(conn)

    def publish(self, payload: Dict) -> None:
        """Broadcast one payload; drop subscribers that can't keep up
        (PUB semantics — plotting never blocks training)."""
        data = wire.dumps(payload)
        if len(data) > wire.MAX_FRAME:
            # Receivers cap frames at MAX_FRAME; silently shipping an
            # undeliverable frame (or overflowing the u32 length prefix)
            # must never crash or stall the training loop.
            self.warning("payload of %d bytes exceeds frame cap; dropped",
                         len(data))
            return
        with self._lock:
            dead = []
            for s in self._subs:
                try:
                    _send_frame(s, data)
                except OSError:
                    dead.append(s)
            for s in dead:
                self._subs.remove(s)
                try:
                    s.close()
                except OSError:
                    pass

    @property
    def n_subscribers(self) -> int:
        with self._lock:
            return len(self._subs)

    def close(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for s in self._subs:
                try:
                    s.close()
                except OSError:
                    pass
            self._subs.clear()


def subscribe(endpoint: str) -> socket.socket:
    """Connect a subscriber socket to ``tcp://host:port``."""
    assert endpoint.startswith("tcp://"), endpoint
    host, _, port = endpoint[6:].partition(":")
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect((host, int(port)))
    return sock


class GraphicsClient(Logger):
    """Subscriber that renders payloads with matplotlib-Agg (reference:
    veles/graphics_client.py:84 — separate process so rendering never
    steals cycles from the training loop)."""

    def __init__(self, endpoint: str, out_dir: str = "plots"):
        self.endpoint = endpoint
        self.out_dir = out_dir
        self.series: Dict[str, List[float]] = {}

    def run(self, max_payloads: Optional[int] = None) -> int:
        import os
        os.makedirs(self.out_dir, exist_ok=True)
        sock = subscribe(self.endpoint)
        n = 0
        try:
            while max_payloads is None or n < max_payloads:
                try:
                    payload = recv_frame(sock)
                except wire.WireError as e:
                    # Frame boundary is lost after a corrupt frame: drop
                    # the connection, keep the renderer process alive.
                    self.warning("dropping connection on bad frame: %s", e)
                    break
                if payload is None:
                    break
                self.handle(payload)
                n += 1
        finally:
            # handle() raises SystemExit on a "stop" frame — the socket
            # must not outlive the loop on that path either
            sock.close()
        return n

    def handle(self, payload: Dict) -> None:
        kind = payload.get("kind", "metrics")
        if kind == "metrics":
            for key, val in payload.get("values", {}).items():
                self.series.setdefault(key, []).append(float(val))
            self._render_series()
        elif kind == "image":
            self._render_image(payload)
        elif kind == "stop":
            raise SystemExit(0)

    def _render_series(self):
        import os
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:  # render-less environments still drain frames
            return
        fig, ax = plt.subplots(figsize=(6, 3.5))
        for key, vals in self.series.items():
            ax.plot(vals, label=key)
        ax.legend(loc="best", fontsize=8)
        ax.set_xlabel("update")
        fig.tight_layout()
        fig.savefig(os.path.join(self.out_dir, "metrics.png"))
        plt.close(fig)

    def _render_image(self, payload: Dict):
        import os
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            return
        import numpy as np
        arr = np.asarray(payload["data"])
        fig, ax = plt.subplots()
        ax.imshow(arr, cmap=payload.get("cmap", "viridis"))
        ax.set_title(payload.get("name", "image"))
        fig.savefig(os.path.join(
            self.out_dir, payload.get("name", "image") + ".png"))
        plt.close(fig)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.graphics")
    p.add_argument("endpoint", help="tcp://host:port from GraphicsServer")
    p.add_argument("--out", default="plots")
    args = p.parse_args(argv)
    client = GraphicsClient(args.endpoint, args.out)
    client.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
