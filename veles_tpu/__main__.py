"""CLI entry point: ``python -m veles_tpu <config> [options] [overrides]``.

Reference parity: veles/__main__.py (``Main`` :136) — positional workflow +
config files, ``--optimize N[:G]`` GA mode (:716-734), ``--ensemble-train
N:r`` / ``--ensemble-test``, ``--dump-config``, ``--result-file``,
``--random-seed`` (:483-537), snapshot-restore positional (:539-589),
``--dry-run`` levels, inline ``root.x.y=z`` overrides (:474-481).
Subcommands: ``benchmark`` (device gemm DB), ``forge`` (model store),
``compare-snapshots A B`` (per-tensor checkpoint diff — reference:
veles/scripts/compare_snapshots.py).

Config conventions (TPU-native redesign of "user config files are executed
Python mutating root", veles/__main__.py:426-472):

* ``config.py``  — executed with ``root`` bound; must define
  ``create(root) -> veles_tpu.Trainer`` (full control), OR set
  ``root.workflow`` / ``root.loader`` trees for the standard path.
* ``config.json`` — merged into ``root``; must contain ``workflow``
  (StandardWorkflow layer config) and ``loader`` ({"name": ..., args}).

Named loaders: mnist, cifar, imagenet_synthetic.
"""

from __future__ import annotations

import argparse
import json
import runpy
import sys
from typing import Optional

from . import prng
from .config import Config, apply_overrides, root
from .logger import setup_logging
from .runtime import Decision, Snapshotter, Trainer


LOADERS = {
    "mnist": "veles_tpu.models.mnist:MnistLoader",
    "cifar": "veles_tpu.models.cifar:CifarLoader",
    "stl": "veles_tpu.models.stl:StlLoader",
    "induction": "veles_tpu.models.lm:InductionLoader",
    "imagenet_synthetic":
        "veles_tpu.models.alexnet:ImagenetSyntheticLoader",
}


def make_loader(name: str, **args):
    import importlib
    mod, _, attr = LOADERS[name].partition(":")
    return getattr(importlib.import_module(mod), attr)(**args)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu",
        description="TPU-native deep learning framework "
                    "(Veles-capability rebuild)")
    p.add_argument("config", nargs="?",
                   help="config .py/.json, or a snapshot .json manifest "
                        "to resume")
    p.add_argument("overrides", nargs="*", default=[],
                   help="inline config overrides: path.to.key=value")
    p.add_argument("--snapshot",
                   help="snapshot manifest to restore from: a file path, "
                        "sqlite://db#id, or http(s):// manifest URL")
    p.add_argument("--visualize", metavar="PATH",
                   help="write the workflow DOT graph here (and PATH.svg "
                        "when graphviz is installed), then continue "
                        "(reference: veles --visualize)")
    p.add_argument("--background", action="store_true",
                   help="daemonize: detach from the terminal and keep "
                        "training (reference: veles --background); logs "
                        "go to --background-log")
    p.add_argument("--background-log", default="veles_tpu.log",
                   help="log file for --background mode")
    p.add_argument("--random-seed", default=None,
                   help="int, hex (0x...), or a file whose bytes seed the "
                        "generators (reference: veles/__main__.py:483-537 "
                        "accepted hex strings and /dev/urandom-style "
                        "sources)")
    p.add_argument("--dump-config", action="store_true")
    p.add_argument("--dry-run", choices=["init", "build"], default=None,
                   help="stop after loader init / workflow build")
    p.add_argument("--result-file", help="write results JSON here")
    p.add_argument("--optimize", metavar="N[:G]",
                   help="GA over config Range tuneables: population[:gens]")
    p.add_argument("--workers", type=int, default=1,
                   help="parallel evaluation workers for --optimize / "
                        "--ensemble-train: each evaluation runs as a "
                        "standalone CLI subprocess on a pool this size "
                        "(reference: slave farm-out). Workers default to "
                        "CPU (JAX_PLATFORMS=cpu) so they don't fight over "
                        "one TPU chip")
    p.add_argument("--ensemble-train", metavar="N:r",
                   help="train N members on ratio-r subsets")
    p.add_argument("--ensemble-test", metavar="MANIFEST",
                   help="test an ensemble from its manifest JSON")
    p.add_argument("--curriculum", metavar="SPEC.json",
                   help="snapshot-phased curriculum: run the config as "
                        "chained training phases per the spec (each "
                        "phase restores the best snapshot so far; see "
                        "runtime/curriculum.py)")
    p.add_argument("--curriculum-out", default="curriculum_out",
                   help="directory for per-phase snapshots/results")
    p.add_argument("--mesh", help="mesh spec, e.g. data=4,model=2")
    p.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache directory "
                        "(root.common.compile_cache): restarted runs "
                        "with unchanged step programs skip the backend "
                        "compile entirely; see docs/compile_cache.md")
    p.add_argument("--platform", default=None,
                   help="pin the jax platform (cpu/tpu/axon) BEFORE first "
                        "backend use. Needed because env vars alone are "
                        "too late when site hooks preload jax: with the "
                        "accelerator tunnel down, backend autodetection "
                        "can hang — '--platform cpu' keeps CPU runs "
                        "(e.g. a virtual-device mesh via "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=N) independent of it")
    p.add_argument("--hosts",
                   help="comma-separated hosts: respawn this command on "
                        "each via ssh (localhost entries spawn locally) "
                        "as one SPMD gang (reference: -n slave specs)")
    p.add_argument("--max-epochs", type=int, default=None)
    p.add_argument("--snapshot-dir", default=None)
    p.add_argument("--frontend", action="store_true",
                   help="serve a browser form that composes this command "
                        "line (reference: veles --frontend)")
    p.add_argument("--publish", metavar="DIR[:FMT]",
                   help="after training, write a run report to DIR; FMT "
                        "is markdown (default), html or pdf — comma-"
                        "separate for several (reference: the Publisher "
                        "unit, veles/publishing/publisher.py:57)")
    p.add_argument("--profile-units", action="store_true",
                   help="before training, time each unit's apply with a "
                        "forced device sync and print the top-5 table "
                        "(reference: --sync-run honest per-unit timers + "
                        "Workflow.print_stats)")
    p.add_argument("--export", metavar="DIR[.zip]", default=None,
                   help="write a native-serving package of the "
                        "(restored) model and exit — contents.json + "
                        "npy for veles_serve (reference: "
                        "Workflow.package_export, veles/workflow.py:868)")
    p.add_argument("--compiled", action="store_true",
                   help="with --export DIR: write a sealed compiled "
                        "artifact instead (jax.export StableHLO of the "
                        "batched forward + the decode engine's fixed "
                        "program set, manifest, weights blob) and print "
                        "the manifest summary; serve it with "
                        "--serve --artifact DIR "
                        "(docs/serving_export.md)")
    p.add_argument("--artifact", metavar="DIR", default=None,
                   help="with --serve: boot from a compiled artifact "
                        "directory (export_compiled) — deserialized "
                        "StableHLO programs, zero model Python, no "
                        "config file needed")
    p.add_argument("--generate", type=int, metavar="N", default=None,
                   help="decode N tokens after --prompt with the "
                        "(restored) sequence model instead of training "
                        "— KV-cached greedy/temperature sampling "
                        "(veles_tpu.generate); prints the token rows "
                        "as JSON")
    p.add_argument("--prompt", default=None,
                   help="comma-separated token ids for --generate "
                        "(';' separates batch rows), or @file.npy")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="sampling temperature for --generate "
                        "(0 = greedy)")
    p.add_argument("--top-k", type=int, default=None,
                   help="restrict --generate sampling to the k highest "
                        "logits (needs --temperature > 0)")
    p.add_argument("--top-p", type=float, default=None,
                   help="nucleus sampling for --generate: smallest "
                        "token set with cumulative probability >= p "
                        "(needs --temperature > 0)")
    p.add_argument("--beams", type=int, default=1,
                   help="beam-search width for --generate (>1 returns "
                        "the highest-total-log-prob continuation; "
                        "exclusive with sampling flags)")
    p.add_argument("--eos-id", type=int, default=None,
                   help="end-of-sequence token: greedy/sampling decode "
                        "stops a row that emits it (padding the rest); "
                        "with --beams, finished beams freeze and pad")
    p.add_argument("--length-penalty", type=float, default=0.0,
                   help="beam score normalization exponent over the "
                        "generated length (GNMT convention; 0 = raw "
                        "log-prob sum)")
    p.add_argument("--serve", type=int, metavar="PORT", default=None,
                   help="serve the (restored) model over HTTP instead "
                        "of training: POST /predict, plus POST "
                        "/generate for sequence chains "
                        "(runtime/restful.py; 0 = ephemeral port); "
                        "blocks until drained (SIGTERM / POST "
                        "/admin/drain) or interrupted")
    p.add_argument("--fleet", type=int, metavar="N", default=None,
                   help="with --serve: boot N replica serving stacks "
                        "in this process behind the fleet router "
                        "(load- + prefix-affinity dispatch, "
                        "coordinated hot swap, rolling drain — "
                        "docs/serving.md 'Fleet serving'); PORT "
                        "serves the router, replicas take ephemeral "
                        "ports (default root.common.serve.fleet."
                        "replicas)")
    p.add_argument("--join", metavar="ROUTER_URL", default=None,
                   help="with --serve: register this replica with a "
                        "running fleet router after boot (POST "
                        "/admin/join) so it starts receiving "
                        "dispatched traffic; the router drains it "
                        "during a rolling drain and readmits it on "
                        "/ready")
    p.add_argument("--model-dir", default=None,
                   help="snapshot directory backing --serve's model "
                        "lifecycle control plane (runtime/deploy.py): "
                        "POST /admin/reload hot-swaps a snapshot/"
                        "package with zero downtime, GET /models lists "
                        "the versioned registry")
    p.add_argument("--watch", action="store_true",
                   help="with --serve --model-dir: poll the directory "
                        "for newer snapshots and hot-swap them "
                        "automatically (exponential retry backoff on "
                        "failures)")
    p.add_argument("--drain-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="graceful-drain deadline for SIGTERM / POST "
                        "/admin/drain: admissions stop and /ready "
                        "answers 503 immediately, in-flight work gets "
                        "this long to retire (default "
                        "root.common.serve.drain_timeout_s)")
    p.add_argument("--status-port", type=int, default=None,
                   help="serve a live status page (JSON + HTML with "
                        "auto-refreshing metric plots) on this port; 0 "
                        "picks a free port (reference: the Tornado web "
                        "status + WebAgg live plots, veles/web_status.py)")
    p.add_argument("--plots", metavar="DIR", default=None,
                   help="write metric-curve PNGs/JSONL here each epoch "
                        "(default 'plots' when --status-port is set)")
    p.add_argument("--trace-out", metavar="FILE", default=None,
                   help="on exit, write the host-side span timeline "
                        "(per-request queue-wait/prefill/decode spans, "
                        "training epochs, status events) as Chrome-"
                        "trace JSON — open in Perfetto; the same "
                        "document GET /trace.json serves live")
    p.add_argument("--profile", metavar="DIR",
                   help="capture a device-level jax.profiler trace of the "
                        "training run into DIR (view with TensorBoard / "
                        "xprof; complements the host-side EventTracer "
                        "timeline)")
    p.add_argument("--verbose", "-v", action="store_true")
    p.add_argument("--version", action="version",
                   version=f"veles_tpu {_version()}")
    p.add_argument("--list-units", action="store_true",
                   help="print the registered unit classes and exit")
    return p


def _version() -> str:
    from . import __version__
    return __version__


def _make_trainer_from_root(cfg: Config, args) -> Trainer:
    """The standard path: root.workflow + root.loader trees."""
    from .models.standard import StandardWorkflow
    wf_cfg = cfg.workflow.to_dict() if "workflow" in cfg else None
    if not wf_cfg:
        raise SystemExit("config must define root.workflow (layer list) "
                         "or a create(root) function")
    sw = StandardWorkflow(wf_cfg)
    loader_cfg = cfg.loader.to_dict() if "loader" in cfg else {}
    name = loader_cfg.pop("name", "mnist")
    loader = make_loader(name, **loader_cfg)
    decision = Decision(
        max_epochs=args.max_epochs or wf_cfg.get("max_epochs"),
        fail_iterations=wf_cfg.get("fail_iterations", 50))
    snap = None
    if args.snapshot_dir:
        snap = Snapshotter(wf_cfg.get("name", "workflow"),
                           args.snapshot_dir)
    mesh = _make_mesh(args.mesh)
    rule = None
    if mesh is not None:
        # auto-compose sharding rules for parallel units present in the
        # graph (expert banks on 'expert', pipeline stages on 'pipe')
        from .parallel.mesh import compose_rules, fsdp_rules
        from .units.parallel_nn import (MoEFFN, PipelineStack,
                                        expert_rules, pipeline_rules)
        rules = []
        kinds = {type(u) for u in sw.workflow.units}
        if MoEFFN in kinds and mesh.shape.get("expert", 1) > 1:
            rules.append(expert_rules())
        if PipelineStack in kinds and mesh.shape.get("pipe", 1) > 1:
            rules.append(pipeline_rules())
        if mesh.shape.get("fsdp", 1) > 1:
            rules.append(fsdp_rules(axis_size=mesh.shape["fsdp"]))
        if rules:
            rule = compose_rules(*rules)
    return Trainer(sw.workflow, loader, sw.optimizer, decision, snap,
                   mesh=mesh, rule=rule,
                   pipeline_microbatches=wf_cfg.get(
                       "pipeline_microbatches"),
                   pipeline_interleave=wf_cfg.get(
                       "pipeline_interleave", 1))


def _make_mesh(spec: Optional[str]):
    if not spec:
        return None
    from .parallel import MeshSpec, make_mesh
    kw = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        kw[k.strip()] = int(v)
    return make_mesh(MeshSpec(**kw))


def _load_config(path: str, overrides):
    """Returns (create_fn_or_None, snapshot_manifest_or_None).

    A positional .json that is actually a snapshot manifest (has a
    'tensors' key — see Snapshotter.save) restores config from its
    embedded 'config' snapshot and schedules a state restore (reference:
    positional snapshot restore, veles/__main__.py:539-589)."""
    create, snapshot = None, None
    if path.endswith(".json"):
        with open(path) as f:
            data = json.load(f)
        if "tensors" in data:  # snapshot manifest, not a config
            snapshot = path
            root.update(data.get("config", {}))
        else:
            root.update(data)
    else:
        ns = runpy.run_path(path, init_globals={"root": root})
        create = ns.get("create")
    apply_overrides(root, overrides)
    return create, snapshot


def _forge_main(argv) -> int:
    """``python -m veles_tpu forge <action>`` (reference: the ``veles forge``
    subcommand, veles/__main__.py:217 _process_special_args +
    veles/forge/forge_client.py ACTIONS)."""
    p = argparse.ArgumentParser(prog="veles_tpu forge")
    sub = p.add_subparsers(dest="action", required=True)
    for act in ("list", "details", "delete"):
        sp = sub.add_parser(act)
        sp.add_argument("--server", "-s", required=True)
        if act != "list":
            sp.add_argument("name")
    sp = sub.add_parser("fetch")
    sp.add_argument("--server", "-s", required=True)
    sp.add_argument("name")
    sp.add_argument("dest")
    sp.add_argument("--version", default=None)
    sp = sub.add_parser("upload")
    sp.add_argument("--server", "-s", required=True)
    sp.add_argument("path")
    sp.add_argument("--manifest", "-m",
                    help="manifest JSON file (default <path>/manifest.json)")
    sp = sub.add_parser("serve")
    sp.add_argument("store_dir")
    sp.add_argument("--port", type=int, default=8080)
    a = p.parse_args(argv)

    from .forge import ForgeClient, ForgeServer, ForgeStore
    if a.action == "serve":
        srv = ForgeServer(ForgeStore(a.store_dir), port=a.port).start()
        try:
            srv._thread.join()
        except KeyboardInterrupt:
            srv.stop()
        return 0
    client = ForgeClient(a.server)
    if a.action == "list":
        print(json.dumps(client.list(), indent=1))
    elif a.action == "details":
        print(json.dumps(client.details(a.name), indent=1))
    elif a.action == "delete":
        client.delete(a.name)
    elif a.action == "fetch":
        client.fetch(a.name, a.dest, a.version)
    elif a.action == "upload":
        import os
        mpath = a.manifest or os.path.join(a.path, "manifest.json")
        with open(mpath) as f:
            print(json.dumps(client.upload(a.path, json.load(f))))
    return 0


def _parse_seed(s: str) -> int:
    """int, 0x-hex, or a file/device whose first 8 bytes seed things."""
    import os
    try:
        return int(s, 10)
    except ValueError:
        pass
    if s.lower().startswith("0x"):
        try:
            return int(s, 16)
        except ValueError:
            raise SystemExit(f"--random-seed {s!r}: bad hex literal")
    if os.path.exists(s):  # regular file OR char device (/dev/urandom)
        with open(s, "rb") as f:
            data = f.read(8)
        if not data:
            raise SystemExit(f"seed file {s!r} is empty")
        return int.from_bytes(data, "little")
    raise SystemExit(
        f"--random-seed {s!r}: not an int, 0x-hex, or readable file")


_PUBLISH_FORMATS = ("markdown", "html", "pdf")


def _publish_backends():
    from .publishing import HtmlBackend, MarkdownBackend, PdfBackend
    return {"markdown": MarkdownBackend, "html": HtmlBackend,
            "pdf": PdfBackend}


def _publish_fmts(fmts: str):
    out = [f.strip() for f in (fmts or "markdown").split(",")]
    bad = [f for f in out if f not in _PUBLISH_FORMATS]
    if bad:
        raise SystemExit(
            f"unknown --publish format(s) {bad}; "
            f"choose from {', '.join(_PUBLISH_FORMATS)}")
    return out


def _daemonize(log_path: str) -> int:
    """Double-fork daemonization. Returns the daemon pid in the original
    process, 0 in the daemon (which has stdio redirected to ``log_path``),
    -1 if the intermediate child died before reporting a pid."""
    import os
    r, w = os.pipe()
    pid = os.fork()
    if pid > 0:  # original process
        os.close(w)
        data = os.read(r, 32)
        os.close(r)
        os.waitpid(pid, 0)
        return int(data) if data else -1
    os.close(r)
    os.setsid()
    pid2 = os.fork()
    if pid2 > 0:  # session leader: report the grandchild and vanish
        os.write(w, str(pid2).encode())
        os._exit(0)
    os.close(w)
    os.environ["VELES_DAEMONIZED"] = "1"
    fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    null = os.open(os.devnull, os.O_RDONLY)
    os.dup2(null, 0)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    os.close(fd)
    os.close(null)
    return 0


def _write_graph(workflow, path: str) -> None:
    """Dump the workflow DOT (reference: --visualize rendered the graph;
    here it lands as files: PATH and PATH.svg — rendered by graphviz
    when available, else by the native Workflow.generate_svg layout)."""
    with open(path, "w") as f:
        f.write(workflow.generate_graph())
    import shutil
    import subprocess
    if shutil.which("dot"):
        subprocess.run(["dot", "-Tsvg", path, "-o", path + ".svg"],
                       check=False)
    else:
        with open(path + ".svg", "w") as f:
            f.write(workflow.generate_svg())


def _check_watch(args) -> None:
    """``--watch`` needs a directory to poll — ONE check, called early
    by ``_serve_artifact`` (before the expensive boot) and again by the
    shared serve loop."""
    if args.watch and not (args.model_dir
                           or root.common.serve.get("model_dir")):
        raise SystemExit("--watch needs --model-dir (the snapshot "
                         "directory to poll)")


def _fleet_n(args) -> int:
    """Replica count for ``--serve --fleet``: the flag wins, the
    ``root.common.serve.fleet.replicas`` knob backs it (0 = plain
    single-replica serving, no router)."""
    if args.fleet is not None:
        return max(0, int(args.fleet))
    return max(0, int(root.common.serve.fleet.get("replicas", 0) or 0))


def _serve_fleet(args, factory, banner: dict) -> int:
    """``--serve PORT --fleet N``: N in-process replica serving stacks
    (each built by ``factory`` — a zero-arg callable returning a
    STARTED RestfulServer with its DeployController attached) fronted
    by the fleet router (runtime/fleet.py).  PORT serves the router;
    replicas listen on ephemeral local ports.  Blocks until the fleet
    drains (SIGTERM / POST /admin/drain on the router)."""
    from .runtime.fleet import FleetRouter, FleetServer, InProcessReplica

    if args.watch:
        raise SystemExit(
            "--watch is per-replica and conflicts with --fleet: "
            "fleet-wide version changes go through the router's "
            "coordinated swap (POST /admin/reload on the router)")
    if args.join:
        raise SystemExit("--fleet runs the router; --join makes this "
                         "process a replica of ANOTHER router — "
                         "pick one")
    n = _fleet_n(args)
    replicas = [InProcessReplica(factory) for _ in range(n)]
    router = FleetRouter()
    for rep in replicas:
        # one process = one metrics registry: the SLO merge must count
        # the shared histograms once, not per replica
        router.add_replica(url=rep.url, registry_key="in-process",
                           restart=rep.restart, kill=rep.kill)
    fsrv = FleetServer(router, port=args.serve)
    fsrv.install_signal_handlers()
    fsrv.start()
    print(json.dumps(dict(
        banner, fleet=n, serving=fsrv.port,
        replicas=[r.url for r in replicas],
        observe=["/metrics", "/fleet.json", "/slo.json"])), flush=True)
    try:
        router.wait()  # released by SIGTERM / POST /admin/drain
    except KeyboardInterrupt:
        router.begin_drain()
    fsrv.stop()
    for rep in replicas:
        rep.stop()
    _maybe_write_trace(args)
    return 0


def _run_serve_loop(args, srv, banner: dict, *, status=None,
                    boot_source: str = "live") -> int:
    """The ONE serve bootstrap/teardown config-booted (``--serve``) and
    artifact-booted (``--serve --artifact``) serving share: deploy
    control plane, signal handlers, optional snapshot watcher, JSON
    boot banner, then block until drained."""
    from .runtime.deploy import DeployController

    _check_watch(args)
    deploy = DeployController(
        server=srv, model_dir=args.model_dir,
        drain_timeout_s=args.drain_timeout,
        status=status, boot_source=boot_source)
    deploy.install_signal_handlers()
    srv.start()
    if args.join:
        # replica mode: hand this process's serving URL to a running
        # fleet router; retries ride the shared transient-HTTP backoff
        # (the router may still be booting)
        from .runtime.deploy import http_retry
        from .runtime.fleet_client import ReplicaClient, ReplicaUnavailable

        def _join():
            try:
                return ReplicaClient(args.join).request(
                    "POST", "/admin/join",
                    {"url": f"http://127.0.0.1:{srv.port}"})
            except ReplicaUnavailable as e:
                # surface as the transport error http_retry retries
                raise ConnectionError(str(e)) from e

        status_code, _h, doc = http_retry(_join, what="fleet join")
        if status_code != 200:
            srv.stop()
            raise SystemExit(
                f"--join {args.join}: router refused the replica "
                f"(HTTP {status_code}: {doc})")
    if args.watch:
        deploy.start_watcher()
    print(json.dumps(dict(banner, serving=srv.port,
                          model_dir=deploy.model_dir,
                          watching=deploy.watching,
                          # the deep-observability surface riding every
                          # serve boot (docs/observability.md)
                          observe=["/metrics", "/trace.json",
                                   "/slo.json", "/memory.json",
                                   "/debug/profile"])), flush=True)
    try:
        deploy.wait()  # released by SIGTERM / POST /admin/drain
    except KeyboardInterrupt:
        deploy.drain(timeout=0)  # interactive: skip the grace hold
    srv.stop()
    _maybe_write_trace(args)
    return 0


def _maybe_write_trace(args) -> None:
    """``--trace-out FILE``: dump the span ring (request timelines /
    train epochs / status events) as a Perfetto-loadable Chrome trace
    at shutdown."""
    if getattr(args, "trace_out", None):
        from .runtime.metrics import write_chrome_trace
        write_chrome_trace(args.trace_out)


def _serve_artifact(args) -> int:
    """``--serve --artifact DIR``: boot HTTP serving from a sealed
    compiled artifact (export_compiled) — deserialized StableHLO
    programs + weights blob, no model Python config anywhere.  Decodable
    artifacts serve POST /generate through an ArtifactRunner (the
    continuous-batching engine over the sealed program set); the
    exported batched forward backs POST /predict.  The deploy control
    plane wraps it exactly like config-booted serving: /models,
    /admin/reload (snapshots, packages, other artifacts — weights only,
    programs stay sealed), graceful drain."""
    import numpy as np

    from .runtime.artifact import (ArtifactRunner, load_forward,
                                   read_manifest)
    from .runtime.restful import RestfulServer

    _check_watch(args)  # fail BEFORE the expensive artifact boot
    man = read_manifest(args.artifact)

    def build_server(port):
        runner = None
        if "decode" in man.get("programs", {}):
            runner = ArtifactRunner(args.artifact)
            wstate = runner.wstate
            predict_fn = runner.predict if runner.has_forward else None
        else:
            predict_fn, wstate, _m = load_forward(args.artifact)

        if predict_fn is None:
            def predict_fn(wstate, batch):  # noqa: ARG001
                raise ValueError(
                    "this artifact was exported without a forward "
                    "program; only /generate is served")

        ispec = man.get("input_spec") or {}
        shape = [int(s) for s in (ispec.get("shape") or (1, 1))]
        return RestfulServer(
            predict_fn, wstate, shape[0], tuple(shape[1:]),
            port=port, workflow=None, engine=runner,
            input_dtype=np.dtype(ispec.get("dtype", "float32")),
            default_eos_id=man.get("eos_id"),
            vocab_size=man.get("input_vocab"))

    banner = {
        "artifact": args.artifact,
        "workflow": man.get("workflow"),
        "programs": {
            "decode": "decode" in man.get("programs", {}),
            "forward": "forward" in man.get("programs", {}),
            "prefill_buckets": man.get("buckets", [])},
    }
    if _fleet_n(args):
        # N sealed-artifact replicas behind the router: each boots the
        # whole deserialized program inventory itself, and the rolling
        # drain's restart handle reboots a replica from the SAME
        # sealed artifact (docs/serving.md "Fleet serving")
        from .runtime.deploy import DeployController

        def factory():
            srv = build_server(0)
            DeployController(server=srv,
                             drain_timeout_s=args.drain_timeout,
                             boot_source=str(args.artifact))
            return srv.start()

        return _serve_fleet(args, factory, banner)
    srv = build_server(args.serve)
    return _run_serve_loop(args, srv, banner,
                           boot_source=str(args.artifact))


def _experiment_main(argv) -> int:
    """``python -m veles_tpu experiment <action>``: inspect or cancel
    experiments in a durable store (docs/experiments.md).  ``list`` and
    ``status`` read the store directly (no running manager needed —
    trial files ARE the progress record); ``cancel`` and ``submit``
    need a live manager and go through its REST surface (``--server``),
    because only the owning process can drive or stop trials."""
    p = argparse.ArgumentParser(prog="veles_tpu experiment")
    sub = p.add_subparsers(dest="action", required=True)
    for act in ("list", "status"):
        sp = sub.add_parser(act)
        sp.add_argument("store_dir")
        if act == "status":
            sp.add_argument("id")
    sp = sub.add_parser("submit")
    sp.add_argument("--server", "-s", required=True,
                    help="fleet/replica base URL serving /experiments")
    sp.add_argument("spec", help="experiment spec JSON file or inline "
                                 "JSON object")
    sp = sub.add_parser("cancel")
    sp.add_argument("--server", "-s", required=True)
    sp.add_argument("id")
    a = p.parse_args(argv)

    from .experiments import ExperimentStore
    if a.action == "list":
        store = ExperimentStore(a.store_dir)
        print(json.dumps({"experiments": store.load_all()}, indent=1))
        return 0
    if a.action == "status":
        store = ExperimentStore(a.store_dir)
        man = store.read_manifest(a.id)
        if man is None:
            print(json.dumps({"error": f"no such experiment: {a.id}"}))
            return 1
        trials = store.load_trials(a.id)
        man["trials"] = [trials[k] for k in sorted(trials)]
        print(json.dumps(man, indent=1))
        return 0
    import urllib.request
    base = a.server.rstrip("/")
    if a.action == "submit":
        import os
        if os.path.exists(a.spec):
            with open(a.spec) as f:
                spec = json.load(f)
        else:
            spec = json.loads(a.spec)
        req = urllib.request.Request(
            f"{base}/experiments", method="POST",
            data=json.dumps(spec).encode(),
            headers={"Content-Type": "application/json"})
    else:
        req = urllib.request.Request(
            f"{base}/experiments/{a.id}", method="DELETE")
    try:
        with urllib.request.urlopen(req) as resp:
            print(json.dumps(json.load(resp), indent=1))
        return 0
    except urllib.error.HTTPError as e:
        print(e.read().decode())
        return 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "benchmark":
        # reference: DeviceBenchmark / device-info DB
        # (veles/accelerated_units.py:706-824, veles/backends.py:672-731)
        from .runtime.benchmark import benchmark_device
        info = benchmark_device(refresh="--refresh" in argv)
        print(json.dumps(info, indent=1))
        return 0
    if argv and argv[0] == "compare-snapshots":
        # reference: veles/scripts/compare_snapshots.py (relative diffs
        # between two Snapshotter pickles, prettytable output)
        p = argparse.ArgumentParser(
            prog="veles_tpu compare-snapshots",
            description="Per-tensor diff of two snapshot manifests "
                        "(paths, _current/_best links, or sqlite:// / "
                        "http:// snapshot URIs)")
        p.add_argument("a")
        p.add_argument("b")
        p.add_argument("--sort", choices=("name", "maxdiff", "reldiff"),
                       default="reldiff", help="row order (default: by "
                       "max relative difference, largest first)")
        p.add_argument("--top", type=int, default=0,
                       help="print only the N most-different tensors")
        p.add_argument("--json", action="store_true",
                       help="machine-readable report instead of a table")
        ca = p.parse_args(argv[1:])
        from .runtime.snapshotter import compare_snapshots
        rep = compare_snapshots(ca.a, ca.b)
        if ca.json:
            print(json.dumps(rep, indent=1))
            return 0
        rows = rep["rows"]
        if ca.sort == "maxdiff":
            rows.sort(key=lambda r: -r.get("max_abs", float("inf")))
        elif ca.sort == "reldiff":
            rows.sort(key=lambda r: -r.get("max_rel", float("inf")))
        if ca.top:
            rows = rows[:ca.top]
        print(f"{'tensor':44s} {'shape':>16s} {'max|d|':>11s} "
              f"{'mean|d|':>11s} {'max rel':>11s}")
        for r in rows:
            if r["mismatch"]:
                print(f"{r['key']:44s} MISMATCH "
                      f"{r['shape_a']}/{r['dtype_a']} vs "
                      f"{r['shape_b']}/{r['dtype_b']}")
            else:
                print(f"{r['key']:44s} {str(tuple(r['shape'])):>16s} "
                      f"{r['max_abs']:11.4g} {r['mean_abs']:11.4g} "
                      f"{r['max_rel']:11.4g}")
        for side, keys in (("a", rep["only_a"]), ("b", rep["only_b"])):
            for k in keys:
                print(f"{k:44s} ONLY IN {side}")
        for k, (va, vb) in sorted(rep["meta"].items()):
            sa, sb = repr(va), repr(vb)
            if len(sa) + len(sb) > 160:  # decision history etc.
                sa, sb = sa[:76] + "…", sb[:76] + "…"
            print(f"meta {k}: {sa} -> {sb}")
        n_diff = sum(1 for r in rep["rows"]  # count BEFORE --top cut
                     if r["mismatch"] or r.get("max_abs", 0) > 0)
        print(f"-- {len(rep['rows'])} shared tensors, {n_diff} differ; "
              f"{len(rep['only_a'])}+{len(rep['only_b'])} unmatched")
        return 0
    if argv and argv[0] == "forge":
        setup_logging()
        return _forge_main(argv[1:])
    if argv and argv[0] == "experiment":
        setup_logging()
        return _experiment_main(argv[1:])
    if "--frontend" in argv:
        # reference: veles --frontend web form -> composed cmdline
        # (veles/__main__.py:258-332)
        setup_logging()
        from .frontend import Frontend
        fe = Frontend(build_parser())
        composed = fe.wait()
        fe.close()
        if composed is None:
            return 1
        return main(composed)
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import os
    if args.background and "VELES_DAEMONIZED" not in os.environ:
        # Classic double-fork daemonization (reference: veles --background,
        # veles/external/daemon). Must happen BEFORE any XLA client exists:
        # forking a process with live device handles corrupts them.
        pid = _daemonize(args.background_log)
        if pid > 0:  # launcher process: report the daemon pid and leave
            print(json.dumps({"daemon_pid": pid}))
            return 0
        if pid < 0:  # intermediate child died before reporting
            print("daemonization failed", file=sys.stderr)
            return 1
    setup_logging(level=10 if args.verbose else 20)

    if args.hosts and "VELES_PROCESS_ID" not in os.environ:
        # Launcher role: respawn this exact command on every host with
        # rank env vars (children skip this branch — they carry
        # VELES_PROCESS_ID). Reference: Launcher SSH slave spawn,
        # veles/launcher.py:808-842.
        from .parallel.launcher import launch_hosts
        return launch_hosts(args.hosts.split(","), argv)
    # Joins the multi-host process group when VELES_* are set (no-op
    # standalone).
    from .parallel.distributed import initialize_distributed
    initialize_distributed()

    if args.list_units:
        from .units.base import UnitRegistry
        for name in UnitRegistry.names():
            print(name)
        return 0

    if args.ensemble_test and not args.config:
        raise SystemExit("--ensemble-test needs the workflow config the "
                         "members were trained with")
    if args.compiled and not args.export:
        raise SystemExit("--compiled modifies --export DIR (it writes "
                         "the compiled artifact there)")
    if args.fleet is not None and args.serve is None:
        raise SystemExit("--fleet fronts HTTP serving with the fleet "
                         "router and needs --serve PORT")
    if args.fleet is not None and args.watch:
        # fail at parse time — _serve_fleet re-checks (it is also a
        # library entry), but a pure argv conflict must not wait for
        # a training run to finish before it fires
        raise SystemExit(
            "--watch is per-replica and conflicts with --fleet: "
            "fleet-wide version changes go through the router's "
            "coordinated swap (POST /admin/reload on the router)")
    if args.fleet is not None and args.join:
        raise SystemExit("--fleet runs the router; --join makes this "
                         "process a replica of ANOTHER router — "
                         "pick one")
    if args.join and args.serve is None:
        raise SystemExit("--join registers a serving replica with a "
                         "fleet router and needs --serve PORT")
    if args.join and args.watch:
        raise SystemExit("--watch is a per-replica auto-swap and would "
                         "silently break the fleet's all-or-nothing "
                         "version invariant on a --join'ed replica; "
                         "fleet-wide version changes go through the "
                         "router's coordinated swap (POST /admin/reload "
                         "on the router)")

    if args.artifact is not None:
        # compiled-artifact serving: no config, no model Python — the
        # sealed program set + weights blob are the whole input
        if args.serve is None:
            raise SystemExit("--artifact serves a compiled artifact "
                             "and needs --serve PORT")
        if args.config:
            raise SystemExit("--artifact serves sealed programs; a "
                             "workflow config cannot apply (drop "
                             f"{args.config!r}, or serve the config "
                             "via --serve without --artifact)")
        if args.export:
            raise SystemExit("--export needs the model config to "
                             "package; it cannot combine with "
                             "--artifact serving (export first, then "
                             "serve the artifact)")
        if args.snapshot:
            raise SystemExit("--artifact serves the artifact's sealed "
                             "weights; --snapshot cannot apply (swap "
                             "weights at runtime via POST "
                             "/admin/reload)")
        if args.generate is not None:
            raise SystemExit("--generate is a one-shot decode of a "
                             "config/snapshot model; with an artifact, "
                             "serve it and POST /generate")
        apply_overrides(root, args.overrides)
        return _serve_artifact(args)

    if not args.config:
        build_parser().print_help()
        return 2

    if args.publish:
        _publish_fmts(args.publish.partition(":")[2])  # fail fast on typos
        if (args.optimize or args.ensemble_train or args.ensemble_test
                or args.dry_run or args.curriculum):
            raise SystemExit("--publish applies to standalone training "
                             "runs (meta-workflow reports: use the "
                             "Publisher API)")
    if args.curriculum and (args.dry_run or args.export
                            or args.generate is not None
                            or args.serve is not None):
        raise SystemExit("--curriculum is a training meta-mode; "
                         "--dry-run/--export/--generate/--serve apply "
                         "to single runs (run them on the final best "
                         "snapshot)")

    if args.random_seed is not None:
        root.common.random_seed = _parse_seed(args.random_seed)
        prng.streams.reset()

    # -- curriculum mode (chained CLI phases; productized
    # configs/induction_lm64_curriculum.sh — BASELINE.md stretch bar).
    # Dispatched BEFORE _load_config: the parent only needs the config
    # PATH — each phase subprocess loads/executes it itself, so loading
    # here would double any import-time side effects. Warm start comes
    # from an explicit --snapshot (a config-manifest snapshot is a
    # single-run convenience and is not consulted).
    if args.curriculum:
        from .runtime.curriculum import CurriculumRunner
        with open(args.curriculum) as f:
            spec = json.load(f)
        extra = list(args.overrides)
        if args.platform:
            # phases run in subprocesses; the flag (not the env) selects
            # the platform there, so forward it
            extra += ["--platform", args.platform]
        seed = (_parse_seed(args.random_seed)
                if args.random_seed is not None else None)
        summary = CurriculumRunner(args.config, spec,
                                   args.curriculum_out,
                                   extra_argv=extra,
                                   initial_snapshot=args.snapshot,
                                   default_seed=seed).run()
        print(json.dumps({k: v for k, v in summary.items()
                          if k != "phases"}))
        if args.result_file:
            with open(args.result_file, "w") as f:
                json.dump(summary, f, indent=1)
        return 0

    create, manifest_snapshot = _load_config(args.config, args.overrides)
    if manifest_snapshot and not args.snapshot:
        args.snapshot = manifest_snapshot
    if not args.platform:
        # the config-file form of --platform ("" = let JAX pick): the
        # backend has not initialized yet at this point — nothing above
        # touches a device — so the pin still lands before first use
        cfg_platform = str(root.common.get("platform", "") or "")
        if cfg_platform:
            import jax
            jax.config.update("jax_platforms", cfg_platform)
    if args.compile_cache:
        # flag wins over config/overrides; Trainer.initialize() activates
        # it right before the first compile
        root.common.compile_cache = args.compile_cache

    if args.dump_config:
        print(root.dump())
        return 0

    def trainer_factory(cfg: Config) -> Trainer:
        if create is not None:
            return create(cfg)
        return _make_trainer_from_root(cfg, args)

    # -- GA mode (reference --optimize, veles/__main__.py:716-734) ---------
    if args.optimize:
        from .genetics import GeneticOptimizer, SubprocessEvaluator
        n, _, g = args.optimize.partition(":")

        fitness, evaluator = None, None
        if args.workers > 1:
            # Reference farm-out: every chromosome is a standalone run on
            # the worker pool (veles/genetics/optimization_workflow.py).
            extra = list(args.overrides)
            if args.max_epochs:
                extra += ["--max-epochs", str(args.max_epochs)]
            if args.random_seed is not None:
                extra += ["--random-seed", str(args.random_seed)]
            evaluator = SubprocessEvaluator(
                extra, base_config=args.config, n_workers=args.workers)
        else:
            def fitness(cfg: Config) -> float:
                t = trainer_factory(cfg)
                t.initialize()
                t.run()
                return t.decision.best_value

        ga = GeneticOptimizer(root, fitness, population_size=int(n),
                              generations=int(g) if g else 10,
                              evaluator=evaluator)
        best = ga.run()
        out = {"best_fitness": best.fitness, "best_genome": best.genome}
        print(json.dumps(out))
        if args.result_file:
            import jax
            if jax.process_index() == 0:  # one writer per gang
                with open(args.result_file, "w") as f:
                    json.dump({**out, "history": ga.history}, f, indent=1)
        return 0

    # -- ensemble train (reference --ensemble-train N:r) -------------------
    if args.ensemble_train:
        from .ensemble import EnsembleTrainer
        n, _, r = args.ensemble_train.partition(":")

        member_factory, cli_argv = None, None
        if args.workers > 1:
            # Reference farm-out: each member is a standalone CLI run
            # (veles/ensemble/base_workflow.py:135-143).
            cli_argv = [args.config, *args.overrides]
            if args.max_epochs:
                cli_argv += ["--max-epochs", str(args.max_epochs)]
        else:
            def member_factory(member_id, seed, train_ratio):
                root.common.random_seed = seed
                prng.streams.reset()
                # Standard-path loaders accept bagging args via the Loader
                # base; create()-style configs must honor root.loader
                # themselves.
                root.loader.train_ratio = train_ratio
                root.loader.subset_seed = seed
                return trainer_factory(root)

        et = EnsembleTrainer(member_factory, int(n),
                             float(r) if r else 0.8,
                             out_dir=args.snapshot_dir or "ensemble",
                             n_workers=args.workers, cli_argv=cli_argv)
        results = et.run()
        print(json.dumps({"members": len(results)}))
        return 0

    # -- ensemble test (reference --ensemble-test: weighted vote over the
    # stored member snapshots, veles/ensemble/test_workflow.py:50-107) ----
    if args.ensemble_test:
        from .ensemble import EnsembleTester
        from .loader.base import VALID

        from .units.base import spec_of

        trainer = trainer_factory(root)
        trainer.loader.initialize()
        if trainer.loader.class_lengths[VALID] == 0:
            raise SystemExit(
                "--ensemble-test needs a validation split in the loader")
        batch = next(trainer.loader.iter_epoch(VALID))
        trainer.workflow.build({k: spec_of(v) for k, v in batch.items()})
        tester = EnsembleTester(lambda: trainer.workflow,
                                args.ensemble_test)
        err = tester.error_rate(trainer.loader.iter_epoch(VALID))
        out = {"ensemble_members": len(tester.members),
               "valid_error_pct": err}
        print(json.dumps(out))
        if args.result_file:
            with open(args.result_file, "w") as f:
                json.dump(out, f, indent=1)
        return 0

    # -- standalone training ------------------------------------------------
    trainer = trainer_factory(root)
    status_server = None
    if args.status_port is not None or args.plots:
        # Live observability: recorder autosaves metric-curve PNGs each
        # epoch; the status server embeds them in an auto-refreshing
        # page — a running job is watchable at an HTTP URL (reference:
        # web_status.py + the WebAgg graphics backend).
        from .plotting import MetricsRecorder
        from .runtime.status import StatusReporter, StatusServer
        plots_dir = args.plots or "plots"
        os.makedirs(plots_dir, exist_ok=True)
        if trainer.recorder is None:
            trainer.recorder = MetricsRecorder(
                name=trainer.workflow.name, out_dir=plots_dir,
                autosave_png=True)
        else:
            # a create()-style config may have wired its own recorder;
            # the flags still promise live plots — upgrade it in place
            trainer.recorder.out_dir = trainer.recorder.out_dir \
                or plots_dir
            trainer.recorder.autosave_png = True
        if args.status_port is not None:
            if trainer.status is None:
                trainer.status = StatusReporter(
                    os.path.join(plots_dir, "status.json"),
                    name=trainer.workflow.name, plots_dir=plots_dir)
            elif trainer.status.plots_dir is None:
                trainer.status.plots_dir = trainer.recorder.out_dir
            if trainer.status.graph_svg is None:
                # the page embeds the live workflow graph (reference:
                # web/viz.js rendered the DOT feed in the browser)
                svg_path = os.path.join(plots_dir, "workflow.svg")
                try:
                    with open(svg_path, "w") as f:
                        f.write(trainer.workflow.generate_svg())
                    trainer.status.graph_svg = svg_path
                except OSError:
                    pass
            status_server = StatusServer(
                trainer.status, port=args.status_port).start()
    if args.snapshot_dir and trainer.snapshotter is None:
        # create()-style configs get the CLI snapshot dir too (the standard
        # path wires this inside _make_trainer_from_root)
        trainer.snapshotter = Snapshotter(trainer.workflow.name,
                                          args.snapshot_dir)
    if args.dry_run == "init":
        trainer.loader.initialize()
        print(json.dumps({"dry_run": "init",
                          "class_lengths": trainer.loader.class_lengths}))
        return 0
    trainer.initialize()
    if args.visualize:
        _write_graph(trainer.workflow, args.visualize)
    if args.dry_run == "build":
        print(json.dumps({"dry_run": "build",
                          "checksum": trainer.workflow.checksum(),
                          "n_params": trainer.workflow.n_params(
                              trainer.wstate)}))
        return 0
    if args.snapshot:
        trainer.restore(args.snapshot)
    if args.export:
        spec = trainer._batch_spec["@input"]
        input_spec = {"shape": list(spec.shape),
                      "dtype": str(spec.dtype)}
        if args.compiled:
            # sealed compiled artifact: StableHLO programs + manifest +
            # weights (export/compiled.py); served via --serve
            # --artifact with zero model Python
            if args.export.endswith(".zip"):
                raise SystemExit("--compiled exports a DIRECTORY "
                                 "artifact (programs + manifest + "
                                 "weights), not a .zip")
            from .export import export_compiled, manifest_summary
            man = export_compiled(
                trainer.workflow, trainer.wstate, args.export,
                input_spec=input_spec, eos_id=args.eos_id)
            out = {"exported": args.export, "compiled": True,
                   "manifest": manifest_summary(man)}
        else:
            from .export import export_package
            export_package(trainer.workflow, trainer.wstate, args.export,
                           input_spec=input_spec)
            out = {"exported": args.export,
                   "units": len(trainer.workflow.units)}
        print(json.dumps(out))
        if args.result_file:
            with open(args.result_file, "w") as f:
                json.dump(out, f, indent=1)
        return 0
    if args.serve is not None:
        # HTTP serving mode: the reference's RESTfulAPI unit as a CLI
        # switch (veles/restful_api.py:78) — POST /predict on the chain
        # head, POST /generate for sequence chains, wrapped in the model
        # lifecycle control plane (runtime/deploy.py): GET /healthz +
        # /ready + /models, POST /admin/reload hot swaps, graceful
        # drain on SIGTERM / POST /admin/drain
        from .runtime.restful import RestfulServer
        wf = trainer.workflow
        head = wf.default_output()
        spec = trainer._batch_spec["@input"]
        if _fleet_n(args):
            # N live replica stacks behind the router — each replica
            # gets its OWN DecodeEngine (own slots/queue/scheduler)
            # over the shared read-only weights, so fleet dispatch has
            # real per-replica load to balance
            from .logger import Logger as _Logger
            from .runtime.deploy import DeployController
            from .runtime.engine import DecodeEngine

            def factory():
                engine = None
                try:
                    engine = DecodeEngine(wf, dict(trainer.wstate),
                                          status=trainer.status)
                except Exception as e:  # noqa: BLE001 — a chain with
                    # no decode path still serves /predict per replica
                    _Logger().warning(
                        "fleet replica serves forward-only (no decode "
                        "engine: %s)", e)
                srv = RestfulServer(
                    wf.make_predict_step(head), dict(trainer.wstate),
                    int(spec.shape[0]), tuple(spec.shape[1:]),
                    port=0, workflow=wf, engine=engine,
                    input_dtype=spec.dtype)
                DeployController(server=srv,
                                 drain_timeout_s=args.drain_timeout,
                                 status=trainer.status,
                                 boot_source=args.snapshot or "live")
                return srv.start()

            return _serve_fleet(args, factory, {"predict_head": head})
        srv = RestfulServer(
            wf.make_predict_step(head), trainer.wstate,
            int(spec.shape[0]), tuple(spec.shape[1:]),
            port=args.serve, workflow=wf,
            input_dtype=spec.dtype)
        return _run_serve_loop(args, srv, {"predict_head": head},
                               status=trainer.status,
                               boot_source=args.snapshot or "live")
    if args.generate is not None:
        # decode mode: the trained (or restored) sequence model emits a
        # continuation instead of training (reference has no LM family;
        # this pairs with `veles_serve --generate` for the native path)
        import numpy as np

        from .runtime.generate import generate as _generate
        if not args.prompt:
            raise SystemExit("--generate needs --prompt "
                             "(token ids, or @file.npy)")
        if (args.top_k is not None or args.top_p is not None) \
                and args.temperature <= 0:
            raise SystemExit(
                "--top-k/--top-p filter SAMPLING and need "
                "--temperature > 0 (temperature 0 is greedy decoding, "
                "which would silently ignore them)")
        if args.top_k is not None and args.top_k < 1:
            raise SystemExit(f"--top-k must be >= 1, got {args.top_k}")
        if args.top_p is not None and not 0.0 < args.top_p <= 1.0:
            raise SystemExit(f"--top-p must be in (0, 1], got "
                             f"{args.top_p}")
        if args.beams < 1:
            raise SystemExit(f"--beams must be >= 1, got {args.beams} "
                             "(a value < 1 would silently fall back to "
                             "greedy/sampling decode)")
        if args.beams <= 1 and args.length_penalty:
            raise SystemExit(
                "--length-penalty shapes BEAM scores and needs "
                "--beams > 1 (greedy/sampling decode would silently "
                "ignore it)")
        if args.prompt.startswith("@"):
            prompt = np.atleast_2d(
                np.load(args.prompt[1:])).astype(np.int32)
        else:
            rows = [[int(t) for t in row.split(",") if t.strip()]
                    for row in args.prompt.split(";") if row.strip()]
            if not rows or len({len(r) for r in rows}) != 1:
                raise SystemExit(
                    "--prompt rows must be non-empty and equal length "
                    f"(got lengths {[len(r) for r in rows]})")
            prompt = np.asarray(rows, np.int32)
        import jax as _jax
        key = _jax.random.key(int(root.common.get("random_seed", 0)))
        if args.beams > 1:
            if args.temperature > 0:
                raise SystemExit(
                    "--beams is deterministic search; drop "
                    "--temperature/--top-k/--top-p or use beams=1")
            from .runtime.generate import generate_beam as _gen_beam
            toks, scores = _gen_beam(
                trainer.workflow, trainer.wstate, prompt, args.generate,
                beams=args.beams, eos_id=args.eos_id,
                length_penalty=args.length_penalty)
            out = {"prompt_len": int(prompt.shape[1]),
                   "tokens": np.asarray(toks).tolist(),
                   "scores": np.asarray(scores).tolist()}
            print(json.dumps(out))
            if args.result_file:
                with open(args.result_file, "w") as f:
                    json.dump(out, f, indent=1)
            return 0
        toks = _generate(trainer.workflow, trainer.wstate, prompt,
                         args.generate, temperature=args.temperature,
                         top_k=args.top_k, top_p=args.top_p,
                         eos_id=args.eos_id, key=key)
        out = {"prompt_len": int(prompt.shape[1]),
               "tokens": np.asarray(toks).tolist()}
        print(json.dumps(out))
        if args.result_file:
            with open(args.result_file, "w") as f:
                json.dump(out, f, indent=1)
        return 0
    if args.profile_units:
        from .loader.base import TRAIN, VALID as _VALID
        klass = TRAIN if trainer.loader.class_lengths[TRAIN] else _VALID
        batch = next(trainer.loader.iter_epoch(klass))
        rows = trainer.workflow.profile_units(trainer.wstate, batch)
        print(trainer.workflow.format_profile(rows))
    import contextlib
    profile_cm = contextlib.nullcontext()
    if args.profile:
        import jax
        profile_cm = jax.profiler.trace(args.profile)
    try:
        with profile_cm:
            results = trainer.run()
    finally:
        if status_server is not None:
            status_server.stop()
        _maybe_write_trace(args)
    print(json.dumps(results))
    if args.publish:
        # after the results are emitted — a report typo must never eat a
        # finished training run
        from .publishing import Publisher
        out_dir, _, fmts = args.publish.partition(":")
        kinds = _publish_backends()
        backends = [kinds[f](out_dir) for f in _publish_fmts(fmts)]
        pub = Publisher(trainer.workflow.name, backends=backends)
        pub.gather(trainer=trainer, config=root)
        pub.publish()
    if args.result_file:
        import jax
        if jax.process_index() == 0:  # one writer per gang (cf. master's
            with open(args.result_file, "w") as f:  # --result-file)
                json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
