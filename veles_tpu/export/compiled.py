"""Compiled-artifact export: StableHLO programs + manifest + weights.

The package export (export/package.py) ships *weights and structure* —
the C++ runtime re-implements the math.  This module ships the
*compiled programs themselves*: the decode engine's fixed program set
(pow2-bucketed prefill + the single decode step, runtime/engine.py) and
the batched forward are lowered ONCE via ``jax.export`` and serialized
as StableHLO, so a PJRT client anywhere can run the sealed artifact
with zero model Python — the "compile the whole program once, run the
artifact" move of "Automatic Full Compilation of Julia Programs and ML
Models to Cloud TPUs" (arxiv 1810.09868), applied to serving.

The exported programs are built by the SAME module-level builders the
live engine compiles (:func:`~veles_tpu.runtime.engine.make_decode_fn`
/ ``make_prefill_fn``), so greedy tokens from the artifact are bitwise
the live engine's — one source of step math, never two.

Artifact layout (a directory, storable in a Forge like any package)::

    <out_dir>/
      artifact.json        # manifest: avals, bucket table, checksums
      tensors.npz          # params (+ state) — snapshotter discipline
      programs/forward.bin           # batched predict (when exportable)
      programs/prefill_<pb>.bin      # one per bucket
      programs/decode.bin            # the lifetime decode step

Integrity follows the snapshot checksum discipline: the manifest
records a sha256 per blob (of the in-memory bytes, so torn writes
fail the verify), written tmp+rename after an fsync, and the loader
(runtime/artifact.py, via ``sha256_files``) verifies before serving
— corruption raises ``SnapshotCorruptError``, exactly like a snapshot.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import Optional

import jax
import jax.export  # noqa: F401 — not auto-imported by `import jax`
import jax.numpy as jnp
import numpy as np

from ..units.workflow import WorkflowError

#: Manifest file name inside an artifact directory — the presence test
#: the deploy control plane uses to recognize ``artifact://`` sources.
MANIFEST = "artifact.json"
FORMAT = "veles-tpu-compiled-artifact"
#: 2 = paged KV-cache layout (cache avals are a page pool, the decode /
#: prefill calling conventions carry a page table, and the manifest
#: records ``paged`` / ``page_size`` / ``pages`` / ``prefix_reuse``).
#: 3 = every prefill program takes the traced ``start`` (the dense
#: convention grew it; paged always had it) and the manifest records
#: ``prefill_start: true`` — the chunked-prefill / preempt-resume
#: calling convention (docs/serving.md "Overload survival").  Version
#: 1 and 2 artifacts still load — the runner keeps the old dense
#: convention and gates chunking off — but v3 artifacts are refused by
#: older readers (docs/serving_export.md).  The megastep program
#: (``programs/megastep.bin`` + manifest ``megastep: {"n": N}``) is an
#: ADDITIVE v3 extension like ``spec_decode``: artifacts without it —
#: every v1/v2 artifact and any v3 export at megastep=1 — load
#: unchanged and serve plain per-token decode.
FORMAT_VERSION = 3


def _aval_rows(tree):
    """Flattened ``[{path, shape, dtype}]`` of a pytree of arrays /
    ShapeDtypeStructs — enough for the runner to rebuild zeroed state
    without any model code.  Paths use the snapshotter's '/'-joined
    form so ``_unflatten`` rebuilds the exact nesting."""
    from ..runtime.snapshotter import _flatten
    # stride-0 stand-ins: _flatten np.asarray's its leaves, and a
    # ShapeDtypeStruct must neither allocate nor land as dtype=object
    spoof = jax.tree.map(
        lambda a: np.broadcast_to(np.zeros((), np.dtype(a.dtype)),
                                  np.shape(a)), tree)
    return [_row(path, leaf)
            for path, leaf in sorted(_flatten(spoof).items())]


def _row(path: str, leaf) -> dict:
    """One manifest aval row — the schema runtime/artifact.py's
    ``_zeros_from_rows`` rebuilds from.  Structural markers
    (``__seq__`` / ``__emptydict__``) carry their VALUES (seq length,
    tuple-vs-list): ``_unflatten`` reads them, zeros would corrupt the
    rebuild."""
    row = {"path": path,
           "shape": [int(s) for s in leaf.shape],
           "dtype": str(leaf.dtype)}
    if path.rsplit("/", 1)[-1] in ("__seq__", "__emptydict__"):
        row["structure"] = np.asarray(leaf).tolist()
    return row


def _rows_from_flat(flat: dict, prefix: str):
    """Manifest aval rows for one subtree of an ALREADY-flattened
    host-side dict (the tensors blob) — shapes and dtypes without a
    second device-to-host copy of the weights."""
    pre = prefix + "/"
    return [_row(path[len(pre):], flat[path])
            for path in sorted(flat) if path.startswith(pre)]


def _export_one(fn, args_sds):
    """jax.export the jitted ``fn`` at the given ShapeDtypeStructs and
    return (serialized bytes, info dict for the manifest)."""
    exp = jax.export.export(fn)(*args_sds)
    info = {
        "platforms": list(exp.platforms),
        "calling_convention_version":
            int(exp.calling_convention_version),
        "in_avals": [str(a) for a in exp.in_avals],
        "out_avals": [str(a) for a in exp.out_avals],
    }
    return bytes(exp.serialize()), info


def _write_blob(path: str, data: bytes, staged: list) -> str:
    """Stage + fsync a blob at ``path + ".tmp"`` and record the
    (tmp, final) rename in ``staged``; returns its sha256 (snapshot
    discipline: the manifest's checksums must describe bytes that are
    on stable storage before the manifest commits, and a re-export that
    dies mid-way must leave the previous artifact's blobs untouched —
    everything lands under final names only at commit).  The hash is of
    the in-memory bytes, not a re-read of the file: a write torn by bad
    disk/RAM must FAIL the load-time verify, not be sealed into the
    manifest as the expected checksum."""
    import hashlib

    from ..runtime.snapshotter import _fsync_file
    tmp = path + ".tmp"
    # recorded BEFORE the write: a write/fsync that dies mid-blob
    # (ENOSPC) must still get its partial .tmp unlinked by the caller's
    # cleanup, not ship as a stray in a forge upload of the dir
    staged.append((tmp, path))
    with open(tmp, "wb") as f:
        f.write(data)
    _fsync_file(tmp)
    return hashlib.sha256(data).hexdigest()


def export_compiled(workflow, wstate, out_dir: str, *,
                    slots: Optional[int] = None,
                    l_max: Optional[int] = None,
                    bucket_min: Optional[int] = None,
                    paged: Optional[bool] = None,
                    page_size: Optional[int] = None,
                    pages: Optional[int] = None,
                    paged_kernel: Optional[bool] = None,
                    spec: Optional[bool] = None,
                    spec_k: Optional[int] = None,
                    megastep: Optional[int] = None,
                    cache_dtype=jnp.float32,
                    output_unit: Optional[str] = None,
                    input_spec: Optional[dict] = None,
                    eos_id: Optional[int] = None) -> dict:
    """Export ``workflow``'s inference step family as a sealed compiled
    artifact under ``out_dir``; returns the manifest dict.

    Always exports the batched **forward** (``make_predict_step`` at the
    build batch shape, or ``input_spec`` {"shape", "dtype"} when given).
    For decodable sequence chains additionally exports the engine's
    **fixed program set** — one prefill per pow2 bucket and the single
    decode step — sized by ``slots`` / ``l_max`` / ``bucket_min`` /
    ``paged`` / ``page_size`` / ``pages`` (defaults from
    ``root.common.serve``, the live engine's own knobs).  Under the
    default paged layout the sealed programs carry the per-slot page
    table in their calling convention and the manifest records the pool
    geometry plus ``prefix_reuse`` (whether the chain's state is pure
    attention KV, i.e. safe for shared-prefix shortcuts) — the
    ArtifactRunner rebuilds the exact paged engine, scheduler-side
    prefix cache included.  A chain ``DecodePlan`` rejects simply ships
    forward-only (the manifest omits the decode program and records why
    under ``decode_unsupported``).

    ``spec`` / ``spec_k`` (defaults ``root.common.serve.spec.*``)
    additionally seal the speculative **verify** program — the third
    program kind, one program at static ``spec_k`` — and record
    ``spec_decode: {"k": K}`` in the manifest; an ``ArtifactRunner``
    serves speculative decode only when that program is sealed (old
    artifacts load unchanged, ``spec_decode`` absent).  ``paged_kernel``
    seals the fused Pallas paged-attention read path into the decode /
    verify programs (bounded-error; manifest records it).

    ``megastep`` (default ``root.common.serve.megastep``; > 1)
    additionally seals the decode **megastep** program — the fourth
    program kind, N micro-steps fused per dispatch at the decode
    calling convention — and records ``megastep: {"n": N}``; an
    ``ArtifactRunner`` fuses steps only when that program is sealed and
    falls back to plain per-token decode otherwise.
    """
    from ..config import root
    from ..runtime.engine import (bucket_table, make_decode_fn,
                                  make_megastep_fn, make_prefill_fn,
                                  make_verify_fn, resolve_serve_geometry)
    from ..runtime.generate import DecodePlan
    from ..runtime.snapshotter import _flatten, _fsync_dir, _to_numpy
    from ..units.base import Context
    from ..units.nn import input_vocab as _input_vocab

    geo = resolve_serve_geometry(slots, l_max, bucket_min, paged=paged,
                                 page_size=page_size, pages=pages,
                                 paged_kernel=paged_kernel,
                                 megastep=megastep)
    slots, l_max, bucket_min = geo.slots, geo.l_max, geo.bucket_min
    mega_n = geo.megastep
    spec_on = bool(root.common.serve.spec.get("enabled", False)
                   if spec is None else spec)
    spec_k = int(root.common.serve.spec.get("k", 4)
                 if spec_k is None else spec_k)
    if spec_on and spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")

    prog_dir = os.path.join(out_dir, "programs")
    os.makedirs(prog_dir, exist_ok=True)
    # strays from an export that died mid-staging would otherwise ship
    # in forge uploads of the directory
    for stray in os.listdir(prog_dir):
        if stray.endswith(".tmp"):
            os.unlink(os.path.join(prog_dir, stray))
    for stray in ("tensors.npz.tmp", MANIFEST + ".tmp"):
        stray = os.path.join(out_dir, stray)
        if os.path.exists(stray):
            os.unlink(stray)
    staged: list = []
    params = wstate["params"]
    state = wstate.get("state") or {}
    # eos is sealed as the serving default — a bad value would 400
    # every /generate of the artifact, so reject it BEFORE paying for
    # lowering/serialization.  Serving bounds eos by the INPUT
    # embedding rows (restful._vocab_size); the head vocab is checked
    # below once the decode plan reveals it.
    input_vocab = _input_vocab(workflow, params)
    if eos_id is not None and (int(eos_id) < 0 or (
            input_vocab is not None and int(eos_id) >= input_vocab)):
        raise ValueError(
            f"eos_id {eos_id} is outside the exported model's "
            f"vocabulary [0, "
            f"{input_vocab if input_vocab is not None else '?'})")
    try:
        # -- weights blob (snapshotter flatten + _write_blob staging, so
        # the manifest hash is of the in-memory npz bytes like every
        # program blob; the compressed buffer is transient) ---------------
        tensors = _flatten(_to_numpy({"params": params, "state": state}))
        buf = io.BytesIO()
        # a handle, not the path: savez would append ".npz"
        np.savez_compressed(buf, **tensors)
        tensors_sha = _write_blob(os.path.join(out_dir, "tensors.npz"),
                                  buf.getvalue(), staged)
        del buf

        programs: dict = {}

        # -- batched forward ----------------------------------------------
        head = output_unit or workflow.default_output()
        if input_spec is None:
            spec = getattr(workflow, "_input_specs", {}).get("@input")
            if spec is not None:
                input_spec = {"shape": [int(s) for s in spec.shape],
                              "dtype": str(spec.dtype)}
        if input_spec is not None:
            predict = workflow.make_predict_step(head, jit=False)

            def forward(params, state, x):
                return predict({"params": params, "state": state},
                               {"@input": x})

            fwd_sds = (jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), params),
                jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), state),
                jax.ShapeDtypeStruct(tuple(input_spec["shape"]),
                                     jnp.dtype(input_spec["dtype"])))
            blob, info = _export_one(jax.jit(forward), fwd_sds)
            sha = _write_blob(os.path.join(out_dir, "programs", "forward.bin"),
                              blob, staged)
            programs["forward"] = dict(info, file="programs/forward.bin",
                                       sha256=sha)

        # -- decode program family (the engine's fixed set) ---------------
        decode_meta = None
        vocab = None
        cache_rows = []
        try:
            plan = DecodePlan(workflow, output_unit)
        except WorkflowError as e:
            plan, decode_reason = None, f"{type(e).__name__}: {e}"
        if plan is not None:
            ctx = Context(train=False, key=None, mesh=None)
            psz = geo.page_size if geo.paged else None
            # avals only — never materialize the slot-batch KV caches on
            # the export host (slots x l_max can be GBs for a real LM)
            csds = jax.eval_shape(
                lambda p: plan.init_caches(
                    p, slots, l_max, cache_dtype,
                    kv_rows=geo.pages + 1 if geo.paged else None,
                    page_size=psz),
                params)
            cache_rows = _aval_rows(csds)
            kd = jax.random.key_data(jax.random.key(0))
            S = slots
            psds = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), params)
            i32 = lambda *sh: jax.ShapeDtypeStruct(sh, jnp.int32)  # noqa: E731
            f32 = lambda *sh: jax.ShapeDtypeStruct(  # noqa: E731
                sh, jnp.float32)
            toks = jax.ShapeDtypeStruct((S, l_max), jnp.int32)
            keys = jax.ShapeDtypeStruct((S,) + kd.shape, kd.dtype)
            pages_arg = None
            if geo.paged:
                pages_arg = (jnp.zeros((S, geo.n_ptab), jnp.int32), psz,
                             jnp.zeros(S, bool))
            vocab = int(jax.eval_shape(
                lambda p, c, t, pv: plan.step(p, c, t, pv, ctx,
                                              pages=pages_arg)[0],
                psds, dict(csds), i32(S), i32(S)).shape[-1])
            if eos_id is not None and not 0 <= int(eos_id) < vocab:
                raise ValueError(f"eos_id {eos_id} is outside the "
                                 f"exported model's vocabulary "
                                 f"[0, {vocab})")

            if geo.paged:  # page table rides the calling convention
                decode_sds = (psds, csds, toks, i32(S, geo.n_ptab),
                              i32(S), jax.ShapeDtypeStruct((S,), jnp.bool_),
                              f32(S), i32(S), f32(S), i32(S), i32(S), keys)
            else:
                decode_sds = (psds, csds, toks, i32(S),
                              jax.ShapeDtypeStruct((S,), jnp.bool_),
                              f32(S), i32(S), f32(S), i32(S), i32(S), keys)
            blob, info = _export_one(
                make_decode_fn(plan, ctx, S, page_size=psz,
                               paged_kernel=geo.paged_kernel),
                decode_sds)
            sha = _write_blob(
                os.path.join(out_dir, "programs", "decode.bin"), blob, staged)
            decode_meta = dict(info, file="programs/decode.bin", sha256=sha)

            if spec_on:
                # the speculative verify program: decode's calling
                # convention + the (S, K) draft matrix — sealed at ONE
                # static k, the manifest's spec_decode contract
                blob, info = _export_one(
                    make_verify_fn(plan, ctx, S, spec_k, page_size=psz,
                                   paged_kernel=geo.paged_kernel),
                    decode_sds + (i32(S, spec_k),))
                sha = _write_blob(
                    os.path.join(out_dir, "programs", "verify.bin"),
                    blob, staged)
                programs["verify"] = dict(info,
                                          file="programs/verify.bin",
                                          sha256=sha)

            if mega_n > 1:
                # the megastep program: decode's exact calling
                # convention, N micro-steps fused — sealed at ONE
                # static N, the manifest's megastep contract
                blob, info = _export_one(
                    make_megastep_fn(plan, ctx, S, mega_n,
                                     page_size=psz,
                                     paged_kernel=geo.paged_kernel),
                    decode_sds)
                sha = _write_blob(
                    os.path.join(out_dir, "programs", "megastep.bin"),
                    blob, staged)
                programs["megastep"] = dict(
                    info, file="programs/megastep.bin", sha256=sha)

            prefills = {}
            for pb in bucket_table(bucket_min, l_max):
                if geo.paged:
                    pre_sds = (psds, csds, toks, i32(geo.n_ptab),
                               i32(1, pb), i32(), i32(), i32(), f32(),
                               i32(), f32(),
                               jax.ShapeDtypeStruct(kd.shape, kd.dtype))
                else:
                    # v3 dense convention: (prompt, new_len, start,
                    # slot, temp, topk, topp, key) — the traced start
                    # the chunked-prefill / preempt-resume path feeds
                    pre_sds = (psds, csds, toks, i32(1, pb), i32(),
                               i32(), i32(), f32(), i32(), f32(),
                               jax.ShapeDtypeStruct(kd.shape, kd.dtype))
                # lint: disable=VP601 pb ranges over bucket_table(
                # bucket_min, l_max) — the fixed static prefill
                # inventory the manifest seals; one program per bucket
                # is the design, not a recompile stream
                fn = make_prefill_fn(plan, ctx, pb, cache_dtype,
                                     page_size=psz)
                # lint: disable=VP601 same bounded bucket inventory
                blob, info = _export_one(fn, pre_sds)
                fname = f"programs/prefill_{pb}.bin"
                sha = _write_blob(os.path.join(out_dir, fname), blob, staged)
                prefills[str(pb)] = dict(info, file=fname, sha256=sha)
            programs["decode"] = decode_meta
            programs["prefill"] = prefills

        manifest = {
            "format": FORMAT,
            "format_version": FORMAT_VERSION,
            "workflow": workflow.name,
            "workflow_checksum": workflow.checksum(),
            "jax_version": jax.__version__,
            "saved_at": time.time(),
            "tensors": "tensors.npz",
            "tensors_sha256": tensors_sha,
            "params": _rows_from_flat(tensors, "params"),
            "state": _rows_from_flat(tensors, "state"),
            "caches": cache_rows,
            "slots": slots, "l_max": l_max, "bucket_min": bucket_min,
            "buckets": bucket_table(bucket_min, l_max) if decode_meta
            else [],
            # paged-cache layout (FORMAT_VERSION 2): the pool geometry is
            # part of the sealed calling convention, and prefix_reuse
            # records whether the chain's cached state is pure attention
            # KV (recurrent carried state cannot take prefix shortcuts)
            "paged": bool(geo.paged and decode_meta),
            "page_size": geo.page_size if geo.paged else None,
            "pages": geo.pages if geo.paged else None,
            "paged_kernel": bool(geo.paged_kernel and decode_meta),
            "prefix_reuse": bool(geo.paged and decode_meta and plan
                                 is not None and not plan._rec_units),
            # FORMAT_VERSION 3: sealed prefill programs take the traced
            # ``start`` on BOTH layouts, so the runner may chunk
            # prefills and resume preempted slots mid-prompt; absent
            # (older artifacts) the runner serves unchunked and keeps
            # the dense whole-prompt calling convention
            "prefill_start": bool(decode_meta),
            # speculative decode support: present (with the sealed
            # verify program's static k) only when the verify program
            # is part of the sealed inventory — the ArtifactRunner's
            # serve-spec-or-reject contract
            "spec_decode": ({"k": spec_k} if spec_on and decode_meta
                            else None),
            # megastep decode support: present (with the sealed fused
            # program's static N) only when the megastep program is in
            # the sealed inventory — artifacts without it serve plain
            # per-token decode (additive; v1/v2 load unchanged)
            "megastep": ({"n": mega_n} if mega_n > 1 and decode_meta
                         else None),
            "cache_dtype": jnp.dtype(cache_dtype).name,
            "vocab": vocab,
            "input_vocab": input_vocab,
            "eos_id": eos_id,
            "input_spec": input_spec,
            "programs": programs,
        }
        if decode_meta is None and plan is None:
            manifest["decode_unsupported"] = decode_reason

        # -- commit: everything above only staged *.tmp files.  The
        # manifest is staged too, so the flip is back-to-back renames
        # (blobs first, manifest last) — a death anywhere before the
        # loop leaves the previous artifact fully intact, manifest
        # included; a death INSIDE it leaves old manifest + new blobs,
        # which the loader's checksum verify detects (the window is the
        # renames themselves — true multi-file atomicity would need a
        # versioned dir + symlink flip, changing the artifact path
        # contract).
        man_path = os.path.join(out_dir, MANIFEST)
        man_tmp = man_path + ".tmp"
        keep = {os.path.basename(final) for _, final in staged}
        # staged before the write so a mid-write death still cleans it
        staged.append((man_tmp, man_path))
        with open(man_tmp, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        for tmp, final in staged:
            os.replace(tmp, final)
        for leftover in os.listdir(prog_dir):
            # re-export into the same dir: programs not in the new manifest
            # would otherwise ship as orphan sealed blobs (.tmp: strays of
            # an export killed between the sweeps above and this commit)
            if leftover.endswith((".bin", ".tmp")) and leftover not in keep:
                os.unlink(os.path.join(prog_dir, leftover))
        _fsync_dir(prog_dir)
        _fsync_dir(out_dir)
        return manifest
    except BaseException:
        # a dead export must not leave *.tmp strays for a forge
        # upload of the directory to ship
        for tmp, _ in staged:
            if os.path.exists(tmp):
                os.unlink(tmp)
        raise


def manifest_summary(manifest: dict) -> dict:
    """Compact human-facing view of an artifact manifest (the CLI's
    ``--export --compiled`` output)."""
    progs = manifest.get("programs", {})
    return {
        "workflow": manifest.get("workflow"),
        "checksum": (manifest.get("workflow_checksum") or "")[:12],
        "jax_version": manifest.get("jax_version"),
        "slots": manifest.get("slots"), "l_max": manifest.get("l_max"),
        "paged": manifest.get("paged", False),
        "page_size": manifest.get("page_size"),
        "pages": manifest.get("pages"),
        "paged_kernel": manifest.get("paged_kernel", False),
        "spec_decode": manifest.get("spec_decode"),
        "megastep": manifest.get("megastep"),
        "buckets": manifest.get("buckets"),
        "vocab": manifest.get("vocab"),
        "programs": sorted(
            [p["file"] for k, p in progs.items() if k != "prefill"]
            + [p["file"] for p in progs.get("prefill", {}).values()]),
        "tensors_sha256": (manifest.get("tensors_sha256") or "")[:12],
    }
