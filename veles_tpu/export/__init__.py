from .package import export_package, load_package
