from .compiled import export_compiled, manifest_summary
from .package import export_package, load_package
