"""Workflow package export for the native serving runtime.

Reference parity: ``Workflow.package_export()`` (reference:
veles/workflow.py:868) produced an archive of ``contents.json`` + ``.npy``
weight files that the C++ libVeles runtime loaded via UnitFactory UUIDs
(libVeles/src/main_file_loader.h:61-80 UnitDefinition,
inc/veles/numpy_array_loader.h). This module keeps that package shape —
contents.json + npy entries in a zip — so the serving/ C++ runtime and its
golden-fixture test pattern (libVeles/tests/workflow_files/) carry over.

The unit 'uuid' of the reference becomes the registered class name; each
exported unit records its constructor config and tensor refs."""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, Optional

import jax
import numpy as np

from ..runtime.snapshotter import _commit_bytes, _fsync_dir, _fsync_file
from ..units.workflow import Workflow

#: Exportable unit types and the constructor fields the native runtime
#: needs. Units not listed fall back to their public scalar attrs.
_EXPORT_FIELDS = {
    "All2All": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllTanh": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllRELU": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllSincos": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllSoftmax": ("output_size", "activation", "include_bias",
                "per_position"),
    "Conv": ("n_kernels", "kx", "ky", "stride", "padding", "activation"),
    "ConvRELU": ("n_kernels", "kx", "ky", "stride", "padding",
                 "activation"),
    "ConvTanh": ("n_kernels", "kx", "ky", "stride", "padding",
                 "activation"),
    "MaxPooling": ("window", "stride"),
    "AvgPooling": ("window", "stride"),
    "LRN": ("n", "k", "alpha", "beta", "method"),
    "Dropout": ("ratio",),
    "Flatten": (),
    "Reshape": ("shape",),
    "MeanDispNormalizer": (),
    "LayerNorm": ("eps",),
    "FFN": ("d_hidden", "activation", "residual"),
    "Embedding": ("vocab", "dim"),
    "SeqLast": (),
    "MultiHeadAttention": ("n_heads", "n_kv_heads", "head_dim", "causal",
                           "window", "block_size", "seq_axis", "rope",
                           "residual"),
    # recurrent family (round 3: served natively; the reference's own
    # libVeles contract was "any registered unit loads",
    # libVeles/inc/veles/unit_factory.h)
    "RNN": ("hidden", "return_sequences", "activation"),
    "GRU": ("hidden", "return_sequences"),
    "LSTM": ("hidden", "return_sequences", "forget_bias"),
    "MoEFFN": ("n_experts", "d_hidden", "top_k", "capacity_factor"),
    "KohonenForward": ("sx", "sy"),
    "RBM": ("n_hidden",),
    "EvaluatorSoftmax": (),
    "EvaluatorMSE": (),
    # identity passthroughs the native runtime maps to IdentityUnit
    "Avatar": (),
    "TrivialUnit": (),
}


def _unit_config(unit) -> dict:
    fields = _EXPORT_FIELDS.get(type(unit).__name__)
    if fields is None:
        fields = [k for k, v in vars(unit).items()
                  if not k.startswith("_") and isinstance(
                      v, (int, float, str, bool))]
    cfg = {}
    for f in fields:
        v = getattr(unit, f, None)
        # Normalize pair forms so the native runtime sees scalars or
        # explicit lists, never Python tuples with mixed meaning.
        if f in ("stride", "window") and isinstance(v, (tuple, list)):
            v = list(v)
            if len(v) == 2 and v[0] == v[1]:
                v = v[0]
        elif f == "padding" and isinstance(v, (tuple, list)):
            flat = []
            for p in v:
                flat.extend(p if isinstance(p, (tuple, list)) else [p])
            v = flat
        elif isinstance(v, tuple):
            v = list(v)
        cfg[f] = v
    return cfg


def _stack_sub_units(stack):
    """The units a PipelineStack expands into at export (config form);
    legacy homogeneous stages expand into FFN units, always servable."""
    if stack._stage_units is None:
        return []
    return [su for units in stack._stage_units for su in units]


def _expand_stack_entries(stack, ptree):
    """Yield (name, class, config, weights, input) unit entries replacing
    a PipelineStack with its sequential stage chain (pipe=1 math).

    Legacy form: each stage ``x + relu(x @ w1) @ w2`` IS an FFN unit with
    zero biases. Config form: the stage sub-units export as themselves.
    """
    prev = stack.inputs[0]
    if stack._stage_units is not None:
        flat = [(i, su) for i, units in enumerate(stack._stage_units)
                for su in units]
        for idx, (i, su) in enumerate(flat):
            name = stack.name if idx == len(flat) - 1 \
                else f"{stack.name}__s{i}_{su.name}"
            w = ptree.get(f"s{i}", {}).get(su.name, {})
            yield name, type(su).__name__, _unit_config(su), w, prev
            prev = name
        return
    w1, w2 = ptree["stage_w1"], ptree["stage_w2"]
    S, E, H = w1.shape[0], w1.shape[1], w1.shape[2]
    for i in range(S):
        name = stack.name if i == S - 1 else f"{stack.name}__s{i}_ffn"
        cfg = {"d_hidden": int(H), "activation": "relu", "residual": True}
        w = {"w1": w1[i], "b1": np.zeros(H, np.float32),
             "w2": w2[i], "b2": np.zeros(E, np.float32)}
        yield name, "FFN", cfg, w, prev
        prev = name


def export_package(workflow: Workflow, wstate: dict, path: str, *,
                   input_spec: Optional[dict] = None,
                   servable: bool = True) -> str:
    """Write a serving package zip: contents.json + <unit>_<param>.npy.

    ``servable=True`` (default) validates every unit against the native
    runtime's family coverage at EXPORT time — an unsupported unit fails
    here with a clear message instead of at the C++ loader (reference
    contract: any registered unit loads, libVeles/inc/veles/
    unit_factory.h; round-2 verdict missing #1). Pass ``servable=False``
    for Python-side-only packages (forge uploads).
    """
    from ..units.parallel_nn import PipelineStack
    if servable:
        bad = []
        for u in workflow.topo_order():
            if isinstance(u, PipelineStack):
                # the stack exports UNSTACKED (see _expand_stack_entries);
                # validate what it expands into
                for su in _stack_sub_units(u):
                    if type(su).__name__ not in _EXPORT_FIELDS:
                        bad.append(f"{u.name}/{su.name} "
                                   f"({type(su).__name__})")
                continue
            if type(u).__name__ not in _EXPORT_FIELDS:
                bad.append(f"{u.name} ({type(u).__name__})")
        if bad:
            raise ValueError(
                "units not supported by the native serving runtime: "
                + ", ".join(bad) + ". See docs/serving_export.md for "
                "the family coverage matrix; pass servable=False for a "
                "Python-side-only package")
    units = []
    arrays: Dict[str, np.ndarray] = {}
    params = jax.device_get(wstate["params"])
    state = jax.device_get(wstate["state"])

    for u in workflow.topo_order():
        if isinstance(u, PipelineStack):
            # Pipeline parallelism is a TRAINING-time sharding construct;
            # stages are ordinary shape-preserving units, so the export
            # unstacks them into the plain sequential chain (same math —
            # the pipe=1 fallback) and the native runtime serves it with
            # no stack-specific machinery. The last expanded unit takes
            # the stack's name so downstream inputs resolve unchanged.
            for name, klass, cfg, wdict, inp in _expand_stack_entries(
                    u, params.get(u.name, {})):
                entry = {"name": name, "class": klass, "inputs": [inp],
                         "config": cfg, "weights": {}}
                for pname, arr in wdict.items():
                    fname = f"{name}_{pname}.npy"
                    arrays[fname] = np.asarray(arr)
                    entry["weights"][pname] = fname
                units.append(entry)
            continue
        entry = {
            "name": u.name,
            "class": type(u).__name__,
            "inputs": list(u.inputs),
            "config": _unit_config(u),
            "weights": {},
        }
        for source, tree in (("params", params), ("state", state)):
            for pname, arr in tree.get(u.name, {}).items():
                if not hasattr(arr, "shape"):
                    continue
                # a name collision between params and state would silently
                # clobber; disambiguate with the source prefix
                key = pname if pname not in entry["weights"] \
                    else f"{source}_{pname}"
                fname = f"{u.name}_{key}.npy"
                arrays[fname] = np.asarray(arr)
                entry["weights"][key] = fname
        units.append(entry)

    contents = {
        "workflow": workflow.name,
        "checksum": workflow.checksum(),
        "format_version": 1,
        "units": units,
    }
    if input_spec is not None:
        contents["input_spec"] = input_spec

    # every blob serialized up front under a CONTENT-ADDRESSED name
    # (`<unit>_<param>.<sha12>.npy`), then committed crash-safely: the
    # previous export's blobs are never overwritten, so a crash at ANY
    # point — staging, blob renames, the manifest — leaves the old
    # manifest paired with the old bytes it names (every reader,
    # load_package / deploy / the C++ runtime, resolves blob names
    # through contents.json).  Manifest lands LAST; stale blobs from
    # prior exports are swept only after it commits.  The VR704 lint
    # rule pins the tmp-fsync-rename half of this discipline.
    import hashlib
    import os
    blobs: Dict[str, bytes] = {}
    renames: Dict[str, str] = {}
    for fname, arr in arrays.items():
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr, np.float32))
        data = buf.getvalue()
        digest = hashlib.sha256(data).hexdigest()[:12]
        final = f"{fname[:-len('.npy')]}.{digest}.npy"
        renames[fname] = final
        blobs[final] = data
    for entry in units:
        entry["weights"] = {k: renames[v]
                            for k, v in entry["weights"].items()}
    manifest = json.dumps(contents, indent=1).encode()

    if path.endswith(".zip"):
        tmp = path + ".tmp"
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("contents.json", manifest.decode())
            for fname, data in blobs.items():
                z.writestr(fname, data)
        _fsync_file(tmp)
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
    else:  # directory package (what the C++ serving runtime consumes)
        os.makedirs(path, exist_ok=True)
        staged = []
        for fname, data in blobs.items():
            tmp = os.path.join(path, fname + ".tmp")
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            staged.append((tmp, os.path.join(path, fname)))
        for tmp, target in staged:
            os.replace(tmp, target)
        # persist the blob renames BEFORE the manifest commit: POSIX
        # orders nothing between successive renames without a dir
        # fsync, and a durable new manifest must never name a blob
        # whose rename was lost to power loss
        _fsync_dir(path)
        _commit_bytes(os.path.join(path, "contents.json"), manifest)
        # post-commit sweep: blobs no manifest names anymore, and tmp
        # strays from any earlier crashed export — then persist the
        # rename/unlink metadata so a power loss cannot durably apply
        # the sweep while losing the commit it depends on
        keep = set(blobs) | {"contents.json"}
        for fn in os.listdir(path):
            if fn not in keep and (fn.endswith(".npy")
                                   or fn.endswith(".tmp")):
                try:
                    os.unlink(os.path.join(path, fn))
                except OSError:
                    pass
        _fsync_dir(path)
    return path


def load_package(path: str) -> dict:
    """Load a package back (Python side — used by tests and the RESTful
    server; the C++ runtime has its own loader)."""
    import os
    if path.endswith(".zip"):
        with zipfile.ZipFile(path) as z:
            contents = json.loads(z.read("contents.json"))
            for u in contents["units"]:
                tensors = {}
                for pname, fname in u["weights"].items():
                    tensors[pname] = np.load(io.BytesIO(z.read(fname)))
                u["tensors"] = tensors
    else:
        with open(os.path.join(path, "contents.json")) as f:
            contents = json.load(f)
        for u in contents["units"]:
            u["tensors"] = {
                pname: np.load(os.path.join(path, fname))
                for pname, fname in u["weights"].items()}
    return contents
