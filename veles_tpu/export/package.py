"""Workflow package export for the native serving runtime.

Reference parity: ``Workflow.package_export()`` (reference:
veles/workflow.py:868) produced an archive of ``contents.json`` + ``.npy``
weight files that the C++ libVeles runtime loaded via UnitFactory UUIDs
(libVeles/src/main_file_loader.h:61-80 UnitDefinition,
inc/veles/numpy_array_loader.h). This module keeps that package shape —
contents.json + npy entries in a zip — so the serving/ C++ runtime and its
golden-fixture test pattern (libVeles/tests/workflow_files/) carry over.

The unit 'uuid' of the reference becomes the registered class name; each
exported unit records its constructor config and tensor refs."""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, Optional

import jax
import numpy as np

from ..units.workflow import Workflow

#: Exportable unit types and the constructor fields the native runtime
#: needs. Units not listed fall back to their public scalar attrs.
_EXPORT_FIELDS = {
    "All2All": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllTanh": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllRELU": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllSincos": ("output_size", "activation", "include_bias",
                "per_position"),
    "All2AllSoftmax": ("output_size", "activation", "include_bias",
                "per_position"),
    "Conv": ("n_kernels", "kx", "ky", "stride", "padding", "activation"),
    "ConvRELU": ("n_kernels", "kx", "ky", "stride", "padding",
                 "activation"),
    "ConvTanh": ("n_kernels", "kx", "ky", "stride", "padding",
                 "activation"),
    "MaxPooling": ("window", "stride"),
    "AvgPooling": ("window", "stride"),
    "LRN": ("n", "k", "alpha", "beta", "method"),
    "Dropout": ("ratio",),
    "Flatten": (),
    "Reshape": ("shape",),
    "MeanDispNormalizer": (),
    "LayerNorm": ("eps",),
    "FFN": ("d_hidden", "activation", "residual"),
    "Embedding": ("vocab", "dim"),
    "SeqLast": (),
    "MultiHeadAttention": ("n_heads", "n_kv_heads", "head_dim", "causal",
                           "window", "block_size", "seq_axis", "rope",
                           "residual"),
    # recurrent family (round 3: served natively; the reference's own
    # libVeles contract was "any registered unit loads",
    # libVeles/inc/veles/unit_factory.h)
    "RNN": ("hidden", "return_sequences", "activation"),
    "GRU": ("hidden", "return_sequences"),
    "LSTM": ("hidden", "return_sequences", "forget_bias"),
    "MoEFFN": ("n_experts", "d_hidden", "top_k", "capacity_factor"),
    "KohonenForward": ("sx", "sy"),
    "RBM": ("n_hidden",),
    "EvaluatorSoftmax": (),
    "EvaluatorMSE": (),
    # identity passthroughs the native runtime maps to IdentityUnit
    "Avatar": (),
    "TrivialUnit": (),
}


def _unit_config(unit) -> dict:
    fields = _EXPORT_FIELDS.get(type(unit).__name__)
    if fields is None:
        fields = [k for k, v in vars(unit).items()
                  if not k.startswith("_") and isinstance(
                      v, (int, float, str, bool))]
    cfg = {}
    for f in fields:
        v = getattr(unit, f, None)
        # Normalize pair forms so the native runtime sees scalars or
        # explicit lists, never Python tuples with mixed meaning.
        if f in ("stride", "window") and isinstance(v, (tuple, list)):
            v = list(v)
            if len(v) == 2 and v[0] == v[1]:
                v = v[0]
        elif f == "padding" and isinstance(v, (tuple, list)):
            flat = []
            for p in v:
                flat.extend(p if isinstance(p, (tuple, list)) else [p])
            v = flat
        elif isinstance(v, tuple):
            v = list(v)
        cfg[f] = v
    return cfg


def export_package(workflow: Workflow, wstate: dict, path: str, *,
                   input_spec: Optional[dict] = None,
                   servable: bool = True) -> str:
    """Write a serving package zip: contents.json + <unit>_<param>.npy.

    ``servable=True`` (default) validates every unit against the native
    runtime's family coverage at EXPORT time — an unsupported unit fails
    here with a clear message instead of at the C++ loader (reference
    contract: any registered unit loads, libVeles/inc/veles/
    unit_factory.h; round-2 verdict missing #1). Pass ``servable=False``
    for Python-side-only packages (forge uploads).
    """
    if servable:
        bad = [f"{u.name} ({type(u).__name__})"
               for u in workflow.topo_order()
               if type(u).__name__ not in _EXPORT_FIELDS]
        if bad:
            raise ValueError(
                "units not supported by the native serving runtime: "
                + ", ".join(bad) + ". See docs/serving_export.md for "
                "the family coverage matrix; pass servable=False for a "
                "Python-side-only package")
    units = []
    arrays: Dict[str, np.ndarray] = {}
    params = jax.device_get(wstate["params"])
    state = jax.device_get(wstate["state"])

    for u in workflow.topo_order():
        entry = {
            "name": u.name,
            "class": type(u).__name__,
            "inputs": list(u.inputs),
            "config": _unit_config(u),
            "weights": {},
        }
        for source, tree in (("params", params), ("state", state)):
            for pname, arr in tree.get(u.name, {}).items():
                if not hasattr(arr, "shape"):
                    continue
                # a name collision between params and state would silently
                # clobber; disambiguate with the source prefix
                key = pname if pname not in entry["weights"] \
                    else f"{source}_{pname}"
                fname = f"{u.name}_{key}.npy"
                arrays[fname] = np.asarray(arr)
                entry["weights"][key] = fname
        units.append(entry)

    contents = {
        "workflow": workflow.name,
        "checksum": workflow.checksum(),
        "format_version": 1,
        "units": units,
    }
    if input_spec is not None:
        contents["input_spec"] = input_spec

    if path.endswith(".zip"):
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("contents.json", json.dumps(contents, indent=1))
            for fname, arr in arrays.items():
                buf = io.BytesIO()
                np.save(buf, np.ascontiguousarray(arr, np.float32))
                z.writestr(fname, buf.getvalue())
    else:  # directory package (what the C++ serving runtime consumes)
        import os
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "contents.json"), "w") as f:
            json.dump(contents, f, indent=1)
        for fname, arr in arrays.items():
            np.save(os.path.join(path, fname),
                    np.ascontiguousarray(arr, np.float32))
    return path


def load_package(path: str) -> dict:
    """Load a package back (Python side — used by tests and the RESTful
    server; the C++ runtime has its own loader)."""
    import os
    if path.endswith(".zip"):
        with zipfile.ZipFile(path) as z:
            contents = json.loads(z.read("contents.json"))
            for u in contents["units"]:
                tensors = {}
                for pname, fname in u["weights"].items():
                    tensors[pname] = np.load(io.BytesIO(z.read(fname)))
                u["tensors"] = tensors
    else:
        with open(os.path.join(path, "contents.json")) as f:
            contents = json.load(f)
        for u in contents["units"]:
            u["tensors"] = {
                pname: np.load(os.path.join(path, fname))
                for pname, fname in u["weights"].items()}
    return contents
