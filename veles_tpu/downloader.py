"""Dataset fetch+unpack at init (reference: veles/downloader.py:56 — a unit
that downloads an archive URL into the data dir and extracts it before the
loader runs).

Redesigned as a plain function the loader calls from ``load_data`` — in a
functional framework there is no "unit that runs once"; side-effecting setup
happens on the host before tracing. Network egress is environment-gated:
when the URL is unreachable the error tells the user to pre-seed the cache
directory, and an already-populated cache short-circuits the fetch entirely
(same idempotence contract as the reference's existence check).
"""

from __future__ import annotations

import hashlib
import os
import tarfile
import urllib.request
import zipfile

from .logger import Logger

_log = Logger()


def fetch(url: str, dest_dir: str, *, sha256: str = "",
          extract: bool = True, timeout: float = 60.0) -> str:
    """Ensure ``url``'s payload exists under ``dest_dir``; return the local
    archive path. Skips download when the target file already exists (and
    matches ``sha256`` if given). Extracts tar/zip archives alongside."""
    os.makedirs(dest_dir, exist_ok=True)
    fname = os.path.basename(url.split("?", 1)[0]) or "download"
    path = os.path.join(dest_dir, fname)
    marker = path + ".extracted"
    cached = os.path.exists(path) and _checksum_ok(path, sha256)
    if not cached:
        tmp = path + ".part"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as f:
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    f.write(chunk)
        except OSError as e:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise IOError(
                f"cannot fetch {url} ({e}); this environment may have no "
                f"network egress — place the file at {path} manually"
            ) from e
        if not _checksum_ok(tmp, sha256):
            os.unlink(tmp)
            raise IOError(f"checksum mismatch for {url}")
        os.replace(tmp, path)
        _log.info("downloaded %s -> %s", url, path)
    if extract and not (cached and os.path.exists(marker)):
        extract_archive(path, dest_dir)
        with open(marker, "w") as f:
            f.write("ok")
    return path


def _checksum_ok(path: str, sha256: str) -> bool:
    if not sha256:
        return True
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == sha256


def safe_extract_tar(tar: tarfile.TarFile, dest_dir: str) -> None:
    """Extract with tarfile's "data" filter: rejects absolute paths and
    ``..`` escapes AND symlink/hardlink members that point outside the
    destination — a plain name check misses the symlink case because
    realpath cannot resolve a link that extractall is about to create."""
    try:
        tar.extractall(dest_dir, filter="data")
    except tarfile.FilterError as e:
        raise IOError(f"unsafe archive member: {e}") from e


def extract_archive(path: str, dest_dir: str) -> None:
    """Extract tar(.gz/.bz2/.xz) and zip archives; other files are left as
    is. Members escaping dest_dir (via ../ or symlinks) are rejected."""
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as tar:
            safe_extract_tar(tar, dest_dir)
    elif zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            base = os.path.realpath(dest_dir)
            for name in z.namelist():
                target = os.path.realpath(os.path.join(dest_dir, name))
                if not target.startswith(base + os.sep) and target != base:
                    raise IOError(f"unsafe archive member: {name}")
            z.extractall(dest_dir)
