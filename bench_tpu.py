#!/usr/bin/env python
"""TPU Pallas kernel smoke + benchmark: every hand-written kernel compiled
through Mosaic on the real chip, numerics checked against its jnp/XLA
reference, and timed against the plain-XLA formulation.

Round-1 verdict gap: the Pallas suite was only ever exercised with
``interpret=True`` on CPU (tests/conftest.py pins CPU); interpret mode can
pass while real lowering fails or is slow.  This script is the proof run —
the reference analog is the per-backend same-math test discipline of
``veles/tests/accelerated_test.py:41-70``.

Run standalone on a TPU host: ``python bench_tpu.py``.  Prints one JSON
line per kernel plus a summary line; results are recorded in BASELINE.md.
"""

import json
import sys
import time

import numpy as np

WARMUP = 3
ITERS = 20
REPS = 8  # in-graph repetitions per dispatch (see timeit)


def drain(out):
    """Force full queue drain — block_until_ready alone is unreliable over
    the axon tunnel (see bench.py); a scalar read can't be faked."""
    import jax
    import jax.numpy as jnp
    leaf = jax.tree.leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(fn, *args, iters=ITERS):
    """Per-call wall time of ``fn`` — measured with REPS invocations
    chained INSIDE one jit.  The axon tunnel adds a ~4 ms fixed dispatch
    latency per executable launch (measured: a 256x256 scalar multiply
    costs 4 ms end-to-end), which would swamp any sub-10 ms kernel; the
    chain amortizes it.  A denormal-scaled feedback term creates a data
    dependence between repetitions that XLA cannot constant-fold away
    (0.0 * x WOULD be folded), so the repetitions really serialize."""
    import jax
    import jax.numpy as jnp

    # Thread the dependence through the SMALLEST argument so the chain
    # edge itself costs almost nothing (chaining through e.g. the 188 MB
    # gather dataset would add a full HBM pass per repetition).
    j = int(np.argmin([np.prod(a.shape, dtype=np.int64) if a.shape else 1
                       for a in args]))

    def chained(*args):
        out = fn(*args)
        for _ in range(REPS - 1):
            # The barrier forces each repetition's outputs to actually
            # materialize: without it XLA fuses an intermediate rep's
            # elementwise output straight into the scalar feedback sum and
            # never writes it — an unfair edge over the opaque pallas_call,
            # which always writes its outputs.
            out = jax.lax.optimization_barrier(out)
            leaf = jax.tree.leaves(out)[0]
            eps = jnp.sum(leaf.astype(jnp.float32)) * 1e-38
            args = list(args)
            args[j] = args[j] + eps.astype(args[j].dtype)
            out = fn(*args)
        return out

    cf = jax.jit(chained)
    for _ in range(WARMUP):
        out = cf(*args)
    drain(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = cf(*args)
    drain(out)
    return (time.perf_counter() - t0) / (iters * REPS), fn(*args)


def rel_err(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    return float(np.abs(a - b).max() / (np.abs(b).max() + 1e-12))


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    if "TPU" not in dev.device_kind.upper():
        print(json.dumps({"error": f"not a TPU: {dev.device_kind}"}))
        return 1

    from veles_tpu.ops import pallas_kernels as pk
    from veles_tpu.parallel.ring_attention import (blockwise_attention,
                                                   full_attention)

    results = []

    def record(name, pallas_ms, xla_ms, max_rel_err, **extra):
        entry = {"kernel": name, "pallas_ms": round(pallas_ms * 1e3, 3),
                 "xla_ms": round(xla_ms * 1e3, 3),
                 "speedup_vs_xla": round(xla_ms / pallas_ms, 2),
                 "max_rel_err": float(f"{max_rel_err:.2e}"), **extra}
        results.append(entry)
        print(json.dumps(entry))

    rng = np.random.default_rng(0)

    # -- flash attention fwd + bwd (reference = the library's f32-accum
    # full attention, same one the test suite uses) ------------------------
    for T, dtype_name in ((2048, "float32"), (4096, "bfloat16")):
        B, H, D = 2, 8, 64
        dtype = jnp.dtype(dtype_name)
        q, k, v = (jnp.asarray(
            rng.standard_normal((B, T, H, D)), dtype) for _ in range(3))

        flash = jax.jit(lambda q, k, v: pk.flash_attention(
            q, k, v, True, None, interpret=False))
        xla = jax.jit(lambda q, k, v: full_attention(q, k, v, causal=True))
        t_p, out_p = timeit(flash, q, k, v)
        t_x, out_x = timeit(xla, q, k, v)
        record(f"flash_attention_fwd_T{T}_{dtype_name}", t_p, t_x,
               rel_err(out_p.astype(jnp.float32), out_x.astype(jnp.float32)))

        # backward: Pallas dq/dkv kernels vs jnp blockwise recompute
        # (the round-1 path) vs full XLA attention grad
        flash_g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(pk.flash_attention(
                q, k, v, True, None, interpret=False)
                .astype(jnp.float32)), argnums=(0, 1, 2)))
        block_g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(blockwise_attention(
                q, k, v, block_size=128, causal=True, use_flash=False)
                .astype(jnp.float32)), argnums=(0, 1, 2)))
        xla_g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(full_attention(q, k, v, causal=True)
                                    .astype(jnp.float32)),
            argnums=(0, 1, 2)))
        t_pg, g_p = timeit(flash_g, q, k, v, iters=10)
        t_bg, g_b = timeit(block_g, q, k, v, iters=10)
        t_xg, g_x = timeit(xla_g, q, k, v, iters=10)
        err = max(rel_err(a.astype(jnp.float32), b.astype(jnp.float32))
                  for a, b in zip(g_p, g_x))
        record(f"flash_attention_bwd_T{T}_{dtype_name}", t_pg, t_xg, err,
               jnp_recompute_ms=round(t_bg * 1e3, 3),
               speedup_vs_recompute=round(t_bg / t_pg, 2))

    # -- sliding-window + GQA flash variants (compiled-lowering proof +
    # the O(T*window) block-skip payoff) ----------------------------------
    T, W = 8192, 1024
    B, H, Hk, D = 1, 8, 2, 64
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
    kf, vf = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
              for _ in range(2))
    full = jax.jit(lambda q, k, v: pk.flash_attention(
        q, k, v, True, None, interpret=False))
    swa = jax.jit(lambda q, k, v: pk.flash_attention(
        q, k, v, True, None, interpret=False, window=W))
    t_full, _ = timeit(full, q, kf, vf, iters=10)
    t_swa, out_swa = timeit(swa, q, kf, vf, iters=10)
    # numerics: dense windowed reference on the last Sq query rows (their
    # window only reaches back W keys, so a K slice of Sq+W suffices)
    Sq = 256
    qs = q[:, -Sq:].astype(jnp.float32)
    ks = kf[:, -(Sq + W):].astype(jnp.float32)
    vs = vf[:, -(Sq + W):].astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qs, ks) * (D ** -0.5)
    qp = (T - Sq + jnp.arange(Sq))[:, None]
    kp = (T - Sq - W + jnp.arange(Sq + W))[None, :]
    msk = (kp <= qp) & (kp > qp - W)
    ref_swa = jnp.einsum(
        "bhqk,bkhd->bqhd",
        jax.nn.softmax(jnp.where(msk[None, None], s, -jnp.inf), -1), vs)
    record(f"flash_swa_T{T}_W{W}_bf16", t_swa, t_full,
           rel_err(out_swa[:, -Sq:].astype(jnp.float32), ref_swa),
           note="xla_ms column = full-attention kernel (the speedup is "
                "the window block-skip); err vs dense windowed ref on "
                "the last 256 rows")

    kg, vg = (jnp.asarray(rng.standard_normal((B, T, Hk, D)), jnp.bfloat16)
              for _ in range(2))
    gqa = jax.jit(lambda q, k, v: pk.flash_attention(
        q, k, v, True, None, interpret=False))
    t_gqa, out_gqa = timeit(gqa, q, kg, vg, iters=10)
    ref_gqa = jax.jit(lambda q, k, v: pk.flash_attention(
        q, jnp.repeat(k, H // Hk, 2), jnp.repeat(v, H // Hk, 2),
        True, None, interpret=False))
    t_rep, out_rep = timeit(ref_gqa, q, kg, vg, iters=10)
    record(f"flash_gqa_T{T}_H{H}kv{Hk}_bf16", t_gqa, t_rep,
           rel_err(out_gqa.astype(jnp.float32),
                   out_rep.astype(jnp.float32)),
           note="xla_ms column = same kernel on materialized repeat")
    # gqa backward: REAL timing row (round-3 verdict #6 — it was a
    # lowering gate only) against the materialized-repeat formulation.
    # value_and_grad, not grad: returning the primal keeps the forward
    # alive under DCE, so the row prices the full training cost.
    G = H // Hk

    def vag(f):
        def timed(q, k, v):
            val, gs = jax.value_and_grad(
                lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2))(q, k, v)
            return (val,) + gs
        return jax.jit(timed)

    g_gqa = vag(lambda q, k, v: pk.flash_attention(
        q, k, v, True, None, interpret=False))
    g_rep = vag(lambda q, k, v: pk.flash_attention(
        q, jnp.repeat(k, G, 2), jnp.repeat(v, G, 2),
        True, None, interpret=False))
    t_gb, out_gb = timeit(g_gqa, q, kg, vg, iters=5)
    t_rb, out_rb = timeit(g_rep, q, kg, vg, iters=5)
    assert all(bool(jnp.all(jnp.isfinite(x.astype(jnp.float32))))
               for x in out_gb[1:])
    # the repeat path differentiates THROUGH jnp.repeat, so AD already
    # group-sums its dk/dv back to kv-head shape — compare directly
    _, dq_g, dk_g, dv_g = out_gb
    _, dq_r, dk_r, dv_r = out_rb
    err_gb = max(
        rel_err(dq_g.astype(jnp.float32), dq_r.astype(jnp.float32)),
        rel_err(dk_g.astype(jnp.float32), dk_r.astype(jnp.float32)),
        rel_err(dv_g.astype(jnp.float32), dv_r.astype(jnp.float32)))
    record(f"flash_gqa_bwd_T{T}_bf16", t_gb, t_rb, err_gb,
           note="xla_ms column = same kernel fwd+bwd on materialized "
                "repeat (4x K/V HBM); timed via value_and_grad")

    # -- fused dropout ----------------------------------------------------
    x = jnp.asarray(rng.standard_normal((4096, 4096)), jnp.float32)
    seed = jnp.uint32(123)  # scalar arg = cheap chain edge for timeit
    fd = jax.jit(lambda x, s: pk.fused_dropout(x, s, 0.3, 256, False))
    key = jax.random.key(0)

    def xla_dropout(x, s):
        keep = jax.random.bernoulli(jax.random.fold_in(key, s), 0.7,
                                    x.shape)
        return jnp.where(keep, x / 0.7, 0.0)

    xd = jax.jit(xla_dropout)
    t_p, out_p = timeit(fd, x, seed)
    t_x, _ = timeit(xd, x, seed)
    kept = float(jnp.mean(out_p != 0))
    record("fused_dropout_4096x4096", t_p, t_x,
           abs(kept - 0.7) / 0.7, kept_fraction=round(kept, 4))

    # -- mean/disp normalize ---------------------------------------------
    xb = jnp.asarray(rng.integers(0, 256, (512, 224 * 224 * 3)), jnp.uint8)
    mean = jnp.asarray(rng.uniform(100, 150, 224 * 224 * 3), jnp.float32)
    rdisp = jnp.asarray(rng.uniform(0.01, 0.02, 224 * 224 * 3), jnp.float32)
    # mean/rdisp as real args: timeit threads its chain edge through the
    # smallest arg, so the 77 MB image block is not rewritten per rep
    md = jax.jit(lambda x, m, r: pk.mean_disp_normalize(x, m, r,
                                                        interpret=False))
    mx = jax.jit(lambda x, m, r: (x.astype(jnp.float32) - m[None]) *
                 r[None])
    t_p, out_p = timeit(md, xb, mean, rdisp)
    t_x, out_x = timeit(mx, xb, mean, rdisp)
    record("mean_disp_normalize_512x150k", t_p, t_x, rel_err(out_p, out_x))

    # -- fullbatch DMA gather --------------------------------------------
    # Times the loader's FULL device path — gather from the packed layout
    # PLUS the unpack reshape back to row geometry — vs jnp.take, so the
    # row measures exactly what FullBatchLoader's default switch governs.
    data = jnp.asarray(rng.standard_normal((60000, 784)), jnp.float32)
    packed, f, sshape = pk.pack_rows(data)
    idx = jnp.asarray(rng.permutation(60000)[:512], jnp.int32)
    ga = jax.jit(lambda p, i: pk.unpack_rows(
        pk.gather_rows_packed(p, i, interpret=False), f, sshape))
    gx = jax.jit(lambda d, i: jnp.take(d, i, axis=0))
    t_p, out_p = timeit(ga, packed, idx)
    t_x, out_x = timeit(gx, data, idx)
    record("gather_rows_packed_512_of_60k", t_p, t_x,
           rel_err(out_p, out_x),
           note="pallas_ms includes the unpack reshape (loader path)")

    worst = max(r["max_rel_err"] for r in results)
    summary = {
        "metric": "pallas_tpu_suite",
        "kernels": len(results),
        "all_compiled": True,
        "worst_rel_err": worst,
        "device": str(dev),
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
