#!/usr/bin/env python
"""Benchmark harness: AlexNet training throughput, samples/sec/chip.

Metric per BASELINE.json: samples/sec/chip on ImageNet-AlexNet (the Znicz
ImagenetWorkflow analog), vs the single-V100 CUDA-backend bar. The reference
publishes no numbers (BASELINE.md), so the bar is the documented estimate
V100_ALEXNET_SAMPLES_PER_SEC below; measured values land in BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}
"""

import json
import sys
import time

import numpy as np

# Published AlexNet end-to-end training throughput on one V100 (fp32 cuDNN,
# batch 128-256) clusters around 1.5-3k img/s; 2000 is the point estimate
# recorded in BASELINE.md for vs_baseline, and the bracket below is
# reported alongside so the claim doesn't rest on one self-declared number
# (round-1 verdict weak #4).
V100_ALEXNET_SAMPLES_PER_SEC = 2000.0
V100_BRACKET = (1500.0, 3000.0)

BATCH = 512
WARMUP = 3
ITERS = 30


def main():
    # During axon outages jax.devices() HANGS (it does not raise), which
    # would eat the driver's whole bench budget.  Probe the device in a
    # killable subprocess with a bounded retry, and only then touch jax
    # in this process.
    import subprocess
    import threading
    err = None
    for attempt in range(3):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices()[0]; "
                 "import jax.numpy as jnp; "
                 "x = jnp.ones((128, 128)); float((x @ x).sum()); "
                 "print(d.device_kind)"],
                capture_output=True, text=True, timeout=150)
            if r.returncode == 0:
                err = None
                break
            err = (r.stderr or r.stdout).strip()[-400:]
        except subprocess.TimeoutExpired:
            err = f"device probe hung >150s (attempt {attempt + 1})"
        if attempt < 2:
            time.sleep(20)
    if err is not None:
        # Contract JSON even when the accelerator tunnel is down
        # (round-2: axon outages made device calls hang) so the driver
        # records a diagnosable result instead of a timeout.
        print(json.dumps({
            "metric": "alexnet_train_samples_per_sec_per_chip",
            "value": None, "unit": "samples/sec/chip", "vs_baseline": None,
            "train_step_recompiles": None, "compile_wall_s": None,
            "anomaly_steps_skipped": None, "snapshot_walkbacks": None,
            "error": f"device unavailable: {err}",
        }))
        return 1

    # The tunnel can still drop between the probe and first use; a
    # watchdog bounds THIS process too (jax.devices() hangs, not raises).
    import os
    import signal

    # Metrics measured so far; _die prints them so a mid-bench hang
    # (e.g. during the optional e2e blocks) still reports the staged
    # number instead of discarding it.
    # train_step_recompiles / compile_wall_s track the compile-time side
    # of the perf trajectory (the recompile-free lifecycle of
    # docs/compile_cache.md) and are reported even when a later e2e
    # block hangs, like the throughput numbers.
    partial = {"metric": "alexnet_train_samples_per_sec_per_chip",
               "value": None, "unit": "samples/sec/chip",
               "vs_baseline": None,
               "train_step_recompiles": None, "compile_wall_s": None,
               # fault-tolerance gauges (docs/robustness.md): non-zero
               # means the sentinel skipped steps / restore walked past
               # corruption during the measurement — numbers from such a
               # run need an asterisk
               "anomaly_steps_skipped": 0, "snapshot_walkbacks": 0}

    def _die():
        out = dict(partial)
        out["error"] = "device hang after successful probe (watchdog)"
        print(json.dumps(out), flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    watchdog = threading.Timer(180.0, _die)
    watchdog.daemon = True
    watchdog.start()

    import jax
    import jax.numpy as jnp
    import veles_tpu as vt
    from veles_tpu.models import alexnet_workflow

    dev = jax.devices()[0]
    watchdog.cancel()
    # re-arm across the first compile + warmup drain (the other window
    # where a tunnel drop turns into a silent hang); generous bound —
    # first AlexNet compile is ~40s on a healthy tunnel
    watchdog = threading.Timer(600.0, _die)
    watchdog.daemon = True
    watchdog.start()
    # Single-device benchmark: the workload runs unsharded on device 0, so
    # per-chip throughput divides by 1 regardless of host chip count.
    n_chips = 1

    sw = alexnet_workflow(minibatch_size=BATCH)
    wf = sw.workflow
    wf.build({"@input": vt.Spec((BATCH, 227, 227, 3), jnp.float32),
              "@labels": vt.Spec((BATCH,), jnp.int32),
              "@mask": vt.Spec((BATCH,), jnp.float32)})
    wstate = wf.init_state(jax.random.key(0), sw.optimizer)
    # AOT-compile through the StepCache so the bench reports compile wall
    # time and recompile count alongside throughput (compile-time wins
    # register in the trajectory even when the device probe is flaky).
    from veles_tpu.runtime.step_cache import StepCache
    batch_spec = {
        "@input": jax.ShapeDtypeStruct((BATCH, 227, 227, 3), jnp.float32),
        "@labels": jax.ShapeDtypeStruct((BATCH,), jnp.int32),
        "@mask": jax.ShapeDtypeStruct((BATCH,), jnp.float32)}
    cache = StepCache()
    step, _, _ = cache.get_step(
        "train",
        cache.trainer_key(wf, sw.optimizer, wstate, batch_spec),
        lambda: (wf.make_train_step(sw.optimizer), None, None),
        (wf.state_struct(wstate), batch_spec))
    partial["compile_wall_s"] = round(cache.compile_wall_s, 3)
    partial["train_step_recompiles"] = cache.recompiles
    recompile_cnt = [cache]  # per-path caches; summed before printing

    # Pre-staged on-device batches (the fullbatch-loader pattern: data
    # resident in HBM, only indices travel — veles/loader/fullbatch.py:79).
    rng = np.random.default_rng(0)
    batches = []
    for i in range(2):
        batches.append({
            "@input": jax.device_put(rng.standard_normal(
                (BATCH, 227, 227, 3)).astype(np.float32), dev),
            "@labels": jax.device_put(
                (np.arange(BATCH) % 1000).astype(np.int32), dev),
            "@mask": jax.device_put(np.ones(BATCH, np.float32), dev),
        })

    for i in range(WARMUP):
        wstate, mets = step(wstate, batches[i % 2])
    float(mets["loss"])  # force full queue drain: block_until_ready alone
    # is unreliable over the axon tunnel (returns early on buffers not yet
    # scheduled); a scalar read can't be faked.
    watchdog.cancel()

    t0 = time.perf_counter()
    for i in range(ITERS):
        wstate, mets = step(wstate, batches[i % 2])
    final_loss = float(mets["loss"])  # chains on all prior steps
    dt = time.perf_counter() - t0

    sps = BATCH * ITERS / dt
    sps_per_chip = sps / max(n_chips, 1)
    partial.update(
        value=round(sps_per_chip, 1),
        vs_baseline=round(sps_per_chip / V100_ALEXNET_SAMPLES_PER_SEC, 3),
        step_ms=round(1000 * dt / ITERS, 2))

    # -- end-to-end input-pipeline variants (round-1 verdict weak #3: the
    # staged number excludes the input pipeline). Both variants share one
    # measurement recipe so their comparison is apples-to-apples; each
    # block gets its OWN watchdog budget (a fresh tunnel hang window —
    # round-2 outage postmortem), and results land in `partial` as they
    # are measured so a later hang cannot discard them.
    trainers = []       # e2e trainers, for the snapshot_walkbacks gauge
    anomalies = [0.0]   # sentinel skips observed across measured epochs

    def timed_e2e(build, label, check=None, budget_s=900.0):
        w = threading.Timer(budget_s, _die)
        w.daemon = True
        w.start()
        try:
            sw = build()
            trainer = sw.make_trainer(sw.loader)
            trainer.initialize(seed=0)
            recompile_cnt.append(trainer.step_cache)
            trainers.append(trainer)
            if check is not None:
                check(sw)
            # bench drives _run_epoch_train directly (no Trainer.run()),
            # so sentinel skips must be read off the returned epoch
            # metrics — the run()-only counters would always report 0
            anomalies[0] += trainer._run_epoch_train(0).get(
                "anomaly_steps", 0.0)  # compile + warm
            t0 = time.perf_counter()
            tot = 0.0
            for ep in (1, 2):
                mets = trainer._run_epoch_train(ep)
                tot += mets.get("n_samples", 0.0)
                anomalies[0] += mets.get("anomaly_steps", 0.0)
            return tot / (time.perf_counter() - t0)
        except Exception as e:  # keep earlier numbers even if this breaks
            print(f"# {label} e2e measurement failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return None
        finally:
            w.cancel()

    # host path: uint8 host store -> random crop/mirror on host ->
    # device-side mean/disp normalize via Trainer prefetch
    from veles_tpu.models.alexnet import (alexnet_e2e_device_workflow,
                                          alexnet_e2e_workflow)
    e2e_sps = timed_e2e(
        lambda: alexnet_e2e_workflow(minibatch_size=BATCH, n_train=8192),
        "host-path")
    if e2e_sps:
        partial["e2e_samples_per_sec"] = round(e2e_sps, 1)

    # TPU-native formulation: device-resident uint8 store, on-device
    # crop/mirror/normalize (FullBatchAugmentedLoader) — only indices +
    # augmentation descriptors cross the host->device boundary

    def _must_be_on_device(sw):
        if not sw.loader.on_device:
            # OOM fallback silently degrades to the HOST gather — that
            # would time the wrong pipeline under this row's name.
            raise RuntimeError("store fell back to host gather (OOM?)")

    e2e_dev_sps = timed_e2e(
        lambda: alexnet_e2e_device_workflow(minibatch_size=BATCH,
                                            n_train=8192),
        "device-aug", check=_must_be_on_device)
    if e2e_dev_sps:
        partial["e2e_device_aug_samples_per_sec"] = round(e2e_dev_sps, 1)

    # compile-side trajectory: total compile wall across all measured
    # paths and any compile beyond one-per-program (must stay 0 — the
    # recompile-free lifecycle contract, tests/test_step_cache.py)
    partial["train_step_recompiles"] = sum(
        c.recompiles for c in recompile_cnt)
    partial["compile_wall_s"] = round(
        sum(c.compile_wall_s for c in recompile_cnt), 3)
    partial["anomaly_steps_skipped"] = int(anomalies[0])
    partial["snapshot_walkbacks"] = sum(
        t.snapshot_walkbacks for t in trainers)

    # -- host->device link bandwidth (context for the host-path e2e row:
    # over the axon tunnel this is the binding constraint, not the
    # framework; on a real v5e host PCIe gives ~GB/s x10 more).
    h2d_mb_s = None
    watchdog = threading.Timer(300.0, _die)
    watchdog.daemon = True
    watchdog.start()
    try:
        import jax as _jax
        buf = np.zeros((64, 1024, 1024), np.uint8)  # 64 MB
        _jax.device_put(buf[:1], dev).block_until_ready()
        t0 = time.perf_counter()
        _jax.device_put(buf, dev).block_until_ready()
        h2d_mb_s = buf.nbytes / (time.perf_counter() - t0) / 1e6
    except Exception:
        pass
    watchdog.cancel()

    # One source of truth: everything already accumulated in `partial`
    # (what a watchdog _die would have printed) + final-only context.
    partial.update({
        "vs_baseline_range": [
            round(sps_per_chip / V100_BRACKET[1], 3),
            round(sps_per_chip / V100_BRACKET[0], 3)],
        "batch": BATCH,
        "iters": ITERS,
        "n_chips": n_chips,
        "device": str(dev),
        "final_loss": round(final_loss, 4),
        "e2e_over_staged": round(e2e_sps / sps_per_chip, 3)
        if e2e_sps else None,
        "h2d_link_mb_per_sec": round(h2d_mb_s, 1) if h2d_mb_s else None,
    })
    print(json.dumps(partial))


if __name__ == "__main__":
    sys.exit(main())
