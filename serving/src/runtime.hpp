// veles_tpu native serving runtime: tensors, thread pool, arena planner.
//
// Counterpart of the reference's libVeles C++11 inference engine
// (reference: libVeles/inc/veles/workflow.h:72 Workflow,
// inc/veles/engine.h:43 ThreadPoolEngine, src/memory_optimizer.h:43
// MemoryOptimizer sliding-block arena packing). The TPU training framework
// exports packages (veles_tpu/export/package.py) that this runtime executes
// on CPU for embedded/serving parity.
#pragma once

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <queue>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace veles {

// ---------------------------------------------------------------------------
struct Shape {
  std::vector<int64_t> dims;
  int64_t size() const {
    int64_t n = 1;
    for (auto d : dims) n *= d;
    return n;
  }
  int64_t operator[](size_t i) const { return dims[i]; }
  size_t rank() const { return dims.size(); }
};

// A tensor view into the arena (or owning, for weights).
struct Tensor {
  Shape shape;
  float* data = nullptr;           // view (arena)
  std::vector<float> storage;      // owning (weights / IO)

  void own(const Shape& s) {
    shape = s;
    storage.resize(s.size());
    data = storage.data();
  }
  int64_t size() const { return shape.size(); }
};

// ---------------------------------------------------------------------------
// Persistent thread pool with parallel_for (the reference scheduled whole
// units on its pool, libVeles/src/engine.h:45; here units run in topo order
// and the parallelism is *inside* each op — better cache behavior for
// inference). Workers are spawned once and fed range tasks through a
// condition variable — no per-op thread create/destroy.
class ThreadPool {
 public:
  explicit ThreadPool(int n_threads = 0)
      : n_(n_threads > 0 ? n_threads
                         : static_cast<int>(
                               std::thread::hardware_concurrency())) {
    if (n_ < 1) n_ = 1;
    for (int t = 1; t < n_; t++)  // worker 0 is the calling thread
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~ThreadPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& th : workers_) th.join();
  }

  int size() const { return n_; }

  // Run fn(begin, end) over [0, total) split across the pool; the calling
  // thread executes its own share, workers take the rest.
  //
  // Exception safety: a throw from any chunk (worker or caller) must not
  // escape a worker's thread function (std::terminate) nor leave pending_
  // undrained (deadlock + workers dereferencing a destroyed closure). Every
  // chunk runs under try/catch; the first exception is captured in eptr_
  // and rethrown here after ALL chunks have joined.
  void ParallelFor(int64_t total,
                   const std::function<void(int64_t, int64_t)>& fn) {
    if (total <= 0) return;
    int k = static_cast<int>(
        std::min<int64_t>(n_, std::max<int64_t>(1, total)));
    if (k == 1 || workers_.empty()) {
      fn(0, total);
      return;
    }
    int64_t chunk = (total + k - 1) / k;
    {
      std::unique_lock<std::mutex> lk(mu_);
      task_ = &fn;
      task_total_ = total;
      task_chunk_ = chunk;
      next_part_ = 1;  // part 0 belongs to the caller
      n_parts_ = k;
      pending_ = k - 1;
      generation_++;
      eptr_ = nullptr;
    }
    cv_.notify_all();
    try {
      fn(0, std::min<int64_t>(total, chunk));  // caller's share
    } catch (...) {
      std::unique_lock<std::mutex> lk(mu_);
      if (!eptr_) eptr_ = std::current_exception();
    }
    std::exception_ptr eptr;
    {
      std::unique_lock<std::mutex> lk(mu_);
      done_cv_.wait(lk, [this] { return pending_ == 0; });
      task_ = nullptr;
      eptr = eptr_;
      eptr_ = nullptr;
    }
    if (eptr) std::rethrow_exception(eptr);
  }

 private:
  void WorkerLoop() {
    uint64_t seen = 0;
    while (true) {
      const std::function<void(int64_t, int64_t)>* fn = nullptr;
      int64_t b = 0, e = 0;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this, &seen] {
          return stop_ || (task_ != nullptr && generation_ != seen &&
                           next_part_ < n_parts_);
        });
        if (stop_) return;
        int part = next_part_++;
        if (next_part_ >= n_parts_) seen = generation_;
        fn = task_;
        b = part * task_chunk_;
        e = std::min(task_total_, b + task_chunk_);
      }
      if (b < e) {
        try {
          (*fn)(b, e);
        } catch (...) {
          std::unique_lock<std::mutex> lk(mu_);
          if (!eptr_) eptr_ = std::current_exception();
        }
      }
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (--pending_ == 0) done_cv_.notify_all();
      }
    }
  }

  int n_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int64_t, int64_t)>* task_ = nullptr;
  int64_t task_total_ = 0, task_chunk_ = 0;
  int next_part_ = 0, n_parts_ = 0, pending_ = 0;
  uint64_t generation_ = 0;
  std::exception_ptr eptr_;
  bool stop_ = false;
};

// ---------------------------------------------------------------------------
// Arena planner: assign each intermediate buffer an offset in one block,
// reusing memory of dead buffers (parity with MemoryOptimizer,
// libVeles/src/memory_optimizer.h:43-55 — greedy best-offset packing of
// [def, last_use) lifetime intervals).
struct ArenaItem {
  int64_t size = 0;   // floats
  int def = 0;        // producing step
  int last_use = 0;   // last consuming step
  int64_t offset = -1;
};

inline int64_t PlanArena(std::vector<ArenaItem>* items) {
  std::vector<int> order(items->size());
  for (size_t i = 0; i < order.size(); i++) order[i] = static_cast<int>(i);
  std::sort(order.begin(), order.end(), [&](int a, int b) {
    return (*items)[a].size > (*items)[b].size;
  });
  int64_t total = 0;
  for (int idx : order) {
    ArenaItem& it = (*items)[idx];
    // collect intervals of temporally-overlapping, already-placed buffers
    std::vector<std::pair<int64_t, int64_t>> busy;
    for (const auto& other : *items) {
      if (other.offset < 0 || &other == &it) continue;
      bool overlap = !(other.last_use < it.def || it.last_use < other.def);
      if (overlap) busy.emplace_back(other.offset,
                                     other.offset + other.size);
    }
    std::sort(busy.begin(), busy.end());
    int64_t pos = 0;
    for (const auto& b : busy) {
      if (pos + it.size <= b.first) break;
      pos = std::max(pos, b.second);
    }
    it.offset = pos;
    total = std::max(total, pos + it.size);
  }
  return total;
}

// ---------------------------------------------------------------------------
// Activations (mirror veles_tpu/ops/activations.py).
// ApplyActivationRange: the shared scalar ladder over x[b, e) with feature
// indices i % last_dim (sincos alternates over the feature index, not the
// flat index). Safe inside a worker lambda (no pool dispatch) — FFNUnit
// calls it per row from within its own ParallelFor.
inline void ApplyActivationRange(const std::string& act, float* x,
                                 int64_t b, int64_t e, int64_t last_dim) {
  if (act == "relu") {
    for (int64_t i = b; i < e; i++) x[i] = x[i] > 0 ? x[i] : 0;
  } else if (act == "tanh") {
    for (int64_t i = b; i < e; i++)
      x[i] = 1.7159f * std::tanh(0.6666f * x[i]);
  } else if (act == "raw_tanh") {
    for (int64_t i = b; i < e; i++) x[i] = std::tanh(x[i]);
  } else if (act == "sigmoid") {
    for (int64_t i = b; i < e; i++) x[i] = 1.f / (1.f + std::exp(-x[i]));
  } else if (act == "sincos") {
    for (int64_t i = b; i < e; i++)
      x[i] = ((i % last_dim) % 2 == 0) ? std::sin(x[i]) : std::cos(x[i]);
  } else {
    throw std::runtime_error("unknown activation " + act);
  }
}

inline void ApplyActivation(const std::string& act, float* x, int64_t n,
                            int64_t last_dim, ThreadPool* pool) {
  if (act == "linear" || act.empty()) return;
  pool->ParallelFor(n, [&](int64_t b, int64_t e) {
    ApplyActivationRange(act, x, b, e, last_dim);
  });
}

}  // namespace veles
